"""Checker 1 — lock acquisition order (DK101) + hierarchy doc (DK190).

Extracts every lock *definition* (``self._lock = threading.Lock()`` /
``RLock`` / ``Condition``, module-level or local) and every *acquisition
site* (``with <lock>:``, ``<lock>.acquire()``), resolves cross-object
receivers through the reviewed tables in ``config.py``, folds nested
closures into their enclosing function, and propagates
"locks-possibly-acquired" through the static call graph to a fixpoint.
The result is the inter-lock acquisition graph: an edge ``A -> B`` means
"somewhere, B is (possibly transitively) acquired while A is held".

Findings:

  * **DK101** — a cycle in the acquisition graph: two lock classes are
    taken in both orders somewhere, i.e. a potential deadlock.
  * **DK190** — the committed ``docs/LOCK_HIERARCHY.md`` no longer
    matches the graph (regenerate with ``--write-docs``).

Self-edges (``A -> A``) are dropped: our lock identities are per-class,
and the only same-class nesting in this codebase is across *instances*
(hot-reload's hand-over-hand over distinct workloads), which is ordered
by the swap lock, not by class identity.

The graph (``build_graph``) is also the contract the ``DUKE_LOCKCHECK=1``
runtime sanitizer (utils/lockcheck.py) asserts real executions against.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .config import (
    CALL_RETURNS_LOCK,
    CALLBACK_TARGETS,
    MANUAL_EDGES,
    RECEIVER_TYPES,
)
from .core import Finding, Module, receiver_name

_LOCK_CTORS = {"Lock", "RLock", "Condition"}

# Names carried by builtin collections (dict/list/deque/set) or too
# generic to trust: the unique-method fallback must never resolve these —
# `self._records.pop(...)` is a dict pop, not LazyRecordMap.pop, even
# when exactly one class happens to define the name.  Table-resolved
# receivers (config.RECEIVER_TYPES) are unaffected.
_GENERIC_METHODS = {
    "get", "pop", "put", "add", "append", "appendleft", "extend",
    "extendleft", "insert", "remove", "discard", "clear", "update",
    "setdefault", "items", "keys", "values", "popleft", "popitem",
    "sort", "reverse", "count", "index", "copy", "move_to_end", "set",
    "inc", "dec", "observe", "wait", "notify", "notify_all", "join",
    "start", "write", "read", "send", "recv", "flush",
}


class LockDef:
    __slots__ = ("name", "rel", "line", "kind")

    def __init__(self, name: str, rel: str, line: int, kind: str):
        self.name = name
        self.rel = rel
        self.line = line
        self.kind = kind


class LockGraph:
    def __init__(self):
        self.locks: Dict[str, LockDef] = {}
        # (A, B) -> (rel, line) witness: B acquired while A held
        self.edges: Dict[Tuple[str, str], Tuple[str, int]] = {}

    def add_edge(self, a: str, b: str, rel: str, line: int) -> None:
        if a == b:
            return  # per-class identity; see module docstring
        self.edges.setdefault((a, b), (rel, line))

    def successors(self) -> Dict[str, Set[str]]:
        out: Dict[str, Set[str]] = {}
        for (a, b) in self.edges:
            out.setdefault(a, set()).add(b)
        return out

    def reachable(self) -> Dict[str, Set[str]]:
        """Transitive closure: ``reachable[a]`` = locks acquirable with
        ``a`` held (used by the runtime sanitizer's inversion check)."""
        succ = self.successors()
        out: Dict[str, Set[str]] = {}

        def visit(node: str) -> Set[str]:
            if node in out:
                return out[node]
            out[node] = set()  # cycle guard; cycles are findings anyway
            acc: Set[str] = set()
            for nxt in succ.get(node, ()):
                acc.add(nxt)
                acc |= visit(nxt)
            out[node] = acc
            return acc

        for node in succ:
            visit(node)
        return out

    def cycles(self) -> List[List[str]]:
        """Elementary cycles via SCC decomposition (one finding per SCC —
        the fix is re-ordering, not enumerating every loop)."""
        succ = self.successors()
        index: Dict[str, int] = {}
        low: Dict[str, int] = {}
        onstack: Set[str] = set()
        stack: List[str] = []
        sccs: List[List[str]] = []
        counter = [0]

        def strongconnect(v: str) -> None:
            index[v] = low[v] = counter[0]
            counter[0] += 1
            stack.append(v)
            onstack.add(v)
            for w in sorted(succ.get(v, ())):
                if w not in index:
                    strongconnect(w)
                    low[v] = min(low[v], low[w])
                elif w in onstack:
                    low[v] = min(low[v], index[w])
            if low[v] == index[v]:
                scc = []
                while True:
                    w = stack.pop()
                    onstack.discard(w)
                    scc.append(w)
                    if w == v:
                        break
                if len(scc) > 1:
                    sccs.append(sorted(scc))
        for v in sorted(succ):
            if v not in index:
                strongconnect(v)
        return sccs


class _FuncInfo:
    """Per-function facts: direct acquisitions + calls, each with the
    locks lexically held at that point."""

    __slots__ = ("qual", "rel", "direct", "calls")

    def __init__(self, qual: str, rel: str):
        self.qual = qual
        self.rel = rel
        # (lockname, held-tuple, line)
        self.direct: List[Tuple[str, Tuple[str, ...], int]] = []
        # (callee-qual, held-tuple, line)
        self.calls: List[Tuple[str, Tuple[str, ...], int]] = []


def _terminates(stmts: Sequence[ast.stmt]) -> bool:
    """True when a statement list cannot fall through (the
    `if not lock.acquire(False): return` idiom's failure branch)."""
    return bool(stmts) and isinstance(
        stmts[-1], (ast.Return, ast.Raise, ast.Continue, ast.Break))


class _Analyzer:
    def __init__(self, modules: Sequence[Module]):
        self.modules = modules
        self.graph = LockGraph()
        # (class, attr) -> lock name
        self.class_locks: Dict[Tuple[str, str], str] = {}
        # attr -> lock names (for unique-attr fallback)
        self.attr_index: Dict[str, Set[str]] = {}
        # class -> base class names (single package-wide namespace)
        self.bases: Dict[str, List[str]] = {}
        # (class, method) -> qual;  (modkey, func) -> qual
        self.methods: Dict[Tuple[str, str], str] = {}
        self.functions: Dict[Tuple[str, str], str] = {}
        self.method_index: Dict[str, Set[str]] = {}
        self.funcs: Dict[str, _FuncInfo] = {}

    # -- pass 1: definitions -------------------------------------------------

    @staticmethod
    def _modkey(mod: Module) -> str:
        # package-qualified (links/base.py -> "links.base") so same-named
        # modules in different subpackages never share an identity
        # namespace; the root package component is dropped for brevity
        parts = mod.rel.split("/")
        parts[-1] = parts[-1].removesuffix(".py")
        if parts[-1] == "__init__" and len(parts) >= 2:
            # package init: the package directory path itself
            # (native/__init__.py -> "native", not an ambiguous __init__)
            parts = parts[:-1]
        if len(parts) > 1:
            parts = parts[1:]
        return ".".join(parts)

    @staticmethod
    def _lock_ctor(value: ast.AST) -> Optional[str]:
        """'Lock'/'RLock'/'Condition' when ``value`` constructs one
        (including inside a conditional expression)."""
        for node in ast.walk(value):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "threading"
                    and node.func.attr in _LOCK_CTORS):
                return node.func.attr
        return None

    def collect_defs(self) -> None:
        for mod in self.modules:
            modkey = self._modkey(mod)
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.ClassDef):
                    self.bases[node.name] = [
                        b.id for b in node.bases if isinstance(b, ast.Name)
                    ]
                    for item in node.body:
                        if isinstance(item, ast.FunctionDef):
                            self.methods[(node.name, item.name)] = (
                                f"{node.name}.{item.name}"
                            )
                            self.method_index.setdefault(
                                item.name, set()
                            ).add(f"{node.name}.{item.name}")
                            self._collect_assigns(
                                mod, modkey, node.name, item
                            )
                elif isinstance(node, ast.FunctionDef):
                    pass  # module functions registered below
            for node in mod.tree.body:
                if isinstance(node, ast.FunctionDef):
                    self.functions[(modkey, node.name)] = (
                        f"{modkey}.{node.name}"
                    )
                elif isinstance(node, ast.Assign):
                    kind = self._lock_ctor(node.value)
                    if kind:
                        for tgt in node.targets:
                            if isinstance(tgt, ast.Name):
                                name = f"{modkey}.{tgt.id}"
                                self._define(name, mod.rel, node.lineno,
                                             kind, tgt.id)

    def _collect_assigns(self, mod: Module, modkey: str, cls: str,
                         func: ast.FunctionDef) -> None:
        for node in ast.walk(func):
            if not isinstance(node, ast.Assign):
                continue
            kind = self._lock_ctor(node.value)
            if not kind:
                continue
            for tgt in node.targets:
                if (isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"):
                    name = f"{cls}.{tgt.attr}"
                    self.class_locks.setdefault((cls, tgt.attr), name)
                    self._define(name, mod.rel, node.lineno, kind,
                                 tgt.attr)
                elif isinstance(tgt, ast.Name):
                    # function-local lock (engine/finalize.py's resolver
                    # serializer): scoped by the enclosing function
                    name = f"{modkey}.{func.name}.{tgt.id}"
                    self._define(name, mod.rel, node.lineno, kind, tgt.id)

    def _define(self, name: str, rel: str, line: int, kind: str,
                attr: str) -> None:
        if name not in self.graph.locks:
            self.graph.locks[name] = LockDef(name, rel, line, kind)
        self.attr_index.setdefault(attr, set()).add(name)

    # -- lock resolution -----------------------------------------------------

    def _class_attr_lock(self, cls: Optional[str],
                         attr: str) -> Optional[str]:
        seen = set()
        while cls and cls not in seen:
            seen.add(cls)
            hit = self.class_locks.get((cls, attr))
            if hit:
                return hit
            parents = self.bases.get(cls, [])
            cls = parents[0] if parents else None
        # unique-attribute fallback: one class in the whole package
        # defines a lock under this attribute name
        names = self.attr_index.get(attr, set())
        if len(names) == 1:
            return next(iter(names))
        return None

    def resolve_lock(self, expr: ast.AST, modkey: str, cls: Optional[str],
                     func: str) -> Optional[str]:
        if isinstance(expr, ast.Name):
            local = f"{modkey}.{func}.{expr.id}"
            if local in self.graph.locks:
                return local
            return self.module_lock(modkey, expr.id)
        if isinstance(expr, ast.Attribute):
            base = expr.value
            if isinstance(base, ast.Name) and base.id == "self":
                return self._class_attr_lock(cls, expr.attr)
            recv = receiver_name(base)
            for candidate in RECEIVER_TYPES.get(recv or "", ()):
                hit = self.class_locks.get((candidate, expr.attr))
                if hit:
                    return hit
            names = self.attr_index.get(expr.attr, set())
            if len(names) == 1:
                return next(iter(names))
        if isinstance(expr, ast.Call):
            # `with self._mesh_op_lock():` — reviewed lock-returning calls
            fn = expr.func
            if isinstance(fn, ast.Attribute):
                return CALL_RETURNS_LOCK.get(fn.attr)
        return None

    def module_lock(self, modkey: str, name: str) -> Optional[str]:
        full = f"{modkey}.{name}"
        return full if full in self.graph.locks else None

    # -- call resolution -----------------------------------------------------

    def resolve_call(self, call: ast.Call, modkey: str,
                     cls: Optional[str]) -> List[str]:
        fn = call.func
        if isinstance(fn, ast.Name):
            qual = self.functions.get((modkey, fn.id))
            if qual:
                return [qual]
            return []
        if not isinstance(fn, ast.Attribute):
            return []
        meth = fn.attr
        base = fn.value
        if isinstance(base, ast.Name) and base.id == "self":
            if cls and (cls, meth) in self.methods:
                return [self.methods[(cls, meth)]]
            targets = CALLBACK_TARGETS.get((cls or "", meth))
            if targets:
                return list(targets)
            # inherited method
            parents = self.bases.get(cls or "", [])
            for p in parents:
                if (p, meth) in self.methods:
                    return [self.methods[(p, meth)]]
        recv = receiver_name(base)
        out = []
        for candidate in RECEIVER_TYPES.get(recv or "", ()):
            if (candidate, meth) in self.methods:
                out.append(self.methods[(candidate, meth)])
        if out:
            return out
        # unique-method fallback: exactly one class defines this name
        # (never for collection-protocol/generic names — see
        # _GENERIC_METHODS)
        names = self.method_index.get(meth, set())
        if (len(names) == 1 and meth not in _GENERIC_METHODS
                and not isinstance(base, ast.Name)):
            return list(names)
        if isinstance(base, ast.Name):
            # module alias: features.extract_batch(...) etc.
            qual = self.functions.get((base.id, meth))
            if qual:
                return [qual]
        return []

    # -- pass 2: per-function held-region walk -------------------------------

    def analyze_functions(self) -> None:
        for mod in self.modules:
            modkey = self._modkey(mod)
            for node in mod.tree.body:
                if isinstance(node, ast.FunctionDef):
                    self._analyze_one(mod, modkey, None, node)
                elif isinstance(node, ast.ClassDef):
                    for item in node.body:
                        if isinstance(item, ast.FunctionDef):
                            self._analyze_one(mod, modkey, node.name, item)

    def _analyze_one(self, mod: Module, modkey: str, cls: Optional[str],
                     func: ast.FunctionDef) -> None:
        qual = f"{cls}.{func.name}" if cls else f"{modkey}.{func.name}"
        info = _FuncInfo(qual, mod.rel)
        self.funcs[qual] = info
        held: List[str] = []

        def lockname_of_acquire(call: ast.Call) -> Optional[str]:
            fn = call.func
            if isinstance(fn, ast.Attribute) and fn.attr in (
                    "acquire", "release"):
                return self.resolve_lock(fn.value, modkey, cls, func.name)
            return None

        def handle_expr(node: ast.AST) -> None:
            """Record calls + bare acquire()/release() inside one
            expression/statement (no with-scoping at this level)."""
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Call):
                    continue
                fn = sub.func
                if isinstance(fn, ast.Attribute) and fn.attr == "acquire":
                    lock = lockname_of_acquire(sub)
                    if lock and lock not in held:
                        for h in held:
                            self.graph.add_edge(h, lock, mod.rel,
                                                sub.lineno)
                        held.append(lock)
                    continue
                if isinstance(fn, ast.Attribute) and fn.attr == "release":
                    lock = lockname_of_acquire(sub)
                    if lock and lock in held:
                        held.remove(lock)
                    continue
                for callee in self.resolve_call(sub, modkey, cls):
                    info.calls.append((callee, tuple(held), sub.lineno))

        def walk_body(stmts: Sequence[ast.stmt]) -> None:
            for stmt in stmts:
                if isinstance(stmt, ast.With):
                    entered: List[str] = []
                    for item in stmt.items:
                        handle_expr(item.context_expr)
                        lock = self.resolve_lock(
                            item.context_expr, modkey, cls, func.name)
                        if lock:
                            info.direct.append(
                                (lock, tuple(held), stmt.lineno))
                            for h in held:
                                self.graph.add_edge(h, lock, mod.rel,
                                                    stmt.lineno)
                            held.append(lock)
                            entered.append(lock)
                    walk_body(stmt.body)
                    for lock in reversed(entered):
                        if lock in held:
                            held.remove(lock)
                elif isinstance(stmt, ast.If):
                    # `if not X.acquire(...):` — the lock is held AFTER
                    # the statement (the body is the failure path);
                    # `if X.acquire(...):` — held inside the body.
                    test = stmt.test
                    negated = (isinstance(test, ast.UnaryOp)
                               and isinstance(test.op, ast.Not))
                    inner = test.operand if negated else test
                    cond_lock = None
                    if (isinstance(inner, ast.Call)
                            and isinstance(inner.func, ast.Attribute)
                            and inner.func.attr == "acquire"):
                        cond_lock = self.resolve_lock(
                            inner.func.value, modkey, cls, func.name)
                    if cond_lock:
                        if negated:
                            # body is the FAILURE path (lock not held);
                            # orelse and the fall-through are the success
                            # path.  Claim the hold past the statement only
                            # when the failure path terminates — otherwise
                            # the merge point is ambiguous and phantom
                            # edges could manufacture a spurious cycle.
                            walk_body(stmt.body)
                            took = cond_lock not in held
                            if took:
                                for h in held:
                                    self.graph.add_edge(
                                        h, cond_lock, mod.rel, stmt.lineno)
                                info.direct.append(
                                    (cond_lock, tuple(held), stmt.lineno))
                                held.append(cond_lock)
                            walk_body(stmt.orelse)
                            if took and not _terminates(stmt.body):
                                held.remove(cond_lock)
                        else:
                            for h in held:
                                self.graph.add_edge(h, cond_lock, mod.rel,
                                                    stmt.lineno)
                            info.direct.append(
                                (cond_lock, tuple(held), stmt.lineno))
                            held.append(cond_lock)
                            walk_body(stmt.body)
                            if cond_lock in held:
                                held.remove(cond_lock)
                            walk_body(stmt.orelse)
                    else:
                        handle_expr(test)
                        walk_body(stmt.body)
                        walk_body(stmt.orelse)
                elif isinstance(stmt, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    # closures run within the enclosing function's lock
                    # context in this codebase (flush(), resolver(), ...)
                    walk_body(stmt.body)
                elif isinstance(stmt, (ast.For, ast.While)):
                    handle_expr(getattr(stmt, "iter", None)
                                or getattr(stmt, "test", None))
                    walk_body(stmt.body)
                    walk_body(stmt.orelse)
                elif isinstance(stmt, ast.Try):
                    walk_body(stmt.body)
                    for handler in stmt.handlers:
                        walk_body(handler.body)
                    walk_body(stmt.orelse)
                    walk_body(stmt.finalbody)
                else:
                    handle_expr(stmt)

        walk_body(func.body)

    # -- pass 3: fixpoint propagation ---------------------------------------

    def propagate(self) -> None:
        closure: Dict[str, Set[str]] = {
            q: {lock for lock, _, _ in f.direct}
            for q, f in self.funcs.items()
        }
        changed = True
        while changed:
            changed = False
            for q, f in self.funcs.items():
                acc = closure[q]
                before = len(acc)
                for callee, _, _ in f.calls:
                    acc |= closure.get(callee, set())
                if len(acc) != before:
                    changed = True
        for q, f in self.funcs.items():
            for callee, held, line in f.calls:
                if not held:
                    continue
                for lock in closure.get(callee, ()):
                    for h in held:
                        self.graph.add_edge(h, lock, f.rel, line)


def build_graph(modules: Sequence[Module]) -> LockGraph:
    a = _Analyzer(modules)
    a.collect_defs()
    a.analyze_functions()
    a.propagate()
    # reviewed runtime-observed edges (config.MANUAL_EDGES): folded into
    # the same graph so the cycle check and the generated doc cover them
    for held, acquired, why in MANUAL_EDGES:
        a.graph.edges.setdefault(
            (held, acquired), ("scripts/dukecheck/config.py", 0))
    return a.graph


# -- the generated hierarchy doc ----------------------------------------------

DOC_RELPATH = "docs/LOCK_HIERARCHY.md"

_DOC_HEADER = """\
# Lock hierarchy (generated — do not edit)

Regenerate with `python -m scripts.dukecheck --write-docs`; CI fails
(DK190) when this file is stale.  An edge `A -> B` means code somewhere
acquires `B` while holding `A` (possibly through calls); the checker
fails (DK101) if the graph ever contains a cycle, and the
`DUKE_LOCKCHECK=1` runtime sanitizer asserts observed acquisition order
against this same graph.

Rules of the hierarchy:

* acquire locks **downward** only (toward leaves of the edge table);
* never call into an engine/workload entry point while holding a leaf
  lock (telemetry, cache, store locks are leaves by design);
* a new nesting that adds an edge here is a reviewed event — regenerate
  the doc in the same PR and make sure no cycle appears.
"""


def render_doc(graph: LockGraph) -> str:
    lines = [_DOC_HEADER]
    lines.append("## Locks\n")
    lines.append("| lock | kind | defined at |")
    lines.append("|---|---|---|")
    for name in sorted(graph.locks):
        d = graph.locks[name]
        lines.append(f"| `{name}` | {d.kind} | {d.rel}:{d.line} |")
    lines.append("")
    lines.append("## Acquisition-order edges\n")
    if not graph.edges:
        lines.append("(no nested acquisitions found)")
    else:
        lines.append("| held | acquires | witness |")
        lines.append("|---|---|---|")
        for (a, b) in sorted(graph.edges):
            rel, line = graph.edges[(a, b)]
            lines.append(f"| `{a}` | `{b}` | {rel}:{line} |")
    lines.append("")
    roots = sorted({a for a, _ in graph.edges}
                   - {b for _, b in graph.edges})
    if roots:
        lines.append("## Top-level (outermost) locks\n")
        for r in roots:
            lines.append(f"* `{r}`")
        lines.append("")
    return "\n".join(lines)


def check(modules: Sequence[Module], root: Path) -> List[Finding]:
    graph = build_graph(modules)
    findings: List[Finding] = []
    for scc in graph.cycles():
        witnesses = []
        n = len(scc)
        for i, a in enumerate(scc):
            b = scc[(i + 1) % n]
            w = graph.edges.get((a, b))
            if w:
                witnesses.append(f"{a}->{b} @ {w[0]}:{w[1]}")
        first = graph.locks.get(scc[0])
        findings.append(Finding(
            "DK101", first.rel if first else "scripts/dukecheck",
            first.line if first else 0,
            "lock-order cycle: " + " / ".join(witnesses),
            "cycle:" + "|".join(scc),
        ))
    doc_path = root / DOC_RELPATH
    want = render_doc(graph)
    have = doc_path.read_text(encoding="utf-8") if doc_path.exists() else ""
    if have != want:
        findings.append(Finding(
            "DK190", DOC_RELPATH, 1,
            "lock hierarchy doc is stale — run "
            "`python -m scripts.dukecheck --write-docs`",
            "stale-doc",
        ))
    return findings
