"""Checker 4 — jit purity (DK401) + jit-cache key identity (DK402).

DK401: a function that reaches ``jax.jit``/``pjit`` (decorated, passed as
the jit argument, or — for jit *factories* like
``ops.scoring.build_property_logits`` — having its result jitted) and its
statically-resolvable same-module callees must not read wall clock
(``time.*``), nondeterminism (``random.*``, ``np.random.*``), the
environment (``os.environ``/``os.getenv``), or mutate module globals
(``global X; X = ...``).  All of these burn the value observed at TRACE
time into the compiled program: the knob/clock silently stops mattering
until the next retrace, which is exactly the class of bug the PR 5 review
cycles kept catching by hand.

DK402: a cache/memo/scorer dict keyed directly with ``id(...)`` at the
use site (``_SCORERS[id(plan)]``).  ``id()`` is reuse-prone the moment
the referent is garbage collected — the PR 5 explain-cache aliasing bug,
generalized.  (Keys built by a helper that PINS the referent alongside
the entry — the fixed explain.py pattern — do not match.)
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Sequence, Set

from .config import IMPURE_MODULES
from .core import Finding, Module

_CACHE_NAME_RE = re.compile(r"cache|memo|scorer", re.IGNORECASE)


def _is_jit_expr(node: ast.expr) -> bool:
    """``jax.jit`` / ``jit`` / ``pjit`` / ``jax.pjit`` (bare or inside
    ``partial(...)``)."""
    if isinstance(node, ast.Attribute):
        return node.attr in ("jit", "pjit")
    if isinstance(node, ast.Name):
        return node.id in ("jit", "pjit")
    return False


def _is_pallas_call(node: ast.expr) -> bool:
    """``pl.pallas_call`` / ``pallas_call`` — the kernel argument traces
    exactly like a jitted closure (ISSUE 13 satellite: these were
    unscanned since the Pallas kernels landed)."""
    if isinstance(node, ast.Attribute):
        return node.attr == "pallas_call"
    if isinstance(node, ast.Name):
        return node.id == "pallas_call"
    return False


def _partial_target(node: ast.expr):
    """``functools.partial(_kernel, ...)`` -> the ``_kernel`` name (the
    idiom every Pallas call site here uses to bind static params)."""
    if not (isinstance(node, ast.Call) and node.args):
        return None
    func = node.func
    is_partial = ((isinstance(func, ast.Name) and func.id == "partial")
                  or (isinstance(func, ast.Attribute)
                      and func.attr == "partial"))
    if not is_partial:
        return None
    target = node.args[0]
    if isinstance(target, ast.Name):
        return target.id
    if isinstance(target, ast.Attribute):
        return target.attr
    return None


def _jit_roots(mod: Module) -> Set[str]:
    """Names of functions whose bodies are jit-reachable: decorated
    (``@jax.jit`` / ``@partial(jit, ...)``), wrapped (``jit(f)``), or
    *factories* whose RESULT is jitted (``jax.jit(build(...))``) — a
    factory's closures trace, so its whole body is jit-reachable too."""
    roots: Set[str] = set()
    # local bindings of partial-wrapped kernels: every Pallas call site
    # here spells ``kernel = functools.partial(_kernel, ...)`` then
    # ``pl.pallas_call(kernel, ...)``, so a bare Name argument must
    # resolve through the binding (over-approximate: a reused local
    # name maps to ALL its bound targets)
    partial_bindings: Dict[str, Set[str]] = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            bound = node.targets[0].id
            target = _partial_target(node.value)
            if target is None and isinstance(node.value, ast.Name):
                target = node.value.id  # plain alias: kernel = _kernel
            if target is not None:
                partial_bindings.setdefault(bound, set()).add(target)
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if _is_jit_expr(dec):
                    roots.add(node.name)
                elif (isinstance(dec, ast.Call)
                      and (_is_jit_expr(dec.func)
                           or (isinstance(dec.func, ast.Name)
                               and dec.func.id == "partial"
                               and dec.args
                               and _is_jit_expr(dec.args[0])))):
                    roots.add(node.name)
        elif isinstance(node, ast.Call) and _is_jit_expr(node.func):
            if not node.args:
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Name):
                roots.add(arg.id)
            elif (isinstance(arg, ast.Call)
                  and isinstance(arg.func, ast.Name)):
                roots.add(arg.func.id)
            elif (isinstance(arg, ast.Call)
                  and isinstance(arg.func, ast.Attribute)):
                roots.add(arg.func.attr)
        elif isinstance(node, ast.Call) and _is_pallas_call(node.func):
            # pallas_call(kernel, ...) / pallas_call(partial(kernel, ..))
            # — the kernel closure traces, so its body is jit-reachable;
            # a bare Name resolves through its partial/alias binding
            if not node.args:
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Name):
                roots.add(arg.id)
                roots.update(partial_bindings.get(arg.id, ()))
            else:
                target = _partial_target(arg)
                if target is not None:
                    roots.add(target)
    return roots


def _impure_calls(func: ast.AST, mod: Module,
                  rel: str) -> List[Finding]:
    out: List[Finding] = []
    fname = getattr(func, "name", "<lambda>")
    globals_written: Set[str] = set()
    for node in ast.walk(func):
        if isinstance(node, ast.Global):
            globals_written.update(node.names)
    for node in ast.walk(func):
        if isinstance(node, ast.Attribute):
            base = node.value
            if (isinstance(base, ast.Name)
                    and base.id in IMPURE_MODULES):
                out.append(Finding(
                    "DK401", rel, node.lineno,
                    f"jit-reachable `{fname}` calls "
                    f"`{base.id}.{node.attr}` — traced once, burned into "
                    "the compiled program",
                    f"{fname}:{base.id}.{node.attr}",
                ))
            elif (isinstance(base, ast.Attribute)
                  and base.attr == "random"
                  and isinstance(base.value, ast.Name)
                  and base.value.id in ("np", "numpy")):
                out.append(Finding(
                    "DK401", rel, node.lineno,
                    f"jit-reachable `{fname}` calls `np.random.{node.attr}`"
                    " — nondeterminism at trace time",
                    f"{fname}:np.random.{node.attr}",
                ))
            elif (isinstance(base, ast.Name)
                  and base.id in ("os", "_os")
                  and node.attr in ("environ", "getenv")):
                out.append(Finding(
                    "DK401", rel, node.lineno,
                    f"jit-reachable `{fname}` reads the environment — the "
                    "knob freezes at trace time",
                    f"{fname}:os.environ",
                ))
        elif (isinstance(node, ast.Name)
              and isinstance(node.ctx, ast.Store)
              and node.id in globals_written):
            out.append(Finding(
                "DK401", rel, node.lineno,
                f"jit-reachable `{fname}` mutates module global "
                f"`{node.id}`",
                f"{fname}:global {node.id}",
            ))
    return out


def check(modules: Sequence[Module], root=None) -> List[Finding]:
    findings: List[Finding] = []
    for mod in modules:
        # same-module function defs (nested closures included — jitted
        # functions in this codebase are mostly factory closures).  A bare
        # name can be defined more than once (same-named methods on two
        # classes, branch-dependent defs): keep EVERY def and treat a
        # reachable name as reaching all of them — over-approximate rather
        # than silently analyzing only the first definition
        defs: Dict[str, List[ast.AST]] = {}
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs.setdefault(node.name, []).append(node)
        roots = _jit_roots(mod)
        if roots:
            reach: Set[str] = set()
            frontier = [name for name in roots if name in defs]
            while frontier:
                name = frontier.pop()
                if name in reach:
                    continue
                reach.add(name)
                for body in defs[name]:
                    for node in ast.walk(body):
                        if isinstance(node, ast.Call):
                            callee = None
                            if isinstance(node.func, ast.Name):
                                callee = node.func.id
                            elif (isinstance(node.func, ast.Attribute)
                                  and isinstance(node.func.value, ast.Name)
                                  and node.func.value.id == "self"):
                                callee = node.func.attr
                            if callee in defs and callee not in reach:
                                frontier.append(callee)
            for name in sorted(reach):
                for body in defs[name]:
                    findings.extend(_impure_calls(body, mod, mod.rel))
        # DK402 — id()-keyed cache access at the use site
        for node in ast.walk(mod.tree):
            key_expr = None
            base_name = None
            if isinstance(node, ast.Subscript):
                key_expr = node.slice
                base_name = (node.value.id
                             if isinstance(node.value, ast.Name) else
                             getattr(node.value, "attr", None))
            elif (isinstance(node, ast.Call)
                  and isinstance(node.func, ast.Attribute)
                  and node.func.attr in ("get", "setdefault", "pop")
                  and node.args):
                key_expr = node.args[0]
                bv = node.func.value
                base_name = (bv.id if isinstance(bv, ast.Name)
                             else getattr(bv, "attr", None))
            if key_expr is None or not base_name:
                continue
            if not _CACHE_NAME_RE.search(base_name):
                continue
            for sub in ast.walk(key_expr):
                if (isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Name)
                        and sub.func.id == "id"):
                    findings.append(Finding(
                        "DK402", mod.rel, node.lineno,
                        f"cache `{base_name}` keyed on bare `id(...)` — "
                        "ids alias after GC; key on the object (pinning "
                        "it) like engine/explain.py's per-plan cache",
                        f"{base_name}:id-key",
                    ))
                    break
    return findings
