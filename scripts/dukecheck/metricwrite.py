"""Checker 5 — single-writer metrics discipline (DK501/DK502).

The registry contract (telemetry/registry.py): engine hot paths write
PLAIN single-writer counters that scrape-time collectors turn into
``FamilySnapshot``s (service/metrics.py); direct registry-child traffic
belongs to the HTTP/telemetry layers.  In the hot modules
(``config.HOT_MODULE_PREFIXES``):

  * **DK501** — ``.labels(...)`` on a registry family: child lookup takes
    the family lock on every miss and allocates the key tuple on every
    call; hot paths must pre-resolve children at init (see
    device_matcher's per-bucket children).
  * **DK502** — a direct child write (``.inc``/``.observe``/``.set``/
    ``.dec``) on a registry metric: rare-event sites (corpus growth,
    mesh failure latches) carry inline justifications; per-record/
    per-op sites must move to the snapshot pattern.

Metric objects are recognized by name: module-level assignments from
``*.counter(...)`` / ``*.gauge(...)`` / ``*.histogram(...)`` anywhere in
the package build the metric-name set; writes are flagged when the
receiver is ``<METRIC>`` or ``telemetry.<METRIC>`` (or a ``.labels()``
chain on one).
"""

from __future__ import annotations

import ast
from typing import List, Sequence, Set

from .config import HOT_MODULE_PREFIXES
from .core import Finding, Module

_WRITES = ("inc", "observe", "set", "dec")
_FACTORIES = ("counter", "gauge", "histogram")


def metric_names(modules: Sequence[Module]) -> Set[str]:
    names: Set[str] = set()
    for mod in modules:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Assign):
                continue
            value = node.value
            if (isinstance(value, ast.Call)
                    and isinstance(value.func, ast.Attribute)
                    and value.func.attr in _FACTORIES):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        names.add(tgt.id)
                    elif isinstance(tgt, ast.Attribute):
                        names.add(tgt.attr)
    return names


def _metric_receiver(node: ast.expr, names: Set[str]) -> str:
    """The metric name when ``node`` is ``METRIC`` / ``telemetry.METRIC``
    / ``mod.METRIC`` / a ``.labels(...)`` call on one of those."""
    if isinstance(node, ast.Name) and node.id in names:
        return node.id
    if isinstance(node, ast.Attribute) and node.attr in names:
        return node.attr
    if (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "labels"):
        return _metric_receiver(node.func.value, names)
    return ""


def check(modules: Sequence[Module], root=None) -> List[Finding]:
    names = metric_names(modules)
    findings: List[Finding] = []
    for mod in modules:
        if not mod.rel.startswith(HOT_MODULE_PREFIXES):
            continue
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)):
                continue
            attr = node.func.attr
            if attr == "labels":
                metric = _metric_receiver(node.func.value, names)
                if metric:
                    findings.append(Finding(
                        "DK501", mod.rel, node.lineno,
                        f"label-child lookup `{metric}.labels(...)` on an "
                        "engine hot path — pre-resolve the child at init "
                        "or use the scrape-time snapshot pattern",
                        f"labels:{metric}",
                    ))
            elif attr in _WRITES:
                recv = node.func.value
                # `.labels(...).inc()` already reported as DK501
                if (isinstance(recv, ast.Call)
                        and isinstance(recv.func, ast.Attribute)
                        and recv.func.attr == "labels"):
                    continue
                metric = _metric_receiver(recv, names)
                if metric:
                    findings.append(Finding(
                        "DK502", mod.rel, node.lineno,
                        f"registry write `{metric}.{attr}(...)` on an "
                        "engine hot path — single-writer counters + "
                        "scrape-time snapshots are the contract here",
                        f"write:{metric}.{attr}",
                    ))
    return findings
