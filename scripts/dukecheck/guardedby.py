"""Checker 2 — ``# guarded by:`` field annotations (DK201/DK202/DK203).

The convention (see README "Static analysis & concurrency invariants"):

    self._queue = deque()   # guarded by: self._cv
    self.queued = 0         # guarded by: self._cv [writes]
    self.hits = 0           # single-writer: dispatcher thread

* ``guarded by: <lock-expr>`` — every access to the attribute in this
  MODULE must sit lexically inside ``with <lock-expr>:`` (the expression
  is matched textually against the enclosing with-items), or inside a
  function annotated ``# dukecheck: holds <lock-expr>`` (the documented
  caller contract), or in ``__init__``/the defining method (construction
  happens-before publication).
* ``[writes]`` — only writes are checked: stores, augmented assigns,
  deletes, subscript stores through the attribute, and calls to known
  mutating methods (``append``/``popleft``/``clear``/...).  Lock-free
  reads are the codebase's documented scrape-path stance.
* ``single-writer: <who>`` — documentation only (no static check); the
  attribute is written by exactly one thread and read lock-free.

Scope is deliberately per-module: the annotated hot classes are accessed
through their owning module's code paths, and module-locality is what
keeps a textual with-match sound (one ``self._cv`` name space).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import Finding, Module, expr_text

_GUARD_RE = re.compile(
    r"#\s*guarded by:\s*([A-Za-z_][A-Za-z0-9_.]*)\s*(\[writes\])?"
)

# method names that mutate their receiver (a `q.pending.append(x)` is a
# WRITE to `pending` even though the attribute load context is Load)
_MUTATORS = {
    "append", "appendleft", "extend", "extendleft", "insert", "pop",
    "popleft", "popitem", "remove", "discard", "clear", "add", "update",
    "setdefault", "move_to_end", "sort", "reverse",
}


class _GuardSpec:
    __slots__ = ("attr", "lock", "writes_only", "line", "owner",
                 "def_func")

    def __init__(self, attr: str, lock: str, writes_only: bool, line: int,
                 owner: Optional[str], def_func: str):
        self.attr = attr
        self.lock = lock
        self.writes_only = writes_only
        self.line = line
        self.owner = owner  # class name, or None for module globals
        self.def_func = def_func  # function holding the defining assign


def _collect_specs(mod: Module) -> List[_GuardSpec]:
    specs: List[_GuardSpec] = []

    def scan_assign(node, owner: Optional[str],
                    def_func: str = "") -> None:
        # the annotation may sit on any line the (possibly wrapped)
        # assignment spans
        last = getattr(node, "end_lineno", node.lineno) or node.lineno
        m = None
        for lineno in range(node.lineno, min(last, len(mod.lines)) + 1):
            m = _GUARD_RE.search(mod.lines[lineno - 1])
            if m:
                break
        if not m:
            return
        lock, writes_only = m.group(1), bool(m.group(2))
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target])
        for tgt in targets:
            if (isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"):
                specs.append(_GuardSpec(tgt.attr, lock, writes_only,
                                        node.lineno, owner, def_func))
            elif isinstance(tgt, ast.Name) and owner is None:
                specs.append(_GuardSpec(tgt.id, lock, writes_only,
                                        node.lineno, None, def_func))

    for node in mod.tree.body:
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            scan_assign(node, None)
        elif isinstance(node, ast.ClassDef):
            for item in node.body:
                if isinstance(item, ast.FunctionDef):
                    for sub in ast.walk(item):
                        if isinstance(sub, (ast.Assign, ast.AnnAssign)):
                            scan_assign(sub, node.name, item.name)
    return specs


def _function_holds(mod: Module, func: ast.FunctionDef) -> Set[str]:
    """Lock expressions a ``# dukecheck: holds <expr>`` comment on the
    def line (or the first body lines, next to the docstring) asserts."""
    held: Set[str] = set()
    last = func.body[0].lineno if func.body else func.lineno
    for line in range(func.lineno, last + 1):
        if line in mod.holds:
            held.update(e.strip() for e in mod.holds[line].split(","))
    return held


def _is_write(node: ast.expr, parents: Dict[ast.AST, ast.AST]) -> bool:
    if isinstance(node.ctx, (ast.Store, ast.Del)):
        return True
    parent = parents.get(node)
    # self.attr[k] = v  /  del self.attr[k]
    if (isinstance(parent, ast.Subscript)
            and isinstance(parent.ctx, (ast.Store, ast.Del))
            and parent.value is node):
        return True
    # self.attr.append(x) and friends
    if (isinstance(parent, ast.Attribute) and parent.value is node
            and parent.attr in _MUTATORS):
        grand = parents.get(parent)
        if isinstance(grand, ast.Call) and grand.func is parent:
            return True
    return False


def check(modules: Sequence[Module], root=None) -> List[Finding]:
    findings: List[Finding] = []
    for mod in modules:
        specs = _collect_specs(mod)
        if not specs:
            continue
        # the textual with-match is per-module by NAME, so two annotations
        # for the same attribute name must agree — a silent "last one
        # wins" would check half the accesses against the wrong lock
        by_attr: Dict[str, _GuardSpec] = {}
        for s in specs:
            prev = by_attr.setdefault(s.attr, s)
            if prev is not s and (prev.lock != s.lock
                                  or prev.writes_only != s.writes_only):
                findings.append(Finding(
                    "DK203", mod.rel, s.line,
                    f"conflicting `# guarded by:` annotations for "
                    f"`{s.attr}`: `{s.lock}`"
                    f"{' [writes]' if s.writes_only else ''} here vs "
                    f"`{prev.lock}`"
                    f"{' [writes]' if prev.writes_only else ''} at "
                    f"{mod.rel}:{prev.line} — rename one field or unify "
                    "the lock",
                    f"{s.owner or 'module'}.{s.attr}:conflict",
                ))
        parents: Dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(mod.tree):
            for child in ast.iter_child_nodes(parent):
                parents[child] = parent

        class Walker(ast.NodeVisitor):
            def __init__(self):
                self.with_stack: List[str] = []
                self.func_stack: List[Tuple[str, Set[str]]] = []

            def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
                # a def's body does NOT run under the with-blocks that
                # lexically enclose it — it runs when called (thread
                # target, callback).  Its own `# dukecheck: holds`
                # contract is the only way in.
                outer_with = self.with_stack
                self.with_stack = []
                self.func_stack.append(
                    (node.name, _function_holds(mod, node)))
                self.generic_visit(node)
                self.func_stack.pop()
                self.with_stack = outer_with

            visit_AsyncFunctionDef = visit_FunctionDef

            def visit_With(self, node: ast.With) -> None:
                texts = [expr_text(item.context_expr)
                         for item in node.items]
                self.with_stack.extend(texts)
                self.generic_visit(node)
                del self.with_stack[len(self.with_stack) - len(texts):]

            def _guards_held(self) -> Set[str]:
                held = set(self.with_stack)
                for _, extra in self.func_stack:
                    held |= extra
                return held

            def _check_access(self, attr: str, node: ast.AST,
                              write: bool) -> None:
                spec = by_attr.get(attr)
                if spec is None:
                    return
                if spec.writes_only and not write:
                    return
                func = self.func_stack[-1][0] if self.func_stack else ""
                # construction happens-before publication; the defining
                # site must match on enclosing function too — an
                # unrelated access can share a line NUMBER with it
                if func == "__init__" or (node.lineno == spec.line
                                          and func == spec.def_func):
                    return
                if spec.lock in self._guards_held():
                    return
                code = "DK201" if write else "DK202"
                kind = "write to" if write else "read of"
                findings.append(Finding(
                    code, mod.rel, node.lineno,
                    f"{kind} `{attr}` outside `with {spec.lock}` "
                    f"(annotated guarded-by at {mod.rel}:{spec.line})",
                    f"{spec.owner or 'module'}.{attr}@{func}",
                ))

            def visit_Attribute(self, node: ast.Attribute) -> None:
                self._check_access(node.attr, node,
                                   _is_write(node, parents))
                self.generic_visit(node)

            def visit_Name(self, node: ast.Name) -> None:
                spec = by_attr.get(node.id)
                if spec is not None and spec.owner is None:
                    self._check_access(node.id, node,
                                       _is_write(node, parents))

        Walker().visit(mod.tree)
    return findings
