"""Checker 3 — env-knob discipline (DK301).

Every environment knob must be read through ``telemetry/env.py``
(``env_int``/``env_float``/``env_str``/``env_flag``/...), which carry the
codebase-wide convention: malformed values fall back to the default
instead of killing the service at import time.  A raw ``os.environ`` /
``os.getenv`` touch anywhere else in the package is a finding — the
handful of justified raw uses (subprocess env composition, the config
parser's injectable ``env=`` seam) carry inline
``# dukecheck: ignore[DK301]`` suppressions with their reasons.
"""

from __future__ import annotations

import ast
from typing import List, Sequence

from .core import Finding, Module

ALLOWED = ("sesam_duke_microservice_tpu/telemetry/env.py",)


def _env_var_hint(node: ast.AST, parents_parent: ast.AST = None) -> str:
    """Best-effort knob name for the baseline key (first string literal
    argument of the enclosing call/subscript, else 'environ')."""
    target = parents_parent
    if isinstance(target, ast.Call):
        for arg in target.args:
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                return arg.value
    if isinstance(target, ast.Subscript):
        sl = target.slice
        if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
            return sl.value
    return "environ"


def check(modules: Sequence[Module], root=None) -> List[Finding]:
    findings: List[Finding] = []
    for mod in modules:
        if mod.rel in ALLOWED:
            continue
        parents = {}
        for parent in ast.walk(mod.tree):
            for child in ast.iter_child_nodes(parent):
                parents[child] = parent
        for node in ast.walk(mod.tree):
            hit = None
            if (isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and node.value.id in ("os", "_os")
                    and node.attr in ("environ", "getenv")):
                hit = node
            elif (isinstance(node, ast.Name)
                  and node.id in ("environ", "getenv")
                  and isinstance(node.ctx, ast.Load)
                  and not isinstance(parents.get(node), ast.Attribute)):
                # `from os import environ` style (none today; keep the
                # checker closed under the obvious dodge)
                imported = any(
                    isinstance(n, ast.ImportFrom) and n.module == "os"
                    and any(a.name in ("environ", "getenv")
                            for a in n.names)
                    for n in mod.tree.body
                )
                if imported:
                    hit = node
            if hit is None:
                continue
            # climb to the expression that names the knob
            up = parents.get(hit)
            while isinstance(up, ast.Attribute):
                up = parents.get(up)
            var = _env_var_hint(hit, up)
            findings.append(Finding(
                "DK301", mod.rel, hit.lineno,
                f"raw environment access ({var!r}) — use the "
                "telemetry.env helpers (env_int/env_float/env_str/"
                "env_flag) instead",
                f"env:{var}",
            ))
    return findings
