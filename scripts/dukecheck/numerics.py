"""Checker 6 — certified-numerics EFT discipline (DK601..DK604).

The double-double (two-float) emulated-f64 pipeline (``ops/dd.py`` and
the ``_dd_*`` program functions in ``ops/scoring.py``) is sound only
under conventions no test can see breaking on today's compiler:

  * **DK601** — raw float arithmetic on dd ``(hi, lo)`` components in a
    dd program function.  ``x[0] + y[0]`` silently discards the low
    word; everything must go through the ``ops.dd`` helpers.
  * **DK602** — an error-free-transform intermediate that escapes
    uncommitted: inside the dd core modules every traced float binop
    (``+ - * /``) must be the direct argument of the commit barrier
    (``_f32`` / ``lax.reduce_precision``).  An uncommitted intermediate
    is exactly what XLA's algebraic simplifier cancels (``x - (x - a)``
    -> ``a``: measured 2.2e-8 vs 3e-16) and what the CPU/GPU backends
    FMA-contract (a full f32 ulp on ``log``'s reduction term) — the two
    compiler passes that silently collapse dd to plain f32 while every
    bit-identity test stays green.
  * **DK603** — a Python float literal that is NOT exactly representable
    in float32 fed to a dd op or lift helper (``from_f32(0.1)``): the
    device then computes with a silently rounded constant while the host
    oracle uses the exact f64 — the dd-constant constructor (``const``)
    carries the full f64 image and is the only blessed spelling.
  * **DK604** — budget-table completeness: every feature kind in
    ``ops.features.ALL_KINDS`` must carry a ``_SIM_ERROR_BOUND`` entry
    and be claimed by exactly one of ``DD_KINDS`` /
    ``DD_FALLBACK_KINDS``, and every certified kind must have a
    ``_DD_SIM_OPS`` budget.  Today a new comparator kind silently gets
    no margin entry (``.get(kind, inf)``) — sound but invisible; this
    makes adding a kind without a reviewed budget decision a CI failure.

All checks are pure stdlib-``ast``; the compiled-HLO counterpart
(``hlocheck``) catches what source-level analysis cannot (a jaxlib
upgrade changing what the barriers mean).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set

import struct

from .config import (
    DD_BUDGET_MODULE,
    DD_CERTIFIED_LIST,
    DD_COMMIT_FUNCS,
    DD_CONST_FUNCS,
    DD_CORE_MODULES,
    DD_F32_TABLE,
    DD_FALLBACK_LIST,
    DD_KINDS_MODULE,
    DD_KINDS_REGISTRY,
    DD_LIFT_FUNCS,
    DD_OPS_TABLE,
    DD_OP_FUNCS,
    DD_PROGRAM_FUNCTIONS,
)
from .core import Finding, Module, expr_text

_FLOAT_BINOPS = (ast.Add, ast.Sub, ast.Mult, ast.Div)


def _call_name(func: ast.expr) -> Optional[str]:
    """Bare/attribute callable name: ``_f32`` / ``lax.reduce_precision``
    / ``D.add`` all resolve to their terminal name."""
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _f32_exact(value: float) -> bool:
    """Does ``float32(value)`` round-trip to the same f64?"""
    try:
        return struct.unpack("f", struct.pack("f", value))[0] == value
    except (OverflowError, struct.error):
        return False


def _parent_map(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    parents: Dict[ast.AST, ast.AST] = {}
    for parent in ast.walk(tree):
        for child in ast.iter_child_nodes(parent):
            parents[child] = parent
    return parents


def _module_functions(mod: Module) -> Dict[str, List[ast.AST]]:
    defs: Dict[str, List[ast.AST]] = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, []).append(node)
    return defs


def _traced_functions(mod: Module) -> Set[str]:
    """Functions whose bodies build traced computations: they reference
    ``jnp``/``lax``, call a commit barrier, or (fixpoint) call another
    traced same-module function.  Host-side helpers (numpy/math only —
    ``const_pair``, ``to_f64``) are exempt from the commit discipline:
    Python f64 arithmetic there is exact and never sees XLA."""
    defs = _module_functions(mod)
    direct: Set[str] = set()
    calls: Dict[str, Set[str]] = {name: set() for name in defs}
    for name, bodies in defs.items():
        for body in bodies:
            for node in ast.walk(body):
                if isinstance(node, ast.Name) and node.id in ("jnp", "lax"):
                    direct.add(name)
                elif isinstance(node, ast.Call):
                    callee = _call_name(node.func)
                    if callee in DD_COMMIT_FUNCS:
                        direct.add(name)
                    if callee in defs:
                        calls[name].add(callee)
    traced = set(direct)
    changed = True
    while changed:
        changed = False
        for name, callees in calls.items():
            if name not in traced and callees & traced:
                traced.add(name)
                changed = True
    return traced


def _in_const_call(node: ast.AST,
                   parents: Dict[ast.AST, ast.AST]) -> bool:
    """Is ``node`` inside an argument of a dd-constant constructor?
    (Host f64 arithmetic there is exact by design.)"""
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, ast.Call) \
                and _call_name(cur.func) in DD_CONST_FUNCS:
            return True
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return False
        cur = parents.get(cur)
    return False


def _constant_only(node: ast.expr) -> bool:
    """Arithmetic over literals and ALL_CAPS module constants is host
    Python (folded to an exact f64 before any tracing)."""
    for leaf in ast.walk(node):
        if isinstance(leaf, ast.Name) and not leaf.id.isupper():
            return False
        if isinstance(leaf, (ast.Call, ast.Attribute, ast.Subscript)):
            return False
    return True


def _component_subscript(node: ast.expr) -> Optional[str]:
    """``x[0]`` / ``x[1]`` (possibly negated) -> the component text."""
    if isinstance(node, ast.UnaryOp):
        node = node.operand
    if (isinstance(node, ast.Subscript)
            and isinstance(node.value, ast.Name)
            and isinstance(node.slice, ast.Constant)
            and node.slice.value in (0, 1)):
        return f"{node.value.id}[{node.slice.value}]"
    return None


# -- DK602: commit discipline in the dd core ----------------------------------


def _check_core(mod: Module) -> Iterable[Finding]:
    traced = _traced_functions(mod)
    defs = _module_functions(mod)
    parents = _parent_map(mod.tree)
    for name in sorted(traced):
        for body in defs[name]:
            for node in ast.walk(body):
                if not (isinstance(node, ast.BinOp)
                        and isinstance(node.op, _FLOAT_BINOPS)):
                    continue
                parent = parents.get(node)
                if (isinstance(parent, ast.Call)
                        and _call_name(parent.func) in DD_COMMIT_FUNCS
                        and node in parent.args):
                    continue  # committed: _f32(a + b)
                if _in_const_call(node, parents):
                    continue  # host f64 constant expression
                if _constant_only(node):
                    continue  # literal/module-constant arithmetic
                yield Finding(
                    "DK602", mod.rel, node.lineno,
                    f"uncommitted EFT intermediate in `{name}`: "
                    f"`{expr_text(node)}` must be wrapped in the commit "
                    "barrier (`_f32(...)`) or XLA's algebraic simplifier "
                    "/ backend FMA contraction can collapse the "
                    "error-free transform",
                    f"{name}:{expr_text(node)}",
                )


# -- DK601/DK603: dd program functions ----------------------------------------


def _dd_functions(mod: Module, prefixes) -> List[ast.AST]:
    out = []
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and any(node.name.startswith(p) for p in prefixes):
            out.append(node)
    return out


def _check_components(mod: Module, func: ast.AST) -> Iterable[Finding]:
    fname = getattr(func, "name", "<lambda>")
    for node in ast.walk(func):
        if not (isinstance(node, ast.BinOp)
                and isinstance(node.op, _FLOAT_BINOPS)):
            continue
        for side in (node.left, node.right):
            comp = _component_subscript(side)
            if comp is not None:
                yield Finding(
                    "DK601", mod.rel, node.lineno,
                    f"raw float arithmetic on dd component `{comp}` in "
                    f"`{fname}` (`{expr_text(node)}`) — the low word is "
                    "silently discarded; use the ops.dd helpers",
                    f"{fname}:{comp}",
                )
                break


def _check_literals(mod: Module, func: ast.AST) -> Iterable[Finding]:
    fname = getattr(func, "name", "<lambda>")
    parents = _parent_map(func)
    for node in ast.walk(func):
        if not isinstance(node, ast.Call):
            continue
        callee = _call_name(node.func)
        if callee not in DD_OP_FUNCS and callee not in DD_LIFT_FUNCS:
            continue
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            for leaf in ast.walk(arg):
                if (isinstance(leaf, ast.Constant)
                        and isinstance(leaf.value, float)
                        and not _f32_exact(leaf.value)
                        and not _in_const_call(leaf, parents)):
                    yield Finding(
                        "DK603", mod.rel, node.lineno,
                        f"float literal {leaf.value!r} fed to dd op "
                        f"`{callee}` in `{fname}` is not exactly "
                        "representable in float32 — it silently rounds; "
                        "route it through the dd-constant constructor "
                        "(`const(...)`) so the device computes with the "
                        "host oracle's f64 image",
                        f"{fname}:{callee}:{leaf.value!r}",
                    )


# -- DK604: budget-table completeness -----------------------------------------


def _tuple_names(node: ast.expr) -> Optional[List[str]]:
    """Names in a tuple/list literal of Names/Attributes, else None."""
    if not isinstance(node, (ast.Tuple, ast.List)):
        return None
    out = []
    for elt in node.elts:
        if isinstance(elt, ast.Name):
            out.append(elt.id)
        elif isinstance(elt, ast.Attribute):
            out.append(elt.attr)
        else:
            return None
    return out


def _module_assign(mod: Module, name: str) -> Optional[ast.expr]:
    for node in mod.tree.body:
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and tgt.id == name:
                    return node.value
    return None


def _dict_key_names(node: ast.expr) -> Optional[List[str]]:
    if not isinstance(node, ast.Dict):
        return None
    out = []
    for key in node.keys:
        if isinstance(key, ast.Attribute):
            out.append(key.attr)
        elif isinstance(key, ast.Name):
            out.append(key.id)
        else:
            return None
    return out


def _check_tables(mods_by_rel: Dict[str, Module]) -> Iterable[Finding]:
    kinds_mod = mods_by_rel.get(DD_KINDS_MODULE)
    budget_mod = mods_by_rel.get(DD_BUDGET_MODULE)
    if kinds_mod is None or budget_mod is None:
        return
    registry = _module_assign(kinds_mod, DD_KINDS_REGISTRY)
    kinds = _tuple_names(registry) if registry is not None else None
    if kinds is None:
        yield Finding(
            "DK604", DD_KINDS_MODULE, 1,
            f"kind registry `{DD_KINDS_REGISTRY}` missing or not a "
            "plain tuple of kind names — the budget-table completeness "
            "check has nothing to check against",
            f"{DD_KINDS_REGISTRY}:missing",
        )
        return

    # the registry itself must be complete: every kind `feature_kind()`
    # can RETURN must be registered, or a new comparator branch bypasses
    # every downstream table check (the exact silent-margin hole DK604
    # exists to close)
    for node in ast.walk(kinds_mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name == "feature_kind":
            for ret in ast.walk(node):
                if isinstance(ret, ast.Return) \
                        and isinstance(ret.value, ast.Name) \
                        and ret.value.id != "None" \
                        and ret.value.id not in kinds:
                    yield Finding(
                        "DK604", DD_KINDS_MODULE, ret.lineno,
                        f"`feature_kind` can return `{ret.value.id}` "
                        f"but it is not in `{DD_KINDS_REGISTRY}` — the "
                        "kind would ship with no budget-table checks "
                        "(margin silently inf); register it",
                        f"{DD_KINDS_REGISTRY}-unregistered:"
                        f"{ret.value.id}",
                    )

    def names_of(table: str, want_dict: bool):
        node = _module_assign(budget_mod, table)
        got = (_dict_key_names(node) if want_dict
               else _tuple_names(node)) if node is not None else None
        if got is None:
            line = node.lineno if node is not None else 1
            return None, Finding(
                "DK604", DD_BUDGET_MODULE, line,
                f"`{table}` missing or not a static "
                f"{'dict' if want_dict else 'tuple'} of kind entries",
                f"{table}:missing",
            )
        return got, None

    f32_keys, err = names_of(DD_F32_TABLE, True)
    if err:
        yield err
    ops_keys, err = names_of(DD_OPS_TABLE, True)
    if err:
        yield err
    certified, err = names_of(DD_CERTIFIED_LIST, False)
    if err:
        yield err
    fallback, err = names_of(DD_FALLBACK_LIST, False)
    if err:
        yield err
    if None in (f32_keys, ops_keys, certified, fallback):
        return

    for kind in kinds:
        if kind not in f32_keys:
            yield Finding(
                "DK604", DD_BUDGET_MODULE, 1,
                f"feature kind `{kind}` has no `{DD_F32_TABLE}` entry — "
                "the f32 certified margin silently treats it as "
                "uncertifiable (inf); add a reviewed similarity-error "
                "budget (or an explicit inf with a soundness comment)",
                f"{DD_F32_TABLE}:{kind}",
            )
        claimed = (kind in certified) + (kind in fallback)
        if claimed == 0:
            yield Finding(
                "DK604", DD_BUDGET_MODULE, 1,
                f"feature kind `{kind}` is in neither "
                f"`{DD_CERTIFIED_LIST}` nor `{DD_FALLBACK_LIST}` — "
                "every kind needs an explicit certified-vs-fallback "
                "decision for the device-finalize split",
                f"partition:{kind}",
            )
        elif claimed == 2:
            yield Finding(
                "DK604", DD_BUDGET_MODULE, 1,
                f"feature kind `{kind}` is in BOTH "
                f"`{DD_CERTIFIED_LIST}` and `{DD_FALLBACK_LIST}` — the "
                "partition must be exact",
                f"partition-overlap:{kind}",
            )
    for kind in certified:
        if kind not in ops_keys:
            yield Finding(
                "DK604", DD_BUDGET_MODULE, 1,
                f"certified dd kind `{kind}` has no `{DD_OPS_TABLE}` "
                "budget — certified_dd_margin would raise on the first "
                "plan carrying it; add the reviewed op-count budget",
                f"{DD_OPS_TABLE}:{kind}",
            )
    for table, keys in ((DD_F32_TABLE, f32_keys), (DD_OPS_TABLE, ops_keys),
                        (DD_CERTIFIED_LIST, certified),
                        (DD_FALLBACK_LIST, fallback)):
        for kind in keys:
            if kind not in kinds:
                yield Finding(
                    "DK604", DD_BUDGET_MODULE, 1,
                    f"`{table}` entry `{kind}` is not in the "
                    f"`{DD_KINDS_REGISTRY}` registry — stale entry or "
                    "unregistered kind",
                    f"{table}-stale:{kind}",
                )


def check(modules: Sequence[Module], root=None) -> List[Finding]:
    findings: List[Finding] = []
    by_rel = {m.rel: m for m in modules}
    for rel in DD_CORE_MODULES:
        mod = by_rel.get(rel)
        if mod is None:
            continue
        findings.extend(_check_core(mod))
        for func in _module_functions(mod).values():
            for body in func:
                findings.extend(_check_literals(mod, body))
    for rel, prefixes in DD_PROGRAM_FUNCTIONS.items():
        mod = by_rel.get(rel)
        if mod is None:
            continue
        for func in _dd_functions(mod, prefixes):
            findings.extend(_check_components(mod, func))
            findings.extend(_check_literals(mod, func))
    findings.extend(_check_tables(by_rel))
    return findings
