"""Checker 7 — machine-checked error-budget ledger (DK611..DK613, DK690).

The certified margins compose hand-derived constants: the per-op dd
epsilon, the log budget, per-kind similarity-error budgets, the JW
branch guard.  Each was derived once in a PR and then became a bare
float no machine ever re-checks — an innocent "tighten this constant"
edit (or a derivation that was wrong all along) voids certification
while every test stays green, because the tests validate against the
budgets, not the budgets against the math.

``# dd-budget:`` annotations close the loop.  On (or adjacent to) the
defining line of a budget constant::

    # dd-budget: DD_EPS covers max(3*u32**2, 5*u32**2, 12*u32**2) headroom 1.25
    DD_EPS = 2.0 ** -44

    F.CHARS: 64.0 * _F32_EPS,  # dd-budget: _SIM_ERROR_BOUND[CHARS] covers 8 * eps32 headroom 4

Grammar::

    dd-budget: <target> covers <expr> [headroom <float>] [below <expr>]

* ``<target>`` — a module-level constant name, or ``TABLE[KEY]`` for a
  static dict entry (``KEY`` is the attribute/name of the dict key).
* ``covers <expr>`` — the re-derived bound.  The expression is evaluated
  in outward-rounded **interval arithmetic** and the code constant must
  be >= its upper bound (DK611 otherwise); the recorded headroom is
  ``constant / derived``.
* ``headroom <h>`` — minimum required headroom (DK611 when violated).
  Policy: every budget keeps slack against its own derivation so host
  f64 rounding, theorem looseness, and platform drift are absorbed by
  construction — a constant that only *equals* its derivation is one
  epsilon of drift from unsound.
* ``below <expr>`` — two-sided constants (guard bands): the constant
  must also stay <= the lower bound of this ceiling (DK612) — e.g. the
  JW branch guard must cover evaluation noise yet stay under the
  rational-spacing floor that makes flagged-pair residue finite.

Builtin symbols: ``u32`` = 2^-24 / ``u64`` = 2^-53 (unit roundoffs),
``eps32`` = 2^-23 (f32 machine epsilon), plus every previously-declared
ledger constant by name (so ``LOG_ERR_ABS`` can be derived in units of
``DD_EPS``).  Code-side value expressions are evaluated with the same
engine plus the pinned symbols in ``CODE_SYMBOLS``.

The ledger renders ``docs/ERROR_BUDGETS.md`` (generated, committed);
a stale doc is DK690, exactly like the lock hierarchy's DK190 — the
derivations are review surface, not just gate state.
"""

from __future__ import annotations

import ast
import math
import re
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

from .core import Finding, Module

DOC_RELPATH = "docs/ERROR_BUDGETS.md"

# code-expression symbols the AST evaluator cannot derive itself
# (``np.finfo(np.float32).eps`` and friends) — reviewed facts
CODE_SYMBOLS = {
    "_F32_EPS": 2.0 ** -23,
}

_BUILTINS = {
    "u32": 2.0 ** -24,
    "u64": 2.0 ** -53,
    "eps32": 2.0 ** -23,
}

_ANNOT_RE = re.compile(
    r"#\s*dd-budget:\s*"
    r"(?P<target>[A-Za-z_][A-Za-z0-9_]*(?:\[[A-Za-z_][A-Za-z0-9_]*\])?)\s+"
    r"covers\s+(?P<covers>.+?)"
    r"(?:\s+headroom\s+(?P<headroom>[0-9.eE+-]+))?"
    r"(?:\s+below\s+(?P<below>.+?))?\s*$"
)
_TARGET_RE = re.compile(
    r"^([A-Za-z_][A-Za-z0-9_]*)(?:\[([A-Za-z_][A-Za-z0-9_]*)\])?$"
)


# -- outward-rounded interval arithmetic --------------------------------------


def _down(x: float) -> float:
    return math.nextafter(x, -math.inf)


def _up(x: float) -> float:
    return math.nextafter(x, math.inf)


class Interval:
    __slots__ = ("lo", "hi")

    def __init__(self, lo: float, hi: float):
        if not (math.isfinite(lo) and math.isfinite(hi)) or lo > hi:
            raise ValueError(f"bad interval [{lo}, {hi}]")
        self.lo = lo
        self.hi = hi

    @classmethod
    def point(cls, x: float) -> "Interval":
        return cls(x, x)

    def __add__(self, o: "Interval") -> "Interval":
        return Interval(_down(self.lo + o.lo), _up(self.hi + o.hi))

    def __sub__(self, o: "Interval") -> "Interval":
        return Interval(_down(self.lo - o.hi), _up(self.hi - o.lo))

    def __mul__(self, o: "Interval") -> "Interval":
        c = (self.lo * o.lo, self.lo * o.hi,
             self.hi * o.lo, self.hi * o.hi)
        return Interval(_down(min(c)), _up(max(c)))

    def __truediv__(self, o: "Interval") -> "Interval":
        if o.lo <= 0.0 <= o.hi:
            raise ValueError("division by an interval containing zero")
        c = (self.lo / o.lo, self.lo / o.hi,
             self.hi / o.lo, self.hi / o.hi)
        return Interval(_down(min(c)), _up(max(c)))

    def pow(self, e: float) -> "Interval":
        c = (math.pow(self.lo, e), math.pow(self.hi, e))
        return Interval(_down(min(c)), _up(max(c)))

    def neg(self) -> "Interval":
        return Interval(-self.hi, -self.lo)


def eval_interval(expr: str, env: Dict[str, float]) -> Interval:
    """Evaluate a budget expression to an outward-rounded interval.
    The builtin unit-roundoff symbols are always in scope."""
    env = {**_BUILTINS, **env}
    tree = ast.parse(expr, mode="eval")

    def ev(node: ast.AST) -> Interval:
        if isinstance(node, ast.Expression):
            return ev(node.body)
        if isinstance(node, ast.Constant) and isinstance(
                node.value, (int, float)):
            return Interval.point(float(node.value))
        if isinstance(node, ast.Name):
            if node.id not in env:
                raise ValueError(f"unknown symbol `{node.id}`")
            return Interval.point(env[node.id])
        if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
            return ev(node.operand).neg()
        if isinstance(node, ast.BinOp):
            if isinstance(node.op, ast.Pow):
                exp = node.right
                neg = False
                if isinstance(exp, ast.UnaryOp) \
                        and isinstance(exp.op, ast.USub):
                    exp, neg = exp.operand, True
                if not (isinstance(exp, ast.Constant)
                        and isinstance(exp.value, (int, float))):
                    raise ValueError("pow exponent must be a literal")
                e = -float(exp.value) if neg else float(exp.value)
                base = ev(node.left)
                if base.lo <= 0.0:
                    raise ValueError("pow base must be positive")
                return base.pow(e)
            a, b = ev(node.left), ev(node.right)
            if isinstance(node.op, ast.Add):
                return a + b
            if isinstance(node.op, ast.Sub):
                return a - b
            if isinstance(node.op, ast.Mult):
                return a * b
            if isinstance(node.op, ast.Div):
                return a / b
            raise ValueError(f"unsupported operator {node.op}")
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                and node.func.id in ("max", "min") and not node.keywords:
            vals = [ev(a) for a in node.args]
            if not vals:
                raise ValueError("empty max()/min()")
            if node.func.id == "max":
                return Interval(max(v.lo for v in vals),
                                max(v.hi for v in vals))
            return Interval(min(v.lo for v in vals),
                            min(v.hi for v in vals))
        raise ValueError(
            f"unsupported expression node {type(node).__name__}")

    return ev(tree)


# -- annotation + code-value extraction ---------------------------------------


class Entry:
    __slots__ = ("target", "table", "key", "covers", "headroom", "below",
                 "rel", "line", "value", "derived", "ceiling", "actual")

    def __init__(self, target: str, table: Optional[str],
                 key: Optional[str], covers: str,
                 headroom: Optional[float], below: Optional[str],
                 rel: str, line: int):
        self.target = target      # display name (NAME or TABLE[KEY])
        self.table = table        # dict name when a table entry
        self.key = key
        self.covers = covers
        self.headroom = headroom
        self.below = below
        self.rel = rel
        self.line = line
        self.value: Optional[float] = None      # resolved code constant
        self.derived: Optional[float] = None    # upper bound of covers
        self.ceiling: Optional[float] = None    # lower bound of below
        self.actual: Optional[float] = None     # value / derived


def _parse_annotations(mod: Module) -> Tuple[List[Entry], List[Finding]]:
    entries: List[Entry] = []
    findings: List[Finding] = []
    for lineno, text in enumerate(mod.lines, start=1):
        if "dd-budget:" not in text:
            continue
        m = _ANNOT_RE.search(text)
        if not m:
            findings.append(Finding(
                "DK613", mod.rel, lineno,
                "unparseable `# dd-budget:` annotation — expected "
                "`<target> covers <expr> [headroom <h>] [below <expr>]`",
                f"syntax:{lineno}",
            ))
            continue
        tm = _TARGET_RE.match(m.group("target"))
        hr = m.group("headroom")
        headroom = None
        if hr:
            try:
                headroom = float(hr)
            except ValueError:
                findings.append(Finding(
                    "DK613", mod.rel, lineno,
                    f"unparseable headroom value {hr!r} in "
                    "`# dd-budget:` annotation",
                    f"headroom-syntax:{lineno}",
                ))
                continue
        entries.append(Entry(
            m.group("target"), tm.group(1) if tm.group(2) else None,
            tm.group(2), m.group("covers").strip(),
            headroom,
            (m.group("below") or "").strip() or None,
            mod.rel, lineno,
        ))
    return entries, findings


def _eval_code_expr(node: ast.expr, env: Dict[str, float]) -> float:
    """Evaluate a code-side constant expression (plain f64 semantics —
    the value IS what Python computed; intervals are for derivations)."""
    if isinstance(node, ast.Constant) and isinstance(
            node.value, (int, float)):
        return float(node.value)
    if isinstance(node, ast.Name):
        if node.id in env:
            return env[node.id]
        raise ValueError(f"unknown code symbol `{node.id}`")
    if isinstance(node, ast.Attribute):
        if node.attr in env:
            return env[node.attr]
        raise ValueError(f"unknown code symbol `{node.attr}`")
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        return -_eval_code_expr(node.operand, env)
    if isinstance(node, ast.BinOp):
        a = _eval_code_expr(node.left, env)
        b = _eval_code_expr(node.right, env)
        if isinstance(node.op, ast.Add):
            return a + b
        if isinstance(node.op, ast.Sub):
            return a - b
        if isinstance(node.op, ast.Mult):
            return a * b
        if isinstance(node.op, ast.Div):
            return a / b
        if isinstance(node.op, ast.Pow):
            return a ** b
    raise ValueError(
        f"unsupported code expression {type(node).__name__}")


def _find_code_value(mod: Module, entry: Entry,
                     env: Dict[str, float]) -> float:
    """Resolve the annotated constant's value from the module AST."""
    if entry.table is None:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name) and tgt.id == entry.target:
                        return _eval_code_expr(node.value, env)
        raise ValueError(f"no assignment `{entry.target} = ...` found")
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Dict):
            names = [t.id for t in node.targets
                     if isinstance(t, ast.Name)]
            if entry.table not in names:
                continue
            for key, val in zip(node.value.keys, node.value.values):
                kname = (key.attr if isinstance(key, ast.Attribute)
                         else key.id if isinstance(key, ast.Name) else None)
                if kname == entry.key:
                    return _eval_code_expr(val, env)
            raise ValueError(
                f"`{entry.table}` has no key `{entry.key}`")
    raise ValueError(f"no dict `{entry.table}` found")


def collect(modules: Sequence[Module]) -> Tuple[List[Entry], List[Finding]]:
    """Parse + evaluate every ledger entry in module order."""
    entries: List[Entry] = []
    findings: List[Finding] = []
    env: Dict[str, float] = dict(_BUILTINS)
    env.update(CODE_SYMBOLS)
    seen: Dict[str, Entry] = {}
    for mod in sorted(modules, key=lambda m: m.rel):
        mod_entries, mod_findings = _parse_annotations(mod)
        findings.extend(mod_findings)
        for entry in mod_entries:
            if entry.target in seen:
                findings.append(Finding(
                    "DK613", entry.rel, entry.line,
                    f"duplicate `# dd-budget:` target `{entry.target}` "
                    f"(first declared at {seen[entry.target].rel}:"
                    f"{seen[entry.target].line})",
                    f"duplicate:{entry.target}",
                ))
                continue
            seen[entry.target] = entry
            try:
                entry.value = _find_code_value(mod, entry, env)
                derived = eval_interval(entry.covers, env)
                entry.derived = derived.hi
                if entry.below is not None:
                    entry.ceiling = eval_interval(entry.below, env).lo
            except (ValueError, SyntaxError) as exc:
                findings.append(Finding(
                    "DK613", entry.rel, entry.line,
                    f"ledger entry `{entry.target}`: {exc}",
                    f"eval:{entry.target}",
                ))
                continue
            # make the constant available to later derivations by its
            # bare name (DD_EPS usable from scoring's annotations)
            if entry.table is None:
                env[entry.target] = entry.value
            if entry.derived > 0:
                entry.actual = entry.value / entry.derived
            entries.append(entry)
            if entry.value < entry.derived:
                findings.append(Finding(
                    "DK611", entry.rel, entry.line,
                    f"budget constant `{entry.target}` = "
                    f"{entry.value:.6g} does NOT cover its derived bound "
                    f"{entry.derived:.6g} (`{entry.covers}`) — the "
                    "certification margin is unsound; widen the constant "
                    "or fix the derivation",
                    f"covers:{entry.target}",
                ))
            elif entry.headroom is not None \
                    and entry.value < _up(entry.derived * entry.headroom):
                findings.append(Finding(
                    "DK611", entry.rel, entry.line,
                    f"budget constant `{entry.target}` = "
                    f"{entry.value:.6g} covers its derived bound "
                    f"{entry.derived:.6g} with only "
                    f"{entry.actual:.3g}x headroom (policy minimum "
                    f"{entry.headroom:g}x) — the slack that absorbs "
                    "host-f64 rounding and theorem looseness is gone",
                    f"headroom:{entry.target}",
                ))
            if entry.ceiling is not None and entry.value > entry.ceiling:
                findings.append(Finding(
                    "DK612", entry.rel, entry.line,
                    f"budget constant `{entry.target}` = "
                    f"{entry.value:.6g} exceeds its ceiling "
                    f"{entry.ceiling:.6g} (`{entry.below}`) — the "
                    "two-sided band (e.g. guard under the rational-"
                    "spacing floor) is violated",
                    f"below:{entry.target}",
                ))
    return entries, findings


# -- generated doc ------------------------------------------------------------


def render_doc(entries: Sequence[Entry]) -> str:
    lines = [
        "# Certified-numerics error-budget ledger",
        "",
        "**GENERATED** by `python -m scripts.dukecheck --write-docs` from "
        "the `# dd-budget:` annotations",
        "in `ops/dd.py` / `ops/scoring.py`.  Do not edit by hand — "
        "dukecheck fails (DK690) when this",
        "file is stale, and fails (DK611/DK612) when a code constant "
        "stops covering its re-derived",
        "bound or escapes its ceiling.  Derivations are evaluated in "
        "outward-rounded interval",
        "arithmetic; `headroom` is `constant / derived upper bound` and "
        "must stay above the",
        "declared policy minimum.",
        "",
        "| constant | where | value | derived bound (covers) | headroom "
        "(min) | ceiling (below) |",
        "|---|---|---|---|---|---|",
    ]
    for e in sorted(entries, key=lambda e: (e.rel, e.line)):
        hr = (f"{e.actual:.3g}x ({e.headroom:g}x)"
              if e.headroom is not None else f"{e.actual:.3g}x")
        ceil = (f"{e.ceiling:.6g} = `{e.below}`"
                if e.ceiling is not None else "—")
        lines.append(
            f"| `{e.target}` | {e.rel} | {e.value:.6g} "
            f"| {e.derived:.6g} = `{e.covers}` | {hr} | {ceil} |"
        )
    lines += [
        "",
        "Builtin symbols: `u32` = 2^-24, `u64` = 2^-53 (unit roundoffs), "
        "`eps32` = 2^-23 (f32",
        "machine epsilon); previously-declared constants are available "
        "by name, so composed",
        "budgets (`LOG_ERR_ABS` in units of `DD_EPS`) re-derive from "
        "their actual inputs.",
        "",
    ]
    return "\n".join(lines)


def check(modules: Sequence[Module], root=None) -> List[Finding]:
    entries, findings = collect(modules)
    if root is not None:
        doc_path = Path(root) / DOC_RELPATH
        want = render_doc(entries)
        have = (doc_path.read_text(encoding="utf-8")
                if doc_path.exists() else "")
        if have != want:
            findings.append(Finding(
                "DK690", DOC_RELPATH, 1,
                "error-budget ledger doc is stale — run "
                "`python -m scripts.dukecheck --write-docs`",
                "stale-doc",
            ))
    return findings
