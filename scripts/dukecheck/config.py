"""Project-native resolution tables for the dukecheck analyzers.

A dependency-free ``ast`` analysis cannot infer types, so the lock-order
checker resolves attribute receivers through these curated tables.  They
are REVIEWED facts about this codebase, not heuristics: every entry names
the class(es) a receiver variable/attribute actually holds at runtime.
The ``DUKE_LOCKCHECK=1`` runtime sanitizer keeps them honest — a dynamic
lock-order edge the static graph is missing means a table entry (or the
analysis) drifted, and the tier-1 lockcheck leg surfaces it.
"""

from __future__ import annotations

# receiver variable / attribute name -> class name(s) it holds.  Used by
# the lock-order checker to resolve `recv.attr` lock acquisitions and
# `recv.method(...)` calls when the receiver is not `self`.
RECEIVER_TYPES = {
    "wl": ("Workload",),
    "workload": ("Workload",),
    "link_database": ("WriteBehindLinkDatabase", "SqliteLinkDatabase",
                      "InMemoryLinkDatabase"),
    "_wb": ("WriteBehindBuffer",),
    "inner": ("SqliteLinkDatabase", "InMemoryLinkDatabase"),
    "record_store": ("SqliteRecordStore", "InMemoryRecordStore"),
    "_store": ("SqliteRecordStore", "InMemoryRecordStore"),
    "processor": ("Processor", "DeviceProcessor", "AnnProcessor",
                  "ShardedAnnProcessor", "ShardedDeviceProcessor"),
    "index": ("DeviceIndex", "AnnIndex", "InvertedIndex",
              "ShardedAnnIndex", "ShardedDeviceIndex"),
    "_pool": ("SqliteConnectionPool",),
    "cache": ("FeatureCache",),
    "listener": ("ServiceMatchListener",),
    "scheduler": ("IngestScheduler",),
    "corpus": ("DeviceCorpus",),
    "database": ("DeviceIndex", "AnnIndex"),
    "journal": ("LinkJournal",),
    # chaos plan (utils.faults.active() return): its occurrence counters
    # lock, so crash/flush checks under engine locks are real edges
    "plan": ("FaultPlan",),
}

# methods that RETURN a lock/guard used as `with self.m():` — resolved to
# the named lock identity
CALL_RETURNS_LOCK = {
    "_mesh_op_lock": "Dispatcher.op_lock",
}

# callable fields invoked as `self.<field>(...)` -> the concrete targets
# wired in at construction time (callback indirection the AST cannot see)
CALLBACK_TARGETS = {
    ("WriteBehindBuffer", "_flush"): (
        "WriteBehindLinkDatabase._flush_batch",
        "AuditLog._write_batch",
    ),
    # batch-sealing hook (ISSUE 10): commit() journals the sealed batch
    # under the buffer condition, so _cv -> LinkJournal._lock is a real
    # static edge the resolver must see through the callable field
    ("WriteBehindBuffer", "_seal"): (
        "WriteBehindLinkDatabase._seal_batch",
    ),
    ("IngestScheduler", "_resolve"): ("DukeApp._resolve_workload",),
}

# Reviewed acquisition-order edges the AST analysis cannot derive —
# each was OBSERVED by the DUKE_LOCKCHECK=1 runtime sanitizer and
# triaged here so the static graph (and its cycle check) covers it.
# Format: (held, acquired, witness "file:why").
MANUAL_EDGES = (
    ("Workload.lock", "_Child._lock",
     "telemetry family .inc()/.set() under the workload lock "
     "(family->child indirection the call resolver skips as generic)"),
    ("DeviceCorpus._upload_lock", "_Child._lock",
     "corpus growth/upload counters under the upload lock"),
    ("Workload.lock", "LatchedRing.lock",
     "decision-ring append during finalize (DecisionRecorder.observe)"),
    ("Processor._listener_lock", "LatchedRing.lock",
     "decision-ring append from the serial event coordinator"),
    ("Workload.lock", "native._lock",
     "lazy native-comparator library load during host scoring"),
    ("Workload.lock", "telemetry.decisions._AUDIT_LOCK",
     "audit_log() singleton resolution during the listener flush"),
    ("Workload.lock", "ops.feature_cache._CACHE_LOCK",
     "feature_cache.active() budget check during encode"),
    ("Processor._listener_lock", "AuditLog._lock",
     "LinkMatchListener batch_done appends confirmed links to the audit "
     "log under the listener lock"),
    ("Processor._listener_lock", "WriteBehindBuffer._cv",
     "listener batch_done commits the write-behind link DB (and the "
     "audit log's drop-on-overflow buffer) under the listener lock"),
    ("telemetry.decisions._AUDIT_LOCK", "WriteBehindBuffer._cv",
     "audit_log() swap closes the old AuditLog's buffer while holding "
     "the singleton lock"),
    ("DeviceIndex._lock", "ops.feature_cache._CACHE_LOCK",
     "feature_cache.active() budget check from extract_batch during "
     "streaming append (index lock held across the slice extract)"),
    ("DeviceIndex._lock", "FeatureCache._lock",
     "feature-row get_many/put_many from extract_batch during streaming "
     "append under the index lock"),
    ("Workload.lock", "engine.sharded_matcher._MESH_LOCK",
     "serving_mesh() resolution while building a sharded scorer under "
     "the workload lock"),
    ("DukeApp._swap_lock", "engine.sharded_matcher._MESH_LOCK",
     "sharded workload (re)build during config reload resolves the "
     "process mesh under the swap lock"),
    ("Workload.lock", "links.base._millis_lock",
     "links.base.now_millis() monotonic-timestamp bump while stamping "
     "links during scoring"),
    ("Processor._listener_lock", "links.base._millis_lock",
     "now_millis() from the listener's link-commit path"),
    # -- HA serving group (ISSUE 8) --
    ("Dispatcher.op_lock", "Dispatcher._send_lock",
     "broadcast() serializes per-follower sends under the global mesh "
     "op lock (every broadcast+execute section holds op_lock)"),
    ("Dispatcher._send_lock", "_Child._lock",
     "eviction counters (duke_follower_evictions_total, follower gauge) "
     "written inside the broadcast send section"),
    ("Dispatcher._send_lock", "_Family._family_lock",
     "first-time .single()/.labels() child resolution from the eviction "
     "path under the send lock"),
    ("Dispatcher.op_lock", "_Family._family_lock",
     "first-per-tag dispatch op-child resolution during a broadcast "
     "under the mesh op lock"),
    ("Dispatcher.op_lock", "ReplicaLinkDatabase.lock",
     "promoted-leader ingest: listener link writes land in the replica "
     "link DB inside the broadcast+execute section"),
    ("Dispatcher.op_lock", "native._lock",
     "lazy native-comparator load during a promoted-leader scoring "
     "pass under the mesh op lock"),
    ("Dispatcher.op_lock", "telemetry.decisions._AUDIT_LOCK",
     "audit_log() singleton resolution during a promoted-leader "
     "listener flush under the mesh op lock"),
    # -- crash-consistent ingest (ISSUE 10) --
    ("DukeApp._swap_lock", "links.journal._RECOVERY_LOCK",
     "config reload builds workloads under the swap lock; the link-DB "
     "factory's journal recovery enters the recovery_in_progress() "
     "contextmanager (readyz 'recovering' flag) — the with-statement "
     "indirection the analyzer cannot follow"),
)

# -- checker 5 (single-writer metrics) ---------------------------------------

# modules where per-event registry writes / label-child creation are
# findings: the engine + data-plane hot paths.  The blessed patterns
# there are plain single-writer counters + scrape-time FamilySnapshots
# (service/metrics.py) or pre-resolved children created at init.
HOT_MODULE_PREFIXES = (
    "sesam_duke_microservice_tpu/engine/",
    "sesam_duke_microservice_tpu/ops/",
    "sesam_duke_microservice_tpu/index/",
    "sesam_duke_microservice_tpu/links/",
    "sesam_duke_microservice_tpu/store/",
    "sesam_duke_microservice_tpu/parallel/",
)

# -- checker 4 (jit purity) ---------------------------------------------------

# modules whose names mean "wall clock" / "nondeterminism" inside traced
# code; calling into them from a jit-reachable function is a finding
IMPURE_MODULES = ("time", "random")

# -- checker 6 (certified numerics, DK6xx) ------------------------------------

# Modules under the full EFT commit discipline (DK602/DK603): every
# traced float binop must be committed through the barrier helper, so
# neither the HLO algebraic simplifier nor backend FMA contraction can
# see a cancellable/contractible pattern.  This is the dd arithmetic
# core only — the discipline is what makes its error bounds theorems
# instead of measurements.
DD_CORE_MODULES = (
    "sesam_duke_microservice_tpu/ops/dd.py",
)

# The commit-barrier spellings (a call wrapping a binop commits it).
DD_COMMIT_FUNCS = ("_f32", "reduce_precision")

# dd constant constructors: Python f64 arithmetic inside their arguments
# is HOST-side and exact (the result is split into a dd pair), so binops
# and float literals there are exempt from the commit/literal checks.
DD_CONST_FUNCS = ("const", "const_pair")

# dd lift helpers that reproduce their argument EXACTLY AS A FLOAT32 —
# feeding them a Python float literal that is not f32-representable
# silently rounds it (DK603's sharpest case: ``from_f32(0.1)`` loses the
# f64 image the oracle computes with; the fix is ``const(0.1)``).
DD_LIFT_FUNCS = ("from_f32", "from_int")

# Modules carrying dd *program* code outside the core, mapped to the
# function-name prefixes that mark their dd-marked functions (DK601:
# no raw float arithmetic on (hi, lo) components there — everything
# goes through the ops.dd helpers; DK603: no inexact float literals
# fed to dd ops outside the constant constructors).
DD_PROGRAM_FUNCTIONS = {
    "sesam_duke_microservice_tpu/ops/scoring.py": ("_dd_", "build_dd_"),
}

# dd arithmetic entry points (module-qualified as D.<name> in program
# modules, bare in the core) whose arguments DK603 scans for inexact
# float literals.
DD_OP_FUNCS = (
    "add", "sub", "mul", "div", "neg", "maximum", "minimum", "clamp",
    "where", "lt", "le", "ge", "log", "scale_pow2",
)

# -- budget-table completeness (DK604) ----------------------------------------

# where the kind registry and the budget tables live
DD_KINDS_MODULE = "sesam_duke_microservice_tpu/ops/features.py"
DD_KINDS_REGISTRY = "ALL_KINDS"
DD_BUDGET_MODULE = "sesam_duke_microservice_tpu/ops/scoring.py"
# every kind needs an entry here (the f32 certified margin)
DD_F32_TABLE = "_SIM_ERROR_BOUND"
# every certified dd kind needs an entry here (the dd margin)
DD_OPS_TABLE = "_DD_SIM_OPS"
# the two tuples that must partition the registry exactly
DD_CERTIFIED_LIST = "DD_KINDS"
DD_FALLBACK_LIST = "DD_FALLBACK_KINDS"
