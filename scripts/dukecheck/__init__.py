"""dukecheck — project-native static analysis for concurrency + telemetry
invariants (ISSUE 7 tentpole).

Five checkers over ``sesam_duke_microservice_tpu/`` (stdlib ``ast`` only,
no installs — runs in the CI lint job like scripts/check_metrics_docs.py):

  DK101  lock-order cycle in the inter-lock acquisition graph
  DK190  stale generated docs/LOCK_HIERARCHY.md
  DK201  write to a ``# guarded by:``-annotated field outside its lock
  DK202  read of a fully-guarded field outside its lock
  DK203  conflicting ``# guarded by:`` annotations for one field name
  DK301  raw os.environ access outside telemetry/env.py
  DK401  impure call (time/random/environ/global-mutation) in
         jit-reachable code
  DK402  cache keyed on bare ``id(...)``
  DK501  ``.labels(...)`` child lookup on an engine hot path
  DK502  direct registry write on an engine hot path

Usage:

    python -m scripts.dukecheck                # check (CI gate)
    python -m scripts.dukecheck --write-docs   # regenerate LOCK_HIERARCHY
    python -m scripts.dukecheck --list         # print every finding,
                                               # baselined or not

Exit 0 iff every finding is inline-suppressed or baselined AND no
baseline entry is stale (the baseline only shrinks).
"""

from __future__ import annotations

import argparse
from pathlib import Path
from typing import List

from . import envknob, guardedby, jitpurity, lockorder, metricwrite
from .core import (
    Finding,
    apply_baseline,
    filter_suppressed,
    load_baseline,
    load_modules,
)

BASELINE_RELPATH = "scripts/dukecheck/baseline.txt"

CHECKERS = (
    ("lock-order", lockorder.check),
    ("guarded-by", guardedby.check),
    ("env-knob", envknob.check),
    ("jit-purity", jitpurity.check),
    ("metrics", metricwrite.check),
)


def collect_findings(root: Path, modules=None) -> List[Finding]:
    if modules is None:
        modules = load_modules(root)
    by_rel = {m.rel: m for m in modules}
    findings: List[Finding] = []
    for _, fn in CHECKERS:
        findings.extend(fn(modules, root))
    findings = filter_suppressed(by_rel, findings)
    findings.sort(key=lambda f: (f.rel, f.line, f.code))
    return findings


def run(root: Path, *, write_docs: bool = False,
        list_all: bool = False) -> int:
    modules = load_modules(root)
    if write_docs:
        graph = lockorder.build_graph(modules)
        doc = root / lockorder.DOC_RELPATH
        doc.parent.mkdir(parents=True, exist_ok=True)
        doc.write_text(lockorder.render_doc(graph), encoding="utf-8")
        print(f"wrote {lockorder.DOC_RELPATH} "
              f"({len(graph.locks)} locks, {len(graph.edges)} edges)")
        return 0
    findings = collect_findings(root, modules)
    baseline = load_baseline(root / BASELINE_RELPATH)
    new, stale = apply_baseline(findings, baseline)
    if list_all:
        for f in findings:
            mark = " [baselined]" if f.key in baseline else ""
            print(f.render() + mark)
        print(f"{len(findings)} findings "
              f"({len(findings) - len(new)} baselined)")
    ok = True
    if new:
        ok = False
        print(f"dukecheck: {len(new)} new finding(s) "
              "(fix, suppress inline with a justification, or — last "
              "resort — baseline):")
        for f in new:
            print("  " + f.render())
    if stale:
        ok = False
        print(f"dukecheck: {len(stale)} stale baseline entr"
              f"{'y' if len(stale) == 1 else 'ies'} — the violation is "
              "gone; delete the line(s) (the baseline only shrinks):")
        for key in stale:
            print("  " + key)
    if ok and not list_all:
        print(f"dukecheck: clean ({len(findings)} finding(s), all "
              f"baselined; {len(baseline)} baseline entr"
              f"{'y' if len(baseline) == 1 else 'ies'})")
    return 0 if ok else 1


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m scripts.dukecheck",
        description="project-native static analysis "
                    "(lock order, guarded-by, env knobs, jit purity, "
                    "metrics discipline)",
    )
    parser.add_argument("--write-docs", action="store_true",
                        help="regenerate docs/LOCK_HIERARCHY.md and exit")
    parser.add_argument("--list", action="store_true", dest="list_all",
                        help="print every finding including baselined")
    parser.add_argument("--root", default=None,
                        help="repo root (default: two levels above this "
                             "package)")
    args = parser.parse_args(argv)
    root = Path(args.root) if args.root else (
        Path(__file__).resolve().parent.parent.parent
    )
    return run(root, write_docs=args.write_docs, list_all=args.list_all)
