"""dukecheck — project-native static analysis for concurrency, telemetry
and certified-numerics invariants (ISSUE 7 tentpole; numerics suite
ISSUE 13).

Eight checkers over ``sesam_duke_microservice_tpu/`` (stdlib ``ast``
except the compiled-HLO gate, which needs jax — runs in the CI lint job
like scripts/check_metrics_docs.py):

  DK101  lock-order cycle in the inter-lock acquisition graph
  DK190  stale generated docs/LOCK_HIERARCHY.md
  DK201  write to a ``# guarded by:``-annotated field outside its lock
  DK202  read of a fully-guarded field outside its lock
  DK203  conflicting ``# guarded by:`` annotations for one field name
  DK301  raw os.environ access outside telemetry/env.py
  DK401  impure call (time/random/environ/global-mutation) in
         jit-reachable code (pl.pallas_call kernel closures included)
  DK402  cache keyed on bare ``id(...)``
  DK501  ``.labels(...)`` child lookup on an engine hot path
  DK502  direct registry write on an engine hot path
  DK601  raw float arithmetic on dd (hi, lo) components
  DK602  error-free-transform intermediate escaping uncommitted
  DK603  inexact float literal fed to a dd op (use the const ctor)
  DK604  feature kind missing from the certified budget tables
  DK611  budget constant fails to cover its re-derived bound / headroom
  DK612  two-sided budget constant exceeds its ceiling
  DK613  unparseable/unevaluable ``# dd-budget:`` annotation
  DK690  stale generated docs/ERROR_BUDGETS.md
  DK701  compiled HLO lost reduce-precision commits (simplifier strip)
  DK702  dd-attributed mul feeding add directly (FMA-contraction
         exposure) in optimized HLO
  DK703  hlocheck program failed to build/lower/compile

Usage:

    python -m scripts.dukecheck                 # check (CI gate)
    python -m scripts.dukecheck --only numerics --only budgets
                                                # subset (pre-commit)
    python -m scripts.dukecheck --write-docs    # regenerate
                                                # LOCK_HIERARCHY.md +
                                                # ERROR_BUDGETS.md
    python -m scripts.dukecheck --list          # print every finding,
                                                # baselined or not

Exit 0 iff every finding is inline-suppressed or baselined AND no
baseline entry is stale (the baseline only shrinks).  hlocheck findings
(DK7xx) are NEVER baselinable: a contraction regression is a release
blocker by definition, and the runner rejects both DK7xx baseline
entries and DK7xx baseline matches outright.
"""

from __future__ import annotations

import argparse
from pathlib import Path
from typing import List, Optional, Sequence

from . import (
    budgets,
    envknob,
    guardedby,
    hlocheck,
    jitpurity,
    lockorder,
    metricwrite,
    numerics,
)
from .core import (
    Finding,
    apply_baseline,
    filter_suppressed,
    load_baseline,
    load_modules,
)

BASELINE_RELPATH = "scripts/dukecheck/baseline.txt"

# name, check fn, the finding codes the checker owns (drives --only's
# stale-baseline scoping: a subset run must not flag other checkers'
# baseline entries as stale)
CHECKERS = (
    ("lock-order", lockorder.check, ("DK101", "DK190")),
    ("guarded-by", guardedby.check, ("DK201", "DK202", "DK203")),
    ("env-knob", envknob.check, ("DK301",)),
    ("jit-purity", jitpurity.check, ("DK401", "DK402")),
    ("metrics", metricwrite.check, ("DK501", "DK502")),
    ("numerics", numerics.check, ("DK601", "DK602", "DK603", "DK604")),
    ("budgets", budgets.check, ("DK611", "DK612", "DK613", "DK690")),
    ("hlocheck", hlocheck.check, ("DK701", "DK702", "DK703")),
)

CHECKER_NAMES = tuple(name for name, _, _ in CHECKERS)

# DK7xx findings may never enter the baseline (see module docstring)
UNBASELINABLE_PREFIX = "DK7"


def collect_findings(root: Path, modules=None,
                     only: Optional[Sequence[str]] = None) -> List[Finding]:
    if modules is None:
        modules = load_modules(root)
    by_rel = {m.rel: m for m in modules}
    findings: List[Finding] = []
    for name, fn, _ in CHECKERS:
        if only and name not in only:
            continue
        findings.extend(fn(modules, root))
    findings = filter_suppressed(by_rel, findings)
    findings.sort(key=lambda f: (f.rel, f.line, f.code))
    return findings


def write_docs(root: Path, modules=None) -> int:
    """Regenerate both generated docs; non-zero when the ledger cannot
    render (a pre-commit doc refresh must not report success over a
    stale ERROR_BUDGETS.md)."""
    if modules is None:
        modules = load_modules(root)
    graph = lockorder.build_graph(modules)
    doc = root / lockorder.DOC_RELPATH
    doc.parent.mkdir(parents=True, exist_ok=True)
    doc.write_text(lockorder.render_doc(graph), encoding="utf-8")
    print(f"wrote {lockorder.DOC_RELPATH} "
          f"({len(graph.locks)} locks, {len(graph.edges)} edges)")
    entries, ledger_findings = budgets.collect(modules)
    bad = [f for f in ledger_findings if f.code == "DK613"]
    if bad:
        print("cannot render the error-budget ledger — fix the "
              "annotation(s) first:")
        for f in bad:
            print("  " + f.render())
        return 1
    bdoc = root / budgets.DOC_RELPATH
    bdoc.parent.mkdir(parents=True, exist_ok=True)
    bdoc.write_text(budgets.render_doc(entries), encoding="utf-8")
    print(f"wrote {budgets.DOC_RELPATH} ({len(entries)} budget "
          f"entr{'y' if len(entries) == 1 else 'ies'})")
    return 0


def run(root: Path, *, write_docs_only: bool = False,
        list_all: bool = False,
        only: Optional[Sequence[str]] = None) -> int:
    modules = load_modules(root)
    if write_docs_only:
        return write_docs(root, modules)
    findings = collect_findings(root, modules, only=only)
    baseline = load_baseline(root / BASELINE_RELPATH)
    ok = True
    # hlocheck findings are never baselinable — both directions
    poisoned = [k for k in baseline
                if k.startswith(UNBASELINABLE_PREFIX)]
    if poisoned:
        ok = False
        print("dukecheck: hlocheck findings (DK7xx) are NEVER "
              "baselinable — a contraction regression is a release "
              "blocker; remove:")
        for key in poisoned:
            print("  " + key)
        baseline = {k: v for k, v in baseline.items() if k not in poisoned}
    if only:
        # scope the stale check to the selected checkers' codes — a
        # subset run knows nothing about other checkers' findings
        codes = set()
        for name, _, owned in CHECKERS:
            if name in only:
                codes.update(owned)
        baseline = {k: v for k, v in baseline.items()
                    if k.split(" ", 1)[0] in codes}
    new, stale = apply_baseline(findings, baseline)
    if list_all:
        for f in findings:
            mark = " [baselined]" if f.key in baseline else ""
            print(f.render() + mark)
        print(f"{len(findings)} findings "
              f"({len(findings) - len(new)} baselined)")
    if new:
        ok = False
        print(f"dukecheck: {len(new)} new finding(s) "
              "(fix, suppress inline with a justification, or — last "
              "resort, and never for DK7xx — baseline):")
        for f in new:
            print("  " + f.render())
    if stale:
        ok = False
        print(f"dukecheck: {len(stale)} stale baseline entr"
              f"{'y' if len(stale) == 1 else 'ies'} — the violation is "
              "gone; delete the line(s) (the baseline only shrinks):")
        for key in stale:
            print("  " + key)
    if ok and not list_all:
        scope = f" [{', '.join(only)}]" if only else ""
        print(f"dukecheck: clean{scope} ({len(findings)} finding(s), "
              f"all baselined; {len(baseline)} baseline entr"
              f"{'y' if len(baseline) == 1 else 'ies'})")
    return 0 if ok else 1


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m scripts.dukecheck",
        description="project-native static analysis "
                    "(lock order, guarded-by, env knobs, jit purity, "
                    "metrics discipline, certified numerics, error "
                    "budgets, compiled-HLO contraction gate)",
    )
    parser.add_argument("--write-docs", action="store_true",
                        help="regenerate docs/LOCK_HIERARCHY.md + "
                             "docs/ERROR_BUDGETS.md and exit")
    parser.add_argument("--list", action="store_true", dest="list_all",
                        help="print every finding including baselined")
    parser.add_argument("--only", action="append", choices=CHECKER_NAMES,
                        metavar="CHECKER",
                        help="run only the named checker(s) (repeatable; "
                             f"one of: {', '.join(CHECKER_NAMES)}) — "
                             "lets the numerics gates run standalone "
                             "pre-commit without paying the full-suite "
                             "or HLO-compile cost")
    parser.add_argument("--root", default=None,
                        help="repo root (default: two levels above this "
                             "package)")
    args = parser.parse_args(argv)
    root = Path(args.root) if args.root else (
        Path(__file__).resolve().parent.parent.parent
    )
    return run(root, write_docs_only=args.write_docs,
               list_all=args.list_all, only=args.only)
