"""dukecheck framework: modules, findings, suppressions, baseline.

Every checker consumes parsed ``Module`` objects and yields ``Finding``s
spelled ``file:line: CODE message``.  Two escape hatches keep the committed
baseline near zero:

  * **inline suppression** — a trailing ``# dukecheck: ignore[DK301] why``
    comment on the finding's line silences exactly those codes there (the
    justification text is required by convention, not parsed);
  * **baseline** — ``scripts/dukecheck/baseline.txt`` lists findings that
    are known, justified, and grandfathered.  Baseline keys deliberately
    carry NO line numbers (they must survive unrelated edits): the key is
    ``CODE path :: detail`` where ``detail`` is the checker's stable
    identifier for the site (lock pair, attribute, env-var name, ...).
    New findings fail; baseline entries that no longer match fail too —
    the baseline only shrinks.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set

# package under analysis, relative to the repo root
PACKAGE = "sesam_duke_microservice_tpu"

_SUPPRESS_RE = re.compile(
    r"#\s*dukecheck:\s*ignore(?:\[([A-Za-z0-9_,\s]+)\])?"
)
_HOLDS_RE = re.compile(r"#\s*dukecheck:\s*holds\s+([^#]+?)\s*$")


class Finding:
    """One ``file:line: CODE message`` result with a line-stable key."""

    __slots__ = ("code", "rel", "line", "message", "detail")

    def __init__(self, code: str, rel: str, line: int, message: str,
                 detail: str):
        self.code = code
        self.rel = rel
        self.line = line
        self.message = message
        # stable identifier for baseline matching (never a line number)
        self.detail = detail

    @property
    def key(self) -> str:
        return f"{self.code} {self.rel} :: {self.detail}"

    def render(self) -> str:
        return f"{self.rel}:{self.line}: {self.code} {self.message}"


class Module:
    """One parsed source file plus its comment-derived metadata."""

    def __init__(self, path: Path, rel: str):
        self.path = path
        self.rel = rel
        self.source = path.read_text(encoding="utf-8")
        self.lines = self.source.splitlines()
        self.tree = ast.parse(self.source, filename=str(path))
        # line -> set of suppressed codes ({"*"} suppresses everything)
        self.suppressions: Dict[int, Set[str]] = {}
        # line -> lock expression the surrounding def asserts is held
        self.holds: Dict[int, str] = {}
        for i, text in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(text)
            if m:
                codes = m.group(1)
                self.suppressions[i] = (
                    {c.strip() for c in codes.split(",") if c.strip()}
                    if codes else {"*"}
                )
            m = _HOLDS_RE.search(text)
            if m:
                self.holds[i] = m.group(1).strip()

    def suppressed(self, line: int, code: str) -> bool:
        codes = self.suppressions.get(line)
        if not codes:
            return False
        return "*" in codes or code in codes or code[:3] in codes


def load_modules(root: Path, subdir: str = PACKAGE) -> List[Module]:
    base = root / subdir
    mods = []
    for path in sorted(base.rglob("*.py")):
        rel = path.relative_to(root).as_posix()
        mods.append(Module(path, rel))
    return mods


def filter_suppressed(mods_by_rel: Dict[str, Module],
                      findings: Iterable[Finding]) -> List[Finding]:
    out = []
    for f in findings:
        mod = mods_by_rel.get(f.rel)
        if mod is not None and mod.suppressed(f.line, f.code):
            continue
        out.append(f)
    return out


# -- baseline -----------------------------------------------------------------


def load_baseline(path: Path) -> Dict[str, str]:
    """``{key: justification}`` from baseline.txt (``key  # justification``
    lines; blank lines and full-line comments skipped)."""
    out: Dict[str, str] = {}
    if not path.exists():
        return out
    for raw in path.read_text(encoding="utf-8").splitlines():
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        key, _, why = line.partition("  #")
        out[key.strip()] = why.strip()
    return out


def apply_baseline(findings: Sequence[Finding], baseline: Dict[str, str]):
    """Split findings into (new, baselined) and report stale entries.

    Returns ``(new_findings, stale_keys)`` — both must be empty for a
    passing run: stale entries mean the violation was fixed, so the
    baseline must shrink to match (delete the line), keeping it honest.
    """
    keys = {f.key for f in findings}
    new = [f for f in findings if f.key not in baseline]
    stale = [k for k in baseline if k not in keys]
    return new, stale


# -- small AST helpers shared by the checkers ---------------------------------


def expr_text(node: ast.AST) -> str:
    """Canonical source text for guard/lock expressions (``self._cv``)."""
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover - unparse covers all our exprs
        return ""


def receiver_name(node: ast.expr) -> Optional[str]:
    """The variable/attribute name an attribute hangs off: for
    ``wl.lock`` -> ``wl``; for ``self.link_database.commit`` ->
    ``link_database``; for bare ``self.x`` -> ``self``."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        if isinstance(node.value, ast.Name) and node.value.id == "self":
            return node.attr if node.attr else None
        return receiver_name(node.value) or node.attr
    if isinstance(node, ast.Call):
        return receiver_name(node.func)
    return None
