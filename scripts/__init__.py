# scripts/ is a namespace for repo tooling; the __init__ makes
# `python -m scripts.dukecheck` work from a checkout without installing
# anything (the dukecheck suite is stdlib-only by design).
