# Serving image — the reference's packaging shape (Dockerfile:1-8: shaded
# jar on java:8-jre-alpine, EXPOSE 4567) re-expressed for the TPU build.
# Base image must provide python>=3.10 with jax wheels matching the target
# accelerator (e.g. a Cloud TPU VM base); pinned here to the generic python
# image for CPU-only smoke runs.
FROM python:3.11-slim

RUN apt-get update && apt-get install -y --no-install-recommends g++ \
    && rm -rf /var/lib/apt/lists/*

WORKDIR /srv/duke-tpu
COPY pyproject.toml README.md ./
COPY sesam_duke_microservice_tpu ./sesam_duke_microservice_tpu
RUN pip install --no-cache-dir .

# build the native comparator library now, while site-packages is still
# writable — at runtime the unprivileged user could not compile it and the
# service would silently fall back to the pure-Python comparators
RUN python -c "from sesam_duke_microservice_tpu import native; assert native.available()"

# the reference creates this user but never switches to it (quirk Q8);
# deliberately fixed: run unprivileged — with a writable /data, which the
# default config's dataFolder points at (root-owned otherwise)
RUN useradd --system --create-home sesam \
    && mkdir -p /data && chown sesam:sesam /data
USER sesam

# durable state (lucene-index equivalent + link DB) lives under /data in
# the default config, as in the reference (testdukeconfig.xml:7)
VOLUME /data
EXPOSE 4567

# CONFIG_STRING / THREADS / PROFILE / MIN_RELEVANCE / FUZZY_SEARCH /
# MAX_SEARCH_HITS / ONE_TO_ONE env vars are honored as in the reference
ENTRYPOINT ["python", "-m", "sesam_duke_microservice_tpu.service"]
