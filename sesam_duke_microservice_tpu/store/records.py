"""Durable host-side record store — the framework's source of truth.

The reference's durable record state is its on-disk Lucene index, opened in
APPEND mode so a restarted container resumes where it left off
(IncrementalLuceneDatabase.java:233-244; paths wired at App.java:331-341,
452-462).  The TPU-native split is different (SURVEY.md section 7 "State"):
records persist here, in a host SQLite table keyed by the synthesized
``ID`` property; the blocking index — host inverted index or HBM-resident
device corpus — is a *rebuildable cache* replayed from this store at
startup.  Re-putting an existing id replaces the previous row, matching
Lucene's delete-then-readd (IncrementalLuceneDatabase.java:507-517).
"""

from __future__ import annotations

import json
import sqlite3
import threading
from typing import Dict, Iterator, List, Optional, Sequence

from ..core.records import Record
from ..utils.sqlite import SqliteConnectionPool


class RecordStore:
    """Interface: durable map ``record_id -> Record`` with replay."""

    def put(self, record: Record) -> None:
        raise NotImplementedError

    def put_many(self, records: Sequence[Record]) -> None:
        for record in records:
            self.put(record)

    def get(self, record_id: str) -> Optional[Record]:
        raise NotImplementedError

    def all_records(self) -> Iterator[Record]:
        """Iterate every stored record in insertion (rowid) order."""
        raise NotImplementedError

    def count(self) -> int:
        raise NotImplementedError

    def close(self) -> None:
        pass


class InMemoryRecordStore(RecordStore):
    """Non-durable store; the counterpart of Lucene's RAMDirectory fallback
    (IncrementalLuceneDatabase.java:218-231, used when no path is set)."""

    def __init__(self):
        self._records: Dict[str, Record] = {}
        self._lock = threading.Lock()

    def put(self, record: Record) -> None:
        rid = record.record_id
        if rid is None:
            raise ValueError("record has no ID property")
        with self._lock:
            # preserve replay order on replace, like a rowid reinsert
            self._records.pop(rid, None)
            self._records[rid] = record

    def get(self, record_id: str) -> Optional[Record]:
        with self._lock:
            return self._records.get(record_id)

    def all_records(self) -> Iterator[Record]:
        with self._lock:
            snapshot = list(self._records.values())
        return iter(snapshot)

    def count(self) -> int:
        with self._lock:
            return len(self._records)


class SqliteRecordStore(RecordStore):
    """SQLite-backed durable store (one row per record, JSON payload).

    WAL mode so the single-writer/concurrent-reader discipline of the
    service layer (one lock per workload, readers with 1 s timeout —
    App.java:96,145,718-725) maps cleanly onto SQLite's locking.
    """

    def __init__(self, path: str):
        self.path = path
        self._pool = SqliteConnectionPool(path)
        with self._conn() as conn:
            conn.execute(
                "CREATE TABLE IF NOT EXISTS records ("
                " id TEXT PRIMARY KEY,"
                " data TEXT NOT NULL)"
            )

    def _conn(self) -> sqlite3.Connection:
        return self._pool.conn()

    @staticmethod
    def _encode(record: Record) -> tuple:
        rid = record.record_id
        if rid is None:
            raise ValueError("record has no ID property")
        return rid, json.dumps(record.to_dict(), separators=(",", ":"))

    def put(self, record: Record) -> None:
        self.put_many([record])

    def put_many(self, records: Sequence[Record]) -> None:
        rows = [self._encode(r) for r in records]
        conn = self._conn()
        with conn:
            # REPLACE deletes-then-inserts under the hood, assigning a fresh
            # rowid so replay order tracks last write — mirroring Lucene's
            # delete-then-readd on reindex; one transaction per batch, and
            # duplicate ids within a batch resolve to the last occurrence
            conn.executemany(
                "INSERT OR REPLACE INTO records (id, data) VALUES (?, ?)", rows
            )

    def get(self, record_id: str) -> Optional[Record]:
        row = self._conn().execute(
            "SELECT data FROM records WHERE id = ?", (record_id,)
        ).fetchone()
        return self._decode(row[0]) if row else None

    def all_records(self) -> Iterator[Record]:
        for (data,) in self._conn().execute(
            "SELECT data FROM records ORDER BY rowid"
        ):
            yield self._decode(data)

    def count(self) -> int:
        return self._conn().execute("SELECT COUNT(*) FROM records").fetchone()[0]

    def close(self) -> None:
        self._pool.close()

    @staticmethod
    def _decode(data: str) -> Record:
        values: Dict[str, List[str]] = json.loads(data)
        return Record(values)
