"""Durable host-side record store — the framework's source of truth.

The reference's durable record state is its on-disk Lucene index, opened in
APPEND mode so a restarted container resumes where it left off
(IncrementalLuceneDatabase.java:233-244; paths wired at App.java:331-341,
452-462).  The TPU-native split is different (SURVEY.md section 7 "State"):
records persist here, in a host SQLite table keyed by the synthesized
``ID`` property; the blocking index — host inverted index or HBM-resident
device corpus — is a *rebuildable cache* replayed from this store at
startup.  Re-putting an existing id replaces the previous row, matching
Lucene's delete-then-readd (IncrementalLuceneDatabase.java:507-517).
"""

from __future__ import annotations

import hashlib
import json
import sqlite3
import threading
from typing import Dict, Iterator, List, Optional, Sequence

from ..core.records import Record
from ..utils.sqlite import SqliteConnectionPool

_HASH_BYTES = 32


def _row_digest(rid: str, data: str) -> bytes:
    """Canonical per-record digest over the stored serialization."""
    h = hashlib.sha256()
    h.update(rid.encode("utf-8", "surrogatepass"))
    h.update(b"\x00")
    h.update(data.encode("utf-8", "surrogatepass"))
    return h.digest()


def serialize_record(record: Record) -> str:
    """THE canonical record serialization — the store row payload AND the
    digest input share this one function, so the two can never drift.
    Core Records serialize their live value dict directly (json.dumps
    only reads it; ``to_dict``'s defensive copy was a measurable slice of
    ingest at 10^5-row slabs); byte-identical either way."""
    values = (record._values if type(record) is Record
              else record.to_dict())
    return json.dumps(values, separators=(",", ":"))


def record_digest(record: Record) -> bytes:
    """``_row_digest`` of a live Record — the SAME bytes the store folds
    for its serialized row, so an index-side incremental hash and the
    store's incremental hash agree exactly when (and only when) their
    record sets agree.  Memoized on core Records only (invalidated by
    ``add_value``; ``get_values`` returns copies so no mutation bypasses
    it): the persistent ingest path digests each record for the store
    row AND the index fold.  Foreign record-like objects are never
    cached — nothing would invalidate them."""
    memoizable = type(record) is Record
    if memoizable and record._digest_cache is not None:
        return record._digest_cache
    rid = record.record_id
    if rid is None:
        raise ValueError("record has no ID property")
    digest = _row_digest(rid, serialize_record(record))
    if memoizable:
        record._digest_cache = digest
    return digest


def _xor(a: bytes, b: bytes) -> bytes:
    # int-wide XOR: ~10x the per-byte generator (this runs twice per
    # record on the ingest path)
    return (int.from_bytes(a, "big") ^ int.from_bytes(b, "big")).to_bytes(
        _HASH_BYTES, "big"
    )


def xor_fold(a: bytes, b: bytes) -> bytes:
    """Public alias of the hash combiner (order-independent fold)."""
    return _xor(a, b)


EMPTY_CONTENT_HASH = bytes(_HASH_BYTES)


class RecordStore:
    """Interface: durable map ``record_id -> Record`` with replay."""

    def put(self, record: Record) -> None:
        raise NotImplementedError

    def put_many(self, records: Sequence[Record]) -> None:
        for record in records:
            self.put(record)

    def get(self, record_id: str) -> Optional[Record]:
        raise NotImplementedError

    def all_records(self) -> Iterator[Record]:
        """Iterate every stored record in insertion (rowid) order."""
        raise NotImplementedError

    def count(self) -> int:
        raise NotImplementedError

    def content_hash(self) -> Optional[str]:
        """Order-independent digest of the store's full content, or None
        when the backend doesn't maintain one.  Durable backends keep it
        INCREMENTALLY (XOR of per-row digests, updated inside each write
        transaction) so the snapshot staleness guard costs O(1) at save
        and load instead of re-hashing the whole corpus — the O(corpus)
        rehash dominated restart at 10M rows (VERDICT r2 #5)."""
        return None

    def all_ids(self) -> Iterator[str]:
        """Every stored record id (no payload decode)."""
        for record in self.all_records():
            yield record.record_id

    def row_digests(self) -> Iterator[tuple]:
        """(record_id, canonical per-record digest) for every stored row —
        the same bytes ``record_digest`` yields for the live record, so a
        consumer can key caches by content without decoding records.
        Durable backends override to fold the raw stored serialization
        directly (the feature-cache snapshot pre-warm walks this at
        restart; a JSON decode per row would defeat the lazy restore)."""
        for record in self.all_records():
            yield record.record_id, record_digest(record)

    def close(self) -> None:
        pass


class LazyRecordMap:
    """Dict-like ``record_id -> Record`` view over a store, decoded on
    demand.

    The device index's host record mirror exists for host-exact
    finalization, feed resolution, and transforms — all of which touch a
    tiny, hot subset of records per request.  Materializing it eagerly is
    what made 10M-row restart take ~24 minutes and ~60 GB of host RAM
    (measured, benchmarks/restart_bench.py): 10M JSON rows decoded into
    Python Record objects on one core.  This map keeps only the id set in
    memory (~100 B/row) as the membership authority, decodes rows from
    SQLite on first touch, and holds every decoded/written record in a
    BOUNDED LRU — writes also land in the LRU (the store already has the
    row: the workload persists before indexing), so memory stays bounded
    for the process lifetime, not just across the restart.
    """

    _LRU_CAP = 200_000

    def __init__(self, store: RecordStore):
        import collections

        self._store = store
        self._ids = set(store.all_ids())
        self._lru: "collections.OrderedDict[str, Record]" = (
            collections.OrderedDict()
        )

    def _cache(self, rid: str, record: Record) -> None:
        self._lru[rid] = record
        self._lru.move_to_end(rid)
        if len(self._lru) > self._LRU_CAP:
            self._lru.popitem(last=False)

    def get(self, rid: str, default=None) -> Optional[Record]:
        if rid not in self._ids:
            # membership authority: a popped id must NOT resurrect from
            # the store row that may still exist there
            return default
        record = self._lru.get(rid)
        if record is not None:
            self._lru.move_to_end(rid)
            return record
        record = self._store.get(rid)
        if record is None:  # store raced ahead of _ids; treat as missing
            return default
        self._cache(rid, record)
        return record

    def __getitem__(self, rid: str) -> Record:
        record = self.get(rid)
        if record is None:
            raise KeyError(rid)
        return record

    def __setitem__(self, rid: str, record: Record) -> None:
        self._ids.add(rid)
        self._cache(rid, record)

    def pop(self, rid: str, default=None):
        record = self.get(rid, default)
        self._ids.discard(rid)
        self._lru.pop(rid, None)
        return record

    def discard(self, rid: str) -> None:
        """Remove without decoding (pop pays a store read just to return
        a value the lazy-mode delete path never uses)."""
        self._ids.discard(rid)
        self._lru.pop(rid, None)

    def __contains__(self, rid) -> bool:
        return rid in self._ids

    def __len__(self) -> int:
        return len(self._ids)

    def __iter__(self):
        return iter(self._ids)

    def keys(self):
        return iter(self._ids)

    def values(self):
        """Streaming decode in id order (memory-bounded by the LRU) —
        only rare bulk paths (value-slot rebuild) walk this."""
        for rid in list(self._ids):
            record = self.get(rid)
            if record is not None:
                yield record

    def bulk_values(self):
        """Corpus-scale streaming walk via the store's bulk cursor —
        ``values()`` pays one point SELECT per id, which turns a 10M-row
        walk (multi-host bootstrap streaming) into hours.  Rows whose id
        has been popped from the membership authority are skipped; the
        store is never BEHIND the map for live ids (the workload persists
        before indexing), so the cursor view is current."""
        for record in self._store.all_records():
            if record.record_id in self._ids:
                yield record

    def prefetch(self, rids) -> None:
        """Warm the LRU with a batch of ids in few store round trips —
        page-sized feed resolution would otherwise pay one SELECT per
        link endpoint under the workload lock.  Bounded: at most
        ``_LRU_CAP`` ids (beyond that, earlier entries would evict before
        use), fetched in chunks so no single call materializes an
        unbounded record dict."""
        want = [
            rid for rid in rids
            if rid in self._ids and rid not in self._lru
        ]
        if not want:
            return
        want = want[: self._LRU_CAP]
        get_many = getattr(self._store, "get_many", None)
        if get_many is None:
            return  # per-id gets will serve (in-memory stores are cheap)
        for start in range(0, len(want), 10_000):
            for rid, record in get_many(want[start:start + 10_000]).items():
                self._cache(rid, record)


class InMemoryRecordStore(RecordStore):
    """Non-durable store; the counterpart of Lucene's RAMDirectory fallback
    (IncrementalLuceneDatabase.java:218-231, used when no path is set)."""

    def __init__(self):
        self._records: Dict[str, Record] = {}
        self._lock = threading.Lock()

    def put(self, record: Record) -> None:
        rid = record.record_id
        if rid is None:
            raise ValueError("record has no ID property")
        with self._lock:
            # preserve replay order on replace, like a rowid reinsert
            self._records.pop(rid, None)
            self._records[rid] = record

    def get(self, record_id: str) -> Optional[Record]:
        with self._lock:
            return self._records.get(record_id)

    def all_records(self) -> Iterator[Record]:
        with self._lock:
            snapshot = list(self._records.values())
        return iter(snapshot)

    def count(self) -> int:
        with self._lock:
            return len(self._records)


class SqliteRecordStore(RecordStore):
    """SQLite-backed durable store (one row per record, JSON payload).

    WAL mode so the single-writer/concurrent-reader discipline of the
    service layer (one lock per workload, readers with 1 s timeout —
    App.java:96,145,718-725) maps cleanly onto SQLite's locking.
    """

    def __init__(self, path: str):
        self.path = path
        self._pool = SqliteConnectionPool(path)
        self._hash_lock = threading.Lock()
        with self._conn() as conn:
            conn.execute(
                "CREATE TABLE IF NOT EXISTS records ("
                " id TEXT PRIMARY KEY,"
                " data TEXT NOT NULL)"
            )
            conn.execute(
                "CREATE TABLE IF NOT EXISTS meta ("
                " key TEXT PRIMARY KEY,"
                " value TEXT NOT NULL)"
            )
        self._hash = self._load_or_build_hash()

    def _load_or_build_hash(self) -> bytes:
        conn = self._conn()
        row = conn.execute(
            "SELECT value FROM meta WHERE key = 'content_hash'"
        ).fetchone()
        if row is not None:
            return bytes.fromhex(row[0])
        # one-time migration for stores created before the incremental
        # hash existed: fold every existing row, then persist
        acc = bytes(_HASH_BYTES)
        for rid, data in conn.execute("SELECT id, data FROM records"):
            acc = _xor(acc, _row_digest(rid, data))
        with conn:
            conn.execute(
                "INSERT OR REPLACE INTO meta (key, value) VALUES "
                "('content_hash', ?)", (acc.hex(),),
            )
        return acc

    def _conn(self) -> sqlite3.Connection:
        return self._pool.conn()

    @staticmethod
    def _encode(record: Record) -> tuple:
        rid = record.record_id
        if rid is None:
            raise ValueError("record has no ID property")
        return rid, serialize_record(record)

    def put(self, record: Record) -> None:
        self.put_many([record])

    def put_many(self, records: Sequence[Record]) -> None:
        # duplicate ids within a batch resolve to the last occurrence
        # (REPLACE semantics); dedupe up front so the hash folds each id
        # exactly once
        by_id = {}
        rec_by_id = {}
        for r in records:
            rid, data = self._encode(r)
            by_id[rid] = data
            rec_by_id[rid] = r
        rows = list(by_id.items())
        if not rows:
            return
        conn = self._conn()
        with self._hash_lock, conn:
            # fold out the rows being replaced, fold in the new ones —
            # the running hash and the rows commit in ONE transaction so
            # a crash can never leave them out of sync
            acc = self._hash
            ids = [rid for rid, _ in rows]
            for start in range(0, len(ids), 450):  # host-parameter cap
                chunk = ids[start:start + 450]
                marks = ",".join("?" * len(chunk))
                for rid, data in conn.execute(
                    f"SELECT id, data FROM records WHERE id IN ({marks})",
                    chunk,
                ):
                    acc = _xor(acc, _row_digest(rid, data))
            for rid, data in rows:
                digest = _row_digest(rid, data)
                acc = _xor(acc, digest)
                # seed the record's memo: the index folds the same digest
                # right after this put (engine.device_matcher); safe
                # because the row data IS serialize_record(record)
                record = rec_by_id[rid]
                if type(record) is Record:
                    record._digest_cache = digest
            # REPLACE deletes-then-inserts under the hood, assigning a fresh
            # rowid so replay order tracks last write — mirroring Lucene's
            # delete-then-readd on reindex; one transaction per batch
            conn.executemany(
                "INSERT OR REPLACE INTO records (id, data) VALUES (?, ?)",
                rows,
            )
            conn.execute(
                "INSERT OR REPLACE INTO meta (key, value) VALUES "
                "('content_hash', ?)", (acc.hex(),),
            )
            self._hash = acc

    def content_hash(self) -> str:
        with self._hash_lock:
            return self._hash.hex()

    def all_ids(self) -> Iterator[str]:
        for (rid,) in self._conn().execute("SELECT id FROM records"):
            yield rid

    def row_digests(self) -> Iterator[tuple]:
        # raw rows, no JSON decode: the stored payload IS
        # serialize_record(record), so _row_digest over it is
        # byte-identical to record_digest of the live record
        for rid, data in self._conn().execute(
            "SELECT id, data FROM records"
        ):
            yield rid, _row_digest(rid, data)

    def get(self, record_id: str) -> Optional[Record]:
        row = self._conn().execute(
            "SELECT data FROM records WHERE id = ?", (record_id,)
        ).fetchone()
        return self._decode(row[0]) if row else None

    def get_many(self, record_ids) -> Dict[str, Record]:
        """Batched lookup (one query per 450-id chunk) — the feed's page
        resolution touches up to 2 x page_size records at once."""
        ids = [rid for rid in record_ids]
        out: Dict[str, Record] = {}
        conn = self._conn()
        for start in range(0, len(ids), 450):  # host-parameter cap
            chunk = ids[start:start + 450]
            marks = ",".join("?" * len(chunk))
            for rid, data in conn.execute(
                f"SELECT id, data FROM records WHERE id IN ({marks})", chunk
            ):
                out[rid] = self._decode(data)
        return out

    def all_records(self) -> Iterator[Record]:
        for (data,) in self._conn().execute(
            "SELECT data FROM records ORDER BY rowid"
        ):
            yield self._decode(data)

    def count(self) -> int:
        return self._conn().execute("SELECT COUNT(*) FROM records").fetchone()[0]

    def close(self) -> None:
        self._pool.close()

    @staticmethod
    def _decode(data: str) -> Record:
        values: Dict[str, List[str]] = json.loads(data)
        return Record(values)
