"""Durable host-side record store.

The framework's source of truth for indexed records (SURVEY.md section 7
"State"): the reference's durable state is its on-disk Lucene index
(IncrementalLuceneDatabase.java:233-244, opened in APPEND mode so a
restarted container resumes where it left off).  Here durability is split
TPU-natively: records persist in a host SQLite store; the blocking index
(host inverted index or device-resident corpus) is a rebuildable cache
replayed from the store at startup.
"""

from .records import InMemoryRecordStore, RecordStore, SqliteRecordStore

__all__ = ["InMemoryRecordStore", "RecordStore", "SqliteRecordStore"]
