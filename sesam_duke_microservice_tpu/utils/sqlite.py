"""Thread-aware SQLite connection management shared by the durable stores.

Both durable backends (links.sqlite.SqliteLinkDatabase and
store.records.SqliteRecordStore) serve the HTTP layer's threading model:
one writer at a time per workload but many reader/writer *threads* over the
process lifetime (ThreadingHTTPServer spawns one per connection).  SQLite
connections are cheap but per-thread, so the pool hands out one connection
per thread, prunes connections whose owning thread has exited, and tracks
the rest so close() releases every handle — the reference leaks its
Lucene/H2 handles on hot reload (SURVEY.md quirk Q7) and this is half of
that fix.

``':memory:'`` gets a single shared serialized connection instead (a
per-thread ``:memory:`` connection would be a *different* empty database
per thread); the sqlite3 module serializes access when the underlying
library is built threadsafe, which CPython requires since 3.11.
"""

from __future__ import annotations

import os
import sqlite3
import threading
import weakref
from typing import Dict, Optional, Tuple


class SqliteConnectionPool:
    def __init__(self, path: str,
                 pragmas: Tuple[str, ...] = ("journal_mode=WAL",
                                             "synchronous=NORMAL")):
        self.path = path
        self._pragmas = pragmas
        self._lock = threading.Lock()
        # thread ident -> (weakref to thread, connection); idents can be
        # reused after a thread dies, so entries are replaced (and their
        # connections closed) on collision
        self._conns: Dict[int, Tuple[weakref.ref, sqlite3.Connection]] = {}
        self._closed = False
        self._local = threading.local()
        self._shared: Optional[sqlite3.Connection] = None
        if path == ":memory:":
            self._shared = sqlite3.connect(path, check_same_thread=False)
        else:
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)

    def conn(self) -> sqlite3.Connection:
        if self._closed:
            raise sqlite3.ProgrammingError(
                f"connection pool for {self.path!r} is closed"
            )
        if self._shared is not None:
            return self._shared
        conn = getattr(self._local, "conn", None)
        if conn is None:
            # check_same_thread=False so close()/pruning can release a
            # connection from a different thread; usage stays per-thread
            conn = sqlite3.connect(self.path, check_same_thread=False)
            for pragma in self._pragmas:
                conn.execute("PRAGMA " + pragma)
            thread = threading.current_thread()
            with self._lock:
                if self._closed:
                    conn.close()
                    raise sqlite3.ProgrammingError(
                        f"connection pool for {self.path!r} is closed"
                    )
                self._prune_dead_locked()
                stale = self._conns.pop(thread.ident, None)
                self._conns[thread.ident] = (weakref.ref(thread), conn)
            if stale is not None:
                self._close_quietly(stale[1])
            self._local.conn = conn
        return conn

    def _prune_dead_locked(self) -> None:
        """Drop connections owned by exited threads (called with _lock)."""
        dead = [ident for ident, (ref, _) in self._conns.items()
                if (t := ref()) is None or not t.is_alive()]
        for ident in dead:
            _, conn = self._conns.pop(ident)
            self._close_quietly(conn)

    def close(self) -> None:
        with self._lock:
            self._closed = True
            conns = [c for _, c in self._conns.values()]
            self._conns = {}
        if self._shared is not None:
            self._shared.close()
            self._shared = None
        for conn in conns:
            self._close_quietly(conn)

    @staticmethod
    def _close_quietly(conn: sqlite3.Connection) -> None:
        try:
            conn.close()
        except sqlite3.Error:
            pass
