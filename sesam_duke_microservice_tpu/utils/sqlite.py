"""Thread-aware SQLite connection management shared by the durable stores.

Both durable backends (links.sqlite.SqliteLinkDatabase and
store.records.SqliteRecordStore) serve the HTTP layer's threading model:
one writer at a time per workload but many reader/writer *threads* over the
process lifetime (ThreadingHTTPServer spawns one per connection).  SQLite
connections are cheap but per-thread, so the pool hands out one connection
per thread and tracks them all, guaranteeing close() releases every handle
— the reference leaks its Lucene/H2 handles on hot reload (SURVEY.md quirk
Q7) and this is half of that fix.

``':memory:'`` gets a single shared serialized connection instead (a
per-thread ``:memory:`` connection would be a *different* empty database
per thread); the sqlite3 module serializes access when the underlying
library is built threadsafe, which CPython requires since 3.11.
"""

from __future__ import annotations

import os
import sqlite3
import threading
from typing import Optional, Tuple


class SqliteConnectionPool:
    def __init__(self, path: str,
                 pragmas: Tuple[str, ...] = ("journal_mode=WAL",
                                             "synchronous=NORMAL")):
        self.path = path
        self._pragmas = pragmas
        self._lock = threading.Lock()
        self._conns: list = []
        self._closed = False
        self._local = threading.local()
        self._shared: Optional[sqlite3.Connection] = None
        if path == ":memory:":
            self._shared = sqlite3.connect(path, check_same_thread=False)
        else:
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)

    def conn(self) -> sqlite3.Connection:
        if self._closed:
            raise sqlite3.ProgrammingError(
                f"connection pool for {self.path!r} is closed"
            )
        if self._shared is not None:
            return self._shared
        conn = getattr(self._local, "conn", None)
        if conn is None:
            # check_same_thread=False so close() can release every tracked
            # connection from the reload thread; usage stays per-thread
            conn = sqlite3.connect(self.path, check_same_thread=False)
            for pragma in self._pragmas:
                conn.execute("PRAGMA " + pragma)
            with self._lock:
                if self._closed:
                    conn.close()
                    raise sqlite3.ProgrammingError(
                        f"connection pool for {self.path!r} is closed"
                    )
                self._conns.append(conn)
            self._local.conn = conn
        return conn

    def close(self) -> None:
        with self._lock:
            self._closed = True
            conns, self._conns = self._conns, []
        if self._shared is not None:
            self._shared.close()
            self._shared = None
        for conn in conns:
            try:
                conn.close()
            except sqlite3.Error:
                pass
