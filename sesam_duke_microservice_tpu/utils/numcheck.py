"""Runtime certified-numerics sanitizer (``DUKE_NUMCHECK=1``, ISSUE 13).

The static layers (dukecheck's ``numerics``/``budgets``/``hlocheck``
gates) prove the EFT discipline is written, the budgets cover their
derivations, and the compiler honors the barriers — for the programs
and flag combos the gates compile.  This module is the dynamic leg that
validates the whole composed chain on *live traffic*, the same
static+sanitizer architecture ``DUKE_LOCKCHECK`` gave the lock order:

  * every **certified event** is shadow-checked for free (the finalize
    path already paid the host ``compare`` for its bit-exact
    confidence): the oracle must actually emit an event, and the dd
    total logit must sit within the certified margin of the oracle's;
  * a sampled fraction (``DUKE_NUMCHECK_SAMPLE``, default 1.0 — the CI
    leg checks everything; production can dial it down) of **certified
    rejects** pays one extra shadow ``compare``: the oracle must NOT
    emit, and the margin bound must hold.

Any certified-vs-oracle class disagreement or margin-bound violation is
recorded and **fails the run**: ``tests/conftest.py`` fails the session
at exit exactly like the lock sanitizer, and every check tail-latches
into a :class:`telemetry.rings.LatchedRing` (violations are latched, so
they survive any sample rate and any ring pressure — the decision-ring
precedent).

The margin-bound check reconstructs the oracle's total logit from its
returned probability (``compare`` is ``sigmoid(sum of clamped
per-property logits)`` — core.bayes), which is only well-conditioned in
the interior: at |logit| = L the reconstruction loses ~``e^L * u64``,
which crosses the ~1e-10 dd margins near L = 14.  Checks outside
``|logit| <= 10`` therefore validate the CLASS only — precisely the
regime where classes are decided by enormous slack anyway.

Thread model: finalize workers call ``observe_*`` concurrently.  The
violations list is append-only (GIL-atomic), the ring carries its own
lock (``LatchedRing.lock`` — an already-modeled hierarchy leaf), and
the sampling counter rides ``itertools.count`` (atomic ``__next__``).
No new lock exists (dukecheck's hierarchy stays at 39 locks).
"""

from __future__ import annotations

import itertools
import logging
import math
from typing import List, Optional

from ..telemetry.env import env_flag, env_float
from ..telemetry.rings import LatchedRing

logger = logging.getLogger("numcheck")

# interior band for the margin-bound leg (see module docstring) and the
# reconstruction allowance inside it (e^10 * u64 * small-constant slack)
_MARGIN_CHECK_LOGIT = 10.0
_RECON_SLACK = 1e-11

_RING_CAPACITY = 256

_counter = itertools.count()
_checked = itertools.count()  # per-observation ring keys
_observed = 0                 # lifetime observations (approximate stat)
_violations: List[str] = []   # append-only; GIL-atomic
_ring = LatchedRing(_RING_CAPACITY)


def enabled() -> bool:
    return env_flag("DUKE_NUMCHECK", False)


def sample_fraction() -> float:
    frac = env_float("DUKE_NUMCHECK_SAMPLE", 1.0)
    return min(max(frac, 0.0), 1.0)


def take_sample(frac: Optional[float] = None) -> bool:
    """Deterministic counter-stride sampling — no RNG on the hot path
    (and no trace-time nondeterminism if this ever nears jit code)."""
    if frac is None:
        frac = sample_fraction()
    if frac <= 0.0:
        return False
    n = next(_counter)
    return math.floor((n + 1) * frac) > math.floor(n * frac)


def _logit(p: float) -> float:
    eps = 1e-10
    p = min(max(p, eps), 1.0 - eps)
    return math.log(p / (1.0 - p))


def _record(kind: str, id1: str, id2: str, total: float, prob: float,
            verdict: Optional[str]) -> None:
    global _observed
    _observed += 1  # approximate under races — a stat, not a gate
    key = f"{next(_checked)}:{id1}:{id2}"
    _ring.put(key, {
        "kind": kind, "id1": id1, "id2": id2,
        "dd_total_logit": total, "oracle_probability": prob,
        "violation": verdict,
    }, remarkable=verdict is not None, nbytes=0)
    if verdict is not None:
        line = (f"{verdict} [{kind}] pair ({id1}, {id2}): "
                f"dd_total={total!r} oracle_p={prob!r}")
        _violations.append(line)
        logger.error("numcheck: %s", line)


def _emits(prob: float, threshold: float,
           maybe: Optional[float]) -> bool:
    if prob > threshold:
        return True
    return maybe is not None and maybe != 0.0 and prob > maybe


def observe(kind: str, id1: str, id2: str, total: float, prob: float,
            threshold: float, maybe: Optional[float],
            margin: float) -> None:
    """Validate one certified verdict against the oracle's probability.

    ``kind`` is ``"reject"`` (certified no-event; ``prob`` came from the
    shadow compare) or ``"event"`` (certified event; ``prob`` is the
    confidence the finalize path fetched anyway).  ``total`` is the dd
    device logit plus the exact host-fallback logits — the quantity the
    certification bounds classified; ``margin`` is the certified dd
    margin plus threshold slack the bounds charged.
    """
    verdict = None
    emits = _emits(prob, threshold, maybe)
    if kind == "reject" and emits:
        verdict = ("CERTIFIED-REJECT DISAGREEMENT: oracle emits an "
                   "event the device certified impossible")
    elif kind == "event" and not emits:
        verdict = ("CERTIFIED-EVENT DISAGREEMENT: oracle emits nothing "
                   "for a device-certified event")
    else:
        oracle_logit = _logit(prob)
        if (abs(total) <= _MARGIN_CHECK_LOGIT
                and abs(oracle_logit) <= _MARGIN_CHECK_LOGIT
                and abs(total - oracle_logit) > margin + _RECON_SLACK):
            verdict = (f"MARGIN-BOUND VIOLATION: |dd - oracle| = "
                       f"{abs(total - oracle_logit):.3e} > certified "
                       f"{margin:.3e}")
    _record(kind, id1, id2, total, prob, verdict)


def violations() -> List[str]:
    return list(_violations)


def report() -> dict:
    return {
        "enabled": enabled(),
        "checked": _observed,
        "violations": list(_violations),
        "ring_entries": len(_ring),
        "recent": _ring.records(),
    }


def reset() -> None:
    """Test hook: clear recorded state (the injection tests must not
    leak their deliberate violations into the session gate)."""
    global _counter, _checked, _ring, _observed
    _violations.clear()
    _observed = 0
    _counter = itertools.count()
    _checked = itertools.count()
    _ring = LatchedRing(_RING_CAPACITY)
