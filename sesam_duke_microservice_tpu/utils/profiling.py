"""Device-timeline tracing hooks (the TPU equivalent of the reference's
``PROFILE=1`` -> ``Processor.setPerformanceProfiling`` per-phase timing,
App.java:239-244,345,466 — SURVEY.md section 5.1).

Two levels:

  * ``PROFILE=1`` — per-batch wall-clock logs + ProfileStats counters
    (engine.processor / engine.device_matcher), mirroring the reference's
    listener-level logging (IncrementalRecordLinkageMatchListener.java:42-52).
  * ``PROFILE_TRACE_DIR=/path`` — additionally capture ``jax.profiler``
    traces (XLA op timeline, HBM usage, fusion view in TensorBoard /
    xprof) for the first ``PROFILE_TRACE_BATCHES`` (default 3) scoring
    batches.  Bounded by default: traces are large and the service is
    long-running.
"""

from __future__ import annotations

import contextlib
import logging
import os
import threading

logger = logging.getLogger("profiling")

_lock = threading.Lock()
_traced_batches = 0


def trace_dir() -> str:
    return os.environ.get("PROFILE_TRACE_DIR", "")


def _trace_budget() -> int:
    try:
        return int(os.environ.get("PROFILE_TRACE_BATCHES", "3"))
    except ValueError:
        return 3


@contextlib.contextmanager
def trace_batch(label: str):
    """Wrap one scoring batch in a jax.profiler trace when enabled.

    No-op unless ``PROFILE_TRACE_DIR`` is set and the trace budget has not
    been spent.  Only profiler setup/teardown failures are swallowed (they
    log) — exceptions from the traced block itself propagate untouched, and
    tracing must never take down a batch.
    """
    global _traced_batches
    directory = trace_dir()
    if not directory:
        yield
        return
    with _lock:
        if _traced_batches >= _trace_budget():
            yield
            return
        _traced_batches += 1
        n = _traced_batches
    stack = contextlib.ExitStack()
    entered = False
    try:
        import jax

        stack.enter_context(jax.profiler.trace(directory))
        stack.enter_context(jax.profiler.TraceAnnotation(label))
        entered = True
    except Exception:
        # return the unused budget slot so later healthy batches still trace
        with _lock:
            _traced_batches -= 1
        logger.exception("device trace setup failed (batch continues)")
    try:
        yield
    finally:
        try:
            stack.close()
            if entered:
                logger.info("captured device trace %d/%d (%s) into %s",
                            n, _trace_budget(), label, directory)
        except Exception:
            logger.exception(
                "device trace teardown failed (batch continues)"
            )
