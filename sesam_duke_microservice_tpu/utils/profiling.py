"""Device-timeline tracing hooks (the TPU equivalent of the reference's
``PROFILE=1`` -> ``Processor.setPerformanceProfiling`` per-phase timing,
App.java:239-244,345,466 — SURVEY.md section 5.1).

Three levels:

  * ``PROFILE=1`` — per-batch wall-clock logs + ProfileStats counters
    (engine.processor / engine.device_matcher), mirroring the reference's
    listener-level logging (IncrementalRecordLinkageMatchListener.java:42-52).
  * ``PROFILE_TRACE_DIR=/path`` — additionally capture ``jax.profiler``
    traces (XLA op timeline, HBM usage, fusion view in TensorBoard /
    xprof) for the first ``PROFILE_TRACE_BATCHES`` (default 3) scoring
    batches.  Bounded by default: traces are large and the service is
    long-running.  The spent budget is resettable at runtime
    (``reset_trace_budget`` / ``POST /debug/profile/reset``) so a
    long-running service can re-capture after a config reload.
  * **on-demand capture** (ISSUE 2) — ``start_capture(seconds)`` /
    ``POST /debug/profile?seconds=N`` opens a ``jax.profiler`` trace NOW
    for N seconds, no restart and no env preconfiguration.  While a
    capture is live, tracing spans created with ``annotate=True``
    (engine phases) also enter ``jax.profiler.TraceAnnotation`` so the
    device timeline carries the request-trace names.
"""

from __future__ import annotations

import contextlib
import logging
import tempfile
import threading
import time
from typing import Any, Dict, Optional

from ..telemetry import tracing as _tracing
from ..telemetry.env import env_int, env_str

logger = logging.getLogger("profiling")

_lock = threading.Lock()
_traced_batches = 0


def trace_dir() -> str:
    return env_str("PROFILE_TRACE_DIR", "")


def _trace_budget() -> int:
    return env_int("PROFILE_TRACE_BATCHES", 3)


def reset_trace_budget() -> int:
    """Re-arm the PROFILE_TRACE_DIR batch-capture budget (the spent count
    used to be process-lifetime-once).  Returns the re-armed budget."""
    global _traced_batches
    with _lock:
        _traced_batches = 0
    return _trace_budget()


@contextlib.contextmanager
def trace_batch(label: str):
    """Wrap one scoring batch in a jax.profiler trace when enabled.

    No-op unless ``PROFILE_TRACE_DIR`` is set and the trace budget has not
    been spent.  Only profiler setup/teardown failures are swallowed (they
    log) — exceptions from the traced block itself propagate untouched, and
    tracing must never take down a batch.
    """
    global _traced_batches
    directory = trace_dir()
    if not directory:
        yield
        return
    with _lock:
        if _traced_batches >= _trace_budget():
            yield
            return
        _traced_batches += 1
        n = _traced_batches
    stack = contextlib.ExitStack()
    entered = False
    try:
        import jax

        stack.enter_context(jax.profiler.trace(directory))
        stack.enter_context(jax.profiler.TraceAnnotation(label))
        entered = True
    except Exception:
        # return the unused budget slot so later healthy batches still trace
        with _lock:
            _traced_batches -= 1
        logger.exception("device trace setup failed (batch continues)")
    try:
        yield
    finally:
        try:
            stack.close()
            if entered:
                logger.info("captured device trace %d/%d (%s) into %s",
                            n, _trace_budget(), label, directory)
        except Exception:
            logger.exception(
                "device trace teardown failed (batch continues)"
            )


# -- on-demand capture (POST /debug/profile) ---------------------------------

# seam for tests: the two jax.profiler touch points, monkeypatchable so
# endpoint smoke tests never spin a real profiler session
def profiler_start(logdir: str) -> None:
    import jax

    jax.profiler.start_trace(logdir)


def profiler_stop() -> None:
    import jax

    jax.profiler.stop_trace()


MAX_CAPTURE_SECONDS = 600.0

_capture_lock = threading.Lock()
_capture: Optional[Dict[str, Any]] = None


def capture_status() -> Optional[Dict[str, Any]]:
    """The live capture's public info, or None."""
    with _capture_lock:
        if _capture is None:
            return None
        info = {k: _capture[k] for k in
                ("dir", "seconds", "started_unix", "owner",
                 "deadline_unix")}
        info["remaining_seconds"] = round(
            max(0.0, _capture["until"] - time.monotonic()), 3)
        return info


def start_capture(seconds: float, logdir: Optional[str] = None,
                  owner: str = "app") -> Dict[str, Any]:
    """Open a ``jax.profiler`` capture NOW for ``seconds`` seconds.

    Generalizes the first-N-batches ``PROFILE_TRACE_DIR`` capture to any
    moment in a running service: a timer thread stops the capture, and
    while it is live the request-tracing layer bridges its engine phase
    spans into device TraceAnnotations.  The profiler is one per
    process but the serving planes (app / replica / federation) each
    expose the endpoint, so a second ``start_capture`` — from ANY plane
    — raises ``CaptureActiveError`` carrying the live capture's owner
    plane and deadline for the endpoint's 409 body; failures to start
    propagate to the caller (the endpoint answers 500) with no state
    latched.  ``owner`` names the requesting plane.
    """
    seconds = float(seconds)
    if not (0 < seconds <= MAX_CAPTURE_SECONDS):
        raise ValueError(
            f"capture seconds must be in (0, {MAX_CAPTURE_SECONDS:g}]"
        )
    global _capture
    with _capture_lock:
        if _capture is not None:
            raise CaptureActiveError(
                f"a device capture (owner={_capture['owner']}) is "
                f"already running into {_capture['dir']}",
                owner=_capture["owner"],
                deadline_unix=_capture["deadline_unix"],
                remaining_seconds=round(
                    max(0.0, _capture["until"] - time.monotonic()), 3),
            )
        directory = (logdir or trace_dir()
                     or tempfile.mkdtemp(prefix="duke-profile-"))
        profiler_start(directory)
        _tracing.set_device_annotations(True)
        timer = threading.Timer(seconds, stop_capture)
        timer.daemon = True
        _capture = {
            "dir": directory,
            "seconds": seconds,
            "started_unix": round(time.time(), 3),
            "deadline_unix": round(time.time() + seconds, 3),
            "until": time.monotonic() + seconds,
            "owner": owner,
            "timer": timer,
        }
        timer.start()
        logger.info("on-demand device capture started: %.3gs into %s "
                    "(owner=%s)", seconds, directory, owner)
        return {k: _capture[k] for k in
                ("dir", "seconds", "started_unix", "deadline_unix",
                 "owner")}


def stop_capture() -> Optional[Dict[str, Any]]:
    """End the live capture (timer callback; also callable early).
    Returns the finished capture's info, or None if none was live."""
    global _capture
    with _capture_lock:
        if _capture is None:
            return None
        done, _capture = _capture, None
        done.pop("until", None)
        timer = done.pop("timer", None)
        if timer is not None:
            timer.cancel()
        _tracing.set_device_annotations(False)
        try:
            profiler_stop()
            logger.info("on-demand device capture finished: %s",
                        done["dir"])
        except Exception:
            logger.exception("on-demand capture teardown failed")
            done["error"] = "profiler stop failed (see logs)"
        return done


class CaptureActiveError(RuntimeError):
    """A second ``start_capture`` while one is live (endpoint: 409).

    Carries the live capture's owner plane and deadline so the 409 body
    can say WHO holds the profiler and until when — a capture started
    through one plane must never swallow another plane's request with a
    misleading success."""

    def __init__(self, message: str, owner: Optional[str] = None,
                 deadline_unix: Optional[float] = None,
                 remaining_seconds: Optional[float] = None):
        super().__init__(message)
        self.owner = owner
        self.deadline_unix = deadline_unix
        self.remaining_seconds = remaining_seconds
