"""Crash-atomic small-file persistence — THE one copy of the dance.

The tmp-write + fsync + ``os.replace`` pattern grew three hand-rolled
copies (corpus snapshot save, partition map, migration state); the JSON
flavors now live here so a hardening fix lands once.  Beyond the
classic sequence this also fsyncs the parent DIRECTORY: ``os.replace``
is atomic against readers, but the rename itself lives in the directory
inode — without the directory sync a power cut can resurrect the OLD
file even though replace() returned.
"""

from __future__ import annotations

import contextlib
import json
import os


def atomic_write_json(path: str, doc) -> None:
    """Write ``doc`` as JSON at ``path`` such that every reader (and
    every restart) sees either the previous complete file or the new
    complete file — never a torn intermediate."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f)
        f.flush()
        os.fsync(f.fileno())
    _replace_and_sync_dir(tmp, path)


def atomic_write_bytes(path: str, data: bytes) -> None:
    """Binary flavor of the same dance (AOT executable-store entries:
    a torn entry would deserialize-fail every restart until overwritten,
    turning a crashed save into a permanent cache reject)."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    _replace_and_sync_dir(tmp, path)


def _replace_and_sync_dir(tmp: str, path: str) -> None:
    os.replace(tmp, path)
    # durability of the rename itself (see module docstring); best-effort
    # where the platform can't open directories
    with contextlib.suppress(OSError):
        dfd = os.open(os.path.dirname(path) or ".", os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
