"""Compiled-program caching: persistent XLA cache + plan-keyed AOT store.

The matcher's jitted programs recompile on corpus-capacity growth and
candidate-K escalation (O(log N) distinct shapes over a corpus's lifetime,
engine.device_matcher).  On TPU each compile costs tens of seconds, which
dominates cold-start and first-contact-with-new-shape latency.  Two
layers remove that cost (the service counterpart of the reference
reopening its Lucene index in APPEND mode instead of rebuilding —
IncrementalLuceneDatabase.java:233-244 — applied to compiled programs
instead of data):

  * ``enable_persistent_cache`` points jax's persistent compilation
    cache at disk, so an XLA *compile* of an already-seen program is a
    cache read.  The first contact with a shape still pays trace +
    lower + cache lookup.
  * ``AotStore`` (ISSUE 15) goes further: whole compiled executables —
    serialized via ``jax.experimental.serialize_executable`` — persist
    on disk keyed by (plan fingerprint, shape tuple, backend,
    jax/jaxlib version, scoring-code hash).  A restart *deserializes*
    the scorer ladder instead of compiling it: zero traces, zero XLA
    invocations before the first scoring batch
    (``tests/test_aot_cache.py`` pins restart-compiles-zero via the
    ``JIT_COMPILES`` counter).

Invalidation is entirely key-derivation: any change to the feature plan
(widths, comparators, probabilities), the ladder geometry (chunk, K,
buckets), the backend/device kind, the jax/jaxlib version, or the
scoring source itself produces a different key — a stale entry is never
*wrong*, only unreachable (and the warm thread re-fills the new key).
Entries that exist but fail to deserialize (foreign runtime, torn file
predating atomic writes, PJRT drift) count as ``reject`` and fall back
to the compile path.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import pickle
import threading
from typing import Dict, List, Optional

from ..telemetry import AOT_LOADS, GLOBAL, JIT_CACHE_HITS, JIT_COMPILES
from ..telemetry.env import env_flag, env_float, env_str
from ..telemetry.registry import FamilySnapshot

logger = logging.getLogger("jit-cache")

_DEFAULT = os.path.join(
    os.path.expanduser("~"), ".cache", "sesam_duke_tpu_xla"
)

# AOT-load outcome children pre-resolved at import (closed label set,
# same DK501 discipline as the device matcher's bucket children)
_AOT_HIT = AOT_LOADS.labels(outcome="hit")  # dukecheck: ignore[DK501] init-time pre-resolution
_AOT_MISS = AOT_LOADS.labels(outcome="miss")  # dukecheck: ignore[DK501] init-time pre-resolution
_AOT_REJECT = AOT_LOADS.labels(outcome="reject")  # dukecheck: ignore[DK501] init-time pre-resolution


def record_compile(n: int = 1) -> None:
    """Count a program build (in-process jit-cache miss or pre-warm AOT
    compile).  A recompile storm — capacity doublings or K-escalation
    racing through new shapes — shows as this counter climbing while
    ``duke_jit_cache_hits_total`` stalls."""
    JIT_COMPILES.inc(n)


def record_cache_hit(n: int = 1) -> None:
    """Count a scorer lookup served from the in-process program cache
    (jitted-function reuse or a registered AOT executable)."""
    JIT_CACHE_HITS.inc(n)


def record_aot_reject(n: int = 1) -> None:
    """Count a registered AOT executable rejected at call time (shape
    drift after it was built) — the caller falls back to the jit path."""
    _AOT_REJECT.inc(n)


def enable_persistent_cache(path: Optional[str] = None) -> Optional[str]:
    """Point jax at an on-disk compilation cache; returns the path used.

    Safe to call multiple times; a failure (read-only fs, old jax) only
    logs — the cache is an optimization, never a requirement.

    ``DUKE_JIT_CACHE_MIN_SECS`` sets the persistence floor (jax's
    ``jax_persistent_cache_min_compile_time_secs``).  The historical
    hard-coded 1.0 s meant CPU-lowered programs — which compile in
    milliseconds — never persisted, so the cache path was untestable in
    CI; tests and CPU deployments set it to 0.
    """
    import jax

    path = path or env_str("JAX_COMPILATION_CACHE_DIR") or _DEFAULT
    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs",
            env_float("DUKE_JIT_CACHE_MIN_SECS", 1.0),
        )
        return path
    except Exception as exc:  # pragma: no cover - depends on fs/jax version
        logger.warning("persistent compilation cache disabled: %s", exc)
        return None


# -- plan-keyed AOT executable store (ISSUE 15) -------------------------------


def aot_enabled() -> bool:
    """``DUKE_AOT`` gates the executable store (default on); =0 pins the
    legacy jit-only path exactly (the CI opt-out leg)."""
    return env_flag("DUKE_AOT", True)


def aot_dir() -> str:
    return env_str("DUKE_AOT_DIR") or os.path.join(_DEFAULT, "aot")


_CODE_FP: Optional[str] = None


def code_fingerprint() -> str:
    """Hash of the scoring-relevant sources (the ``ops`` package plus
    the matcher modules).  Any edit to kernel/scoring/feature code
    yields new store keys, so an AOT entry can never serve HLO compiled
    from different source — the invalidation rule README documents.
    Computed once per process."""
    global _CODE_FP
    if _CODE_FP is None:
        from .. import core, engine, ops

        h = hashlib.sha256()
        roots = [os.path.dirname(ops.__file__),
                 os.path.dirname(engine.__file__),
                 os.path.dirname(core.__file__)]
        for root in roots:
            for name in sorted(os.listdir(root)):
                if not name.endswith(".py"):
                    continue
                with open(os.path.join(root, name), "rb") as f:
                    h.update(name.encode("utf-8"))
                    h.update(f.read())
        _CODE_FP = h.hexdigest()[:16]
    return _CODE_FP


def environment_fingerprint() -> dict:
    """The runtime facets a serialized executable is only valid under:
    backend platform + device kind (a CPU executable must never load
    into a TPU process and vice versa), jax/jaxlib versions (PJRT
    serialization formats drift), and the XLA flags (they change
    codegen, e.g. the forced host device count)."""
    import jax
    import jaxlib

    devices = jax.devices()
    return {
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "platform": jax.default_backend(),
        "device_kind": devices[0].device_kind if devices else "none",
        "device_count": len(devices),
        "xla_flags": env_str("XLA_FLAGS", "") or "",
        "code": code_fingerprint(),
    }


def mesh_fingerprint(mesh) -> dict:
    """The mesh facets a sharded executable is only valid under: device
    grid shape and axis names.  These join the store KEY (not the env
    fingerprint — single-device and mesh workloads share a process, and
    ``device_count`` alone cannot distinguish a 4-way from an 8-way mesh
    on the same 8-device host), so an executable partitioned for one
    topology is unreachable from any other.

    In a multi-process job the facets also carry this controller's
    process coordinates: every process of the job shares the store
    directory and derives otherwise-identical keys, but a serialized
    executable embeds the saving process's device assignment — process 0
    must never deserialize process 1's artifact."""
    import jax

    doc = {
        "shape": [int(s) for s in mesh.devices.shape],
        "axes": [str(a) for a in mesh.axis_names],
    }
    if jax.process_count() > 1:
        doc["proc"] = [jax.process_index(), jax.process_count()]
    return doc


class AotStore:
    """On-disk store of serialized compiled executables.

    One file per (plan, shape, backend, version) key: the key dict is
    canonical-JSON-hashed into the filename, and the file holds a pickle
    of ``(key, payload, in_tree, out_tree)`` where payload/trees come
    from ``jax.experimental.serialize_executable.serialize``.  Writes
    are crash-atomic (``utils.atomicio``); concurrent savers of the same
    key race benignly (identical content, last replace wins).  No lock:
    load/save are pure file ops keyed by immutable content.
    """

    def __init__(self, root: Optional[str] = None):
        self.root = root or aot_dir()
        self._env = None  # environment fingerprint, resolved lazily

    def _key_doc(self, key: dict) -> dict:
        if self._env is None:
            self._env = environment_fingerprint()
        doc = dict(key)
        doc["__env__"] = self._env
        return doc

    def _path(self, key: dict) -> str:
        blob = json.dumps(self._key_doc(key), sort_keys=True,
                          separators=(",", ":"), default=str)
        digest = hashlib.sha256(blob.encode("utf-8")).hexdigest()
        return os.path.join(self.root, digest + ".aotx")

    def save(self, key: dict, compiled) -> bool:
        """Serialize ``compiled`` under ``key``; False (logged once per
        cause) when the backend/executable does not support
        serialization — saving is an optimization, never a requirement."""
        from jax.experimental import serialize_executable as se

        from .atomicio import atomic_write_bytes

        try:
            payload, in_tree, out_tree = se.serialize(compiled)
            # round-trip validation BEFORE the write: an executable whose
            # XLA compile was served from jax's persistent compilation
            # cache serializes THIN (the payload references jit symbols
            # it does not carry — observed as "Symbols not found" at
            # deserialize).  Persisting one would reject on every future
            # restart; refusing the save leaves the entry to a fresh
            # compile instead (the warm thread compiles cache-bypassed
            # for exactly this reason).
            se.deserialize_and_load(payload, in_tree, out_tree)
            blob = pickle.dumps(
                (self._key_doc(key), payload, in_tree, out_tree),
                protocol=pickle.HIGHEST_PROTOCOL,
            )
            os.makedirs(self.root, exist_ok=True)
            atomic_write_bytes(self._path(key), blob)
            return True
        except Exception as exc:
            logger.warning("AOT executable save failed for %s: %s",
                           key, exc)
            return False

    def load(self, key: dict):
        """Deserialize the executable stored under ``key``, or None.

        Outcomes land in ``duke_aot_loads_total``: hit (loaded), miss
        (no file), reject (file present but key-mismatched or
        undeserializable — deleted so the warm thread's re-save isn't
        rejected forever)."""
        from jax.experimental import serialize_executable as se

        path = self._path(key)
        try:
            with open(path, "rb") as f:
                blob = f.read()
        except OSError:
            _AOT_MISS.inc()
            return None
        try:
            stored_key, payload, in_tree, out_tree = pickle.loads(blob)
            if stored_key != self._key_doc(key):
                raise ValueError("stored key mismatch (hash collision?)")
            loaded = se.deserialize_and_load(payload, in_tree, out_tree)
        except Exception as exc:
            _AOT_REJECT.inc()
            logger.warning(
                "rejecting AOT executable %s (%s); it will be recompiled "
                "and re-saved", path, exc)
            try:
                os.unlink(path)
            except OSError:
                pass
            return None
        _AOT_HIT.inc()
        return loaded


# -- shared in-process AOT ladders (ISSUE 19 tentpole b) ----------------------


def shared_aot_enabled() -> bool:
    """``DUKE_SHARED_AOT`` gates the cross-workload ladder sharing
    (default on); =0 pins the per-workload registration maps exactly."""
    return env_flag("DUKE_SHARED_AOT", True)


class SharedLadder:
    """One refcounted (plan fingerprint, geometry) executable ladder.

    ``map`` is the scorer caches' ``_aot`` registration dict — the same
    lock-free akey->executable contract as before, now pointed at by
    every tenant on the schema.  ``warm_lock`` serializes the tenants'
    warm threads over the ladder so N same-schema tenants pay ONE warm
    compile per entry (the losers find the entry present and skip).
    ``refs`` is guarded by the registry lock."""

    __slots__ = ("key", "map", "refs", "warm_lock")

    def __init__(self, key: tuple):
        self.key = key
        self.map: Dict[tuple, object] = {}
        self.refs = 0  # guarded by: self._lock (the registry's — SharedLadder has no lock of its own)
        self.warm_lock = threading.Lock()


class SharedLadderRegistry:
    """Process-wide (fingerprint, geometry) -> :class:`SharedLadder` map.

    The on-disk :class:`AotStore` already dedupes by plan fingerprint;
    this is the in-process counterpart: N tenants with identical keys
    share one registration map (and so one warm pass and one set of
    live executables) instead of compiling N ladders.  Release is
    refcounted — the PR 14 plan-mutation eviction seam releases the
    tenant's lease, and the LAST tenant off a plan drops the ladder and
    its executables (the refcounted evict)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: Dict[tuple, SharedLadder] = {}  # guarded by: self._lock

    def acquire(self, key: tuple) -> SharedLadder:
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                entry = self._entries[key] = SharedLadder(key)
            entry.refs += 1
            return entry

    def release(self, entry: Optional[SharedLadder]) -> None:
        if entry is None:
            return
        with self._lock:
            entry.refs -= 1
            if entry.refs <= 0:
                self._entries.pop(entry.key, None)

    def stats(self) -> Dict[str, int]:
        """{ladders, refs, executables} — bench/debug surface."""
        with self._lock:
            entries = list(self._entries.values())
            return {
                "ladders": len(entries),
                "refs": sum(e.refs for e in entries),
                "executables": sum(len(e.map) for e in entries),
            }

    def _reset_for_tests(self) -> None:
        with self._lock:
            self._entries.clear()


SHARED_LADDERS = SharedLadderRegistry()


def release_shared_lease(holder: List[Optional[SharedLadder]]) -> None:
    """weakref.finalize target for a scorer cache's lease holder: the
    cache dying (workload reload/close) must release its ref so the
    last tenant off a schema actually evicts the shared ladder."""
    lease, holder[0] = holder[0], None
    SHARED_LADDERS.release(lease)


def _collect_shared() -> List[FamilySnapshot]:
    """Scrape-time collector (registered on ``telemetry.GLOBAL``)."""
    stats = SHARED_LADDERS.stats()
    return [
        FamilySnapshot(
            "duke_aot_shared_refs", "gauge",
            "Scorer caches currently leasing a shared AOT ladder "
            "(tenants sharing compiled executables by plan fingerprint "
            "+ geometry)",
            [("", (), float(stats["refs"]))]),
    ]


GLOBAL.register_collector(_collect_shared)
