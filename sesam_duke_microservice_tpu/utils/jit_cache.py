"""Persistent XLA compilation cache.

The matcher's jitted programs recompile on corpus-capacity growth and
candidate-K escalation (O(log N) distinct shapes over a corpus's lifetime,
engine.device_matcher).  On TPU each compile costs tens of seconds, which
dominates cold-start and first-contact-with-new-shape latency.  Enabling
jax's persistent compilation cache amortizes that across process restarts —
the service counterpart of the reference reopening its Lucene index in
APPEND mode instead of rebuilding (IncrementalLuceneDatabase.java:233-244),
applied to compiled programs instead of data.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

from ..telemetry import JIT_CACHE_HITS, JIT_COMPILES
from ..telemetry.env import env_str

logger = logging.getLogger("jit-cache")

_DEFAULT = os.path.join(
    os.path.expanduser("~"), ".cache", "sesam_duke_tpu_xla"
)


def record_compile(n: int = 1) -> None:
    """Count a program build (in-process jit-cache miss or pre-warm AOT
    compile).  A recompile storm — capacity doublings or K-escalation
    racing through new shapes — shows as this counter climbing while
    ``duke_jit_cache_hits_total`` stalls."""
    JIT_COMPILES.inc(n)


def record_cache_hit(n: int = 1) -> None:
    """Count a scorer lookup served from the in-process program cache."""
    JIT_CACHE_HITS.inc(n)


def enable_persistent_cache(path: Optional[str] = None) -> Optional[str]:
    """Point jax at an on-disk compilation cache; returns the path used.

    Safe to call multiple times; a failure (read-only fs, old jax) only
    logs — the cache is an optimization, never a requirement.
    """
    import jax

    path = path or env_str("JAX_COMPILATION_CACHE_DIR") or _DEFAULT
    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        return path
    except Exception as exc:  # pragma: no cover - depends on fs/jax version
        logger.warning("persistent compilation cache disabled: %s", exc)
        return None
