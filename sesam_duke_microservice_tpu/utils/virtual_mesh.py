"""Virtual CPU mesh provisioning for hosts without enough chips.

THE one copy of the re-exec recipe (`JAX_PLATFORMS=cpu` +
``--xla_force_host_platform_device_count=N`` + an inner-guard env var)
that `tests/conftest.py` pioneered: the driver's multichip dryrun
(`__graft_entry__.py`) and the sharded benchmark
(`benchmarks/large_scale.py`) both validate N-way `shard_map` programs on
single-chip hosts by re-exec'ing themselves in a child with these
settings.  The env must be set before the child's interpreter starts
(jax reads it at init), and on axon hosts the sitecustomize hook pins the
platform even earlier — so children must ALSO call
``force_cpu_platform()`` before any computation.
"""

from __future__ import annotations

import os
from typing import Dict


def virtual_mesh_env(n_devices: int, inner_flag: str) -> Dict[str, str]:
    """Child-process env for an ``n_devices`` virtual CPU mesh.

    ``inner_flag`` is the guard the child checks to know it has been
    re-exec'd (so it provisions instead of re-exec'ing again).
    """
    env = dict(os.environ)  # dukecheck: ignore[DK301] child-process env composition, not a knob read
    env[inner_flag] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    flags = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    )
    env["XLA_FLAGS"] = (
        f"{flags} --xla_force_host_platform_device_count={n_devices}".strip()
    )
    return env


def force_cpu_platform() -> None:
    """Pin jax to CPU from inside a re-exec'd child.

    The env var alone is not enough when a sitecustomize hook (axon)
    imports jax at interpreter startup; forcing the config still works as
    long as no computation has run.
    """
    import jax

    jax.config.update("jax_platforms", "cpu")
