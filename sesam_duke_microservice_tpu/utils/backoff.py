"""Capped exponential backoff with full jitter — THE one policy copy.

Both retry loops introduced by ISSUE 8 (dispatcher per-follower send
retries, feed mid-stream lock retries) draw their delays here, so a
policy change (e.g. adding a floor) lands once.  Full jitter
(uniform(0, ceiling)) decorrelates retriers contending for the same
resource; see the AWS architecture blog's classic analysis.
"""

from __future__ import annotations

import random


def full_jitter_delay(attempt: int, base_s: float, cap_s: float) -> float:
    """Delay before retry ``attempt`` (1-based): uniform in
    [0, min(base * 2^(attempt-1), cap)]."""
    ceiling = min(base_s * (2 ** min(max(attempt, 1) - 1, 32)), cap_s)
    return random.uniform(0, ceiling)
