"""Deterministic fault injection for the multi-host serving path.

The HA serving group (ISSUE 8) is only trustworthy if its failure
handling is *exercised*: follower eviction, send retry/backoff, epoch
fencing, replica watermarks, and the write-behind latch all have
behavior that production traffic alone never reaches.  This layer
injects those failures deterministically so a chaos differential test
can pin the surviving group's output bit-identical to unfaulted serving.

Activation is the ``DUKE_FAULTS`` env var (or ``configure()`` from
tests): a ``;``/``,``-separated spec of fault tokens.  Probabilities are
resolved by *hashing* the injection site's coordinates (seed, kind, op
index, follower index) — not by consuming a shared RNG stream — so a
given spec injects the same faults at the same ops regardless of thread
interleaving or call order.  That determinism is what makes the chaos CI
leg reproducible.

Spec tokens (``p`` in [0,1]; ``@tag`` filters to one dispatch op tag):

  ``seed=<int>``                   hash seed (default 0)
  ``drop=<p>[@tag]``               transient send failure (first attempt
                                   only — the retry layer must heal it)
  ``delay=<p>:<seconds>[@tag]``    sleep before the send
  ``dup=<p>[@tag]``                send the frame twice (same stream seq
                                   — the follower must drop the dup)
  ``partition=<f>:<from>:<to>``    every send attempt to follower ``f``
                                   fails for op index in [from, to) —
                                   exhausts the retries, forcing eviction
  ``crash_follower=<f>:<n>``       follower ``f``'s replay loop dies hard
                                   at its ``n``-th received op
  ``crash_leader=<n>``             the dispatcher raises LeaderCrash
                                   before broadcasting op ``n``
  ``flush_fail=<n>``               the ``n``-th write-behind link flush
                                   raises (exercises the latch +
                                   /readyz unready satellite)
  ``slow_lock=<p>:<seconds>``      feed-path lock acquisitions sleep
                                   first (exercises the bounded-backoff
                                   deadline path)
  ``crash_at=<site>:<n>``          SIGKILL this process the ``n``-th time
                                   the named ingest crash site is
                                   reached — no cleanup, no atexit, the
                                   honest crash the kill-differential
                                   harness (ISSUE 10) restarts from.
                                   Sites: ``post_store_put``,
                                   ``post_journal_append``, ``pre_flush``,
                                   ``mid_flush``,
                                   ``post_flush_pre_truncate``,
                                   ``mid_journal_write`` (writes HALF the
                                   journal frame first — torn-tail
                                   synthesis), ``mid_snapshot_save``
                                   (tmp written, ``os.replace`` pending);
                                   range-migration sites (ISSUE 14,
                                   federation/migrate.py): ``pre_freeze``
                                   (state recorded, map not yet frozen),
                                   ``post_snapshot`` (range snapshot
                                   written, nothing shipped),
                                   ``mid_replay`` (snapshot loaded at the
                                   target, journal slice partially
                                   replayed), ``pre_cutover`` (target
                                   complete, map still names the source),
                                   ``post_cutover`` (map cut over, drain/
                                   cleanup pending)
  ``probe_flip=<n>``               corrupt the ``n``-th canary verdict
                                   the prober checks (service/prober.py
                                   readback seam) — drives the
                                   mismatch-latch + /healthz-degraded
                                   detection drill
  ``fed_down=<g>``                 federation group ``g`` is unreachable:
                                   every router call into it raises
                                   GroupUnavailable — drives the
                                   scatter-gather degraded-mode contract
                                   (dead ranges 503 + Retry-After, live
                                   ranges keep serving)

Every injected fault counts in ``duke_faults_injected_total{kind}``.
This module is wired into ``parallel/dispatch.py`` (send path + follower
loop), ``links/write_behind.py`` (flush), and ``service/app.py`` (feed
locks); with no spec set every hook is a no-op attribute read.
"""

from __future__ import annotations

import hashlib
import os
import threading
import time
from typing import Dict, Optional, Tuple

from .. import telemetry
from ..telemetry.env import env_str


class LeaderCrash(RuntimeError):
    """Injected leader death: the dispatcher aborts before broadcasting."""


class InjectedSendFailure(OSError):
    """Injected transient send failure.  Subclasses OSError so any code
    treating it generically sees a socket-like error, but it is raised
    BEFORE any bytes hit the wire — retrying it cannot tear a frame."""


class InjectedFlushFailure(IOError):
    """Injected write-behind flush failure (latches the buffer)."""


# cached label children (dukecheck DK501): fault kinds are a tiny closed
# set, so each child resolves through the family lock at most once
_KIND_CHILDREN: Dict[str, object] = {}


def _count(kind: str) -> None:
    child = _KIND_CHILDREN.get(kind)
    if child is None:
        child = telemetry.FAULTS_INJECTED.labels(kind=kind)  # dukecheck: ignore[DK501] once per fault kind, cached
        _KIND_CHILDREN[kind] = child
    child.inc()


def _unit(seed: int, *key) -> float:
    """Deterministic uniform draw in [0, 1) from the site coordinates."""
    h = hashlib.sha256(repr((seed,) + key).encode()).digest()
    return int.from_bytes(h[:8], "big") / float(1 << 64)


class FaultPlan:
    def __init__(self, spec: str):
        self.spec = spec
        self.seed = 0
        # (p, tag-or-None) rules
        self._drop: list = []
        self._dup: list = []
        # (p, seconds, tag-or-None)
        self._delay: list = []
        # follower -> (from_op, to_op)
        self._partitions: Dict[int, Tuple[int, int]] = {}
        # follower -> op count at which its loop dies
        self._follower_crash: Dict[int, int] = {}
        self._leader_crash: Optional[int] = None
        self._flush_fail_at: Optional[int] = None
        self._slow_lock: Optional[Tuple[float, float]] = None
        # crash site name -> 1-based occurrence that kills the process
        self._crash_at: Dict[str, int] = {}
        # federation groups whose router calls fail (ISSUE 14)
        self._fed_down: set = set()
        # 1-based canary-verdict check occurrence to corrupt (ISSUE 20)
        self._probe_flip_at: Optional[int] = None
        self._flush_lock = threading.Lock()
        self._flush_count = 0  # guarded by: self._flush_lock
        self._probe_count = 0  # guarded by: self._flush_lock
        self._lock_count = 0  # guarded by: self._flush_lock
        self._crash_counts: Dict[str, int] = {}  # guarded by: self._flush_lock
        self._parse(spec)

    def _parse(self, spec: str) -> None:
        for raw in spec.replace(",", ";").split(";"):
            token = raw.strip()
            if not token:
                continue
            kind, _, args = token.partition("=")
            kind = kind.strip()
            args, _, tag = args.partition("@")
            tag = tag.strip() or None
            parts = [p for p in args.split(":") if p != ""]
            try:
                if kind == "seed":
                    self.seed = int(parts[0])
                elif kind == "drop":
                    self._drop.append((float(parts[0]), tag))
                elif kind == "dup":
                    self._dup.append((float(parts[0]), tag))
                elif kind == "delay":
                    self._delay.append((float(parts[0]), float(parts[1]), tag))
                elif kind == "partition":
                    self._partitions[int(parts[0])] = (
                        int(parts[1]), int(parts[2]))
                elif kind == "crash_follower":
                    self._follower_crash[int(parts[0])] = int(parts[1])
                elif kind == "crash_leader":
                    self._leader_crash = int(parts[0])
                elif kind == "flush_fail":
                    self._flush_fail_at = int(parts[0])
                elif kind == "slow_lock":
                    self._slow_lock = (float(parts[0]), float(parts[1]))
                elif kind == "crash_at":
                    self._crash_at[str(parts[0])] = int(parts[1])
                elif kind == "fed_down":
                    self._fed_down.add(int(parts[0]))
                elif kind == "probe_flip":
                    self._probe_flip_at = int(parts[0])
                else:
                    raise ValueError(f"unknown fault kind {kind!r}")
            except (IndexError, ValueError) as e:
                raise ValueError(
                    f"bad DUKE_FAULTS token {token!r}: {e}"
                ) from e

    # -- dispatcher send path -------------------------------------------------

    def check_leader_crash(self, op_index: int) -> None:
        if self._leader_crash is not None and op_index == self._leader_crash:
            _count("crash_leader")
            raise LeaderCrash(
                f"injected leader crash at op {op_index} (DUKE_FAULTS)"
            )

    def before_send(self, tag: str, follower: int, op_index: int,
                    attempt: int) -> None:
        """Called before each send attempt; sleeps for delay faults and
        raises ``InjectedSendFailure`` for drop/partition faults — always
        BEFORE any bytes are written, so a retry is stream-safe."""
        window = self._partitions.get(follower)
        if window is not None and window[0] <= op_index < window[1]:
            _count("partition")
            raise InjectedSendFailure(
                f"injected partition: follower {follower} unreachable "
                f"for op {op_index}"
            )
        if attempt == 0:
            for p, seconds, t in self._delay:
                if t is None or t == tag:
                    if _unit(self.seed, "delay", op_index, follower) < p:
                        _count("delay")
                        time.sleep(seconds)
                        break
            for p, t in self._drop:
                if t is None or t == tag:
                    if _unit(self.seed, "drop", op_index, follower) < p:
                        _count("drop")
                        raise InjectedSendFailure(
                            f"injected send drop at op {op_index} "
                            f"(follower {follower})"
                        )

    def dup_send(self, tag: str, follower: int, op_index: int) -> bool:
        for p, t in self._dup:
            if t is None or t == tag:
                if _unit(self.seed, "dup", op_index, follower) < p:
                    _count("dup")
                    return True
        return False

    # -- follower loop --------------------------------------------------------

    def follower_crash(self, follower: int, op_count: int) -> bool:
        if self._follower_crash.get(follower) == op_count:
            _count("crash_follower")
            return True
        return False

    # -- write-behind flush ---------------------------------------------------

    def check_flush(self, name: str) -> None:
        if self._flush_fail_at is None:
            return
        with self._flush_lock:
            self._flush_count += 1
            hit = self._flush_count == self._flush_fail_at
        if hit:
            _count("flush_fail")
            raise InjectedFlushFailure(
                f"injected {name} flush failure (DUKE_FAULTS flush_fail)"
            )

    # -- ingest crash sites (ISSUE 10 kill differential) ----------------------

    def crash_hit(self, site: str) -> bool:
        """Count one arrival at ``site``; True iff this is the configured
        occurrence.  Split from ``crash_now`` so a site that must do
        site-specific damage first (``mid_journal_write`` writes half a
        frame) can interleave the two; plain sites use ``check_crash``."""
        n = self._crash_at.get(site)
        if n is None:
            return False
        with self._flush_lock:
            count = self._crash_counts.get(site, 0) + 1
            self._crash_counts[site] = count
        return count == n

    def crash_now(self, site: str) -> None:
        """Die the way a real crash dies: SIGKILL to self — no cleanup,
        no flush, no atexit.  The kill-differential harness asserts the
        restart recovers to the uncrashed control from exactly this."""
        import signal
        import sys

        _count("crash_at")
        print(f"duke-faults: injected crash at {site}", file=sys.stderr,
              flush=True)
        os.kill(os.getpid(), signal.SIGKILL)

    def check_crash(self, site: str) -> None:
        if self.crash_hit(site):
            self.crash_now(site)

    # -- canary prober (ISSUE 20) ---------------------------------------------

    def probe_flip(self) -> bool:
        """Count one canary-verdict check; True iff this is the
        configured occurrence (spec ``probe_flip=<n>``) — the prober
        then corrupts that one verdict at its readback seam, exactly as
        a finalize corruption would surface."""
        if self._probe_flip_at is None:
            return False
        with self._flush_lock:
            self._probe_count += 1
            hit = self._probe_count == self._probe_flip_at
        if hit:
            _count("probe_flip")
        return hit

    # -- federation router (ISSUE 14) -----------------------------------------

    def fed_group_down(self, group: int) -> bool:
        """True iff router calls into federation group ``group`` should
        fail (spec ``fed_down=<g>``) — the deterministic dead-group fault
        behind the degraded-mode contract tests."""
        if group in self._fed_down:
            _count("fed_down")
            return True
        return False

    # -- lock paths -----------------------------------------------------------

    def lock_delay(self) -> float:
        """Seconds the feed path should stall before a lock attempt."""
        if self._slow_lock is None:
            return 0.0
        p, seconds = self._slow_lock
        with self._flush_lock:
            self._lock_count += 1
            n = self._lock_count
        if _unit(self.seed, "slow_lock", n) < p:
            _count("slow_lock")
            return seconds
        return 0.0


_cached: Tuple[Optional[str], Optional[FaultPlan]] = (None, None)
_override: Optional[FaultPlan] = None
_override_set = False


def configure(spec: Optional[str]) -> Optional[FaultPlan]:
    """Test hook: install (or clear, with None) an explicit plan that
    wins over the env var.  Returns the installed plan."""
    global _override, _override_set
    _override = FaultPlan(spec) if spec else None
    _override_set = spec is not None
    return _override


def check_crash(site: str) -> None:
    """Module-level crash-site hook (ISSUE 10): with an active plan
    arming ``crash_at=<site>:<n>``, the n-th arrival SIGKILLs the
    process; otherwise a no-op attribute read.  THE one copy of the
    plan-resolution dance — call sites that need the plan for more than
    one check (the flusher) fetch it once via ``active()`` instead."""
    plan = active()
    if plan is not None:
        plan.check_crash(site)


def active() -> Optional[FaultPlan]:
    """The current fault plan, or None (the overwhelmingly common case —
    one env read and a tuple compare per call)."""
    global _cached
    if _override_set:
        return _override
    spec = env_str("DUKE_FAULTS") or None
    cached_spec, cached_plan = _cached
    if spec != cached_spec:
        cached_plan = FaultPlan(spec) if spec else None
        _cached = (spec, cached_plan)
    return cached_plan
