"""Shared host-side utilities (SQLite plumbing, etc.)."""
