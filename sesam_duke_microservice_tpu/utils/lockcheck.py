"""Runtime lock-order sanitizer (``DUKE_LOCKCHECK=1``).

The static half of this contract is ``scripts/dukecheck`` (checker 1):
an ``ast``-level inter-lock acquisition graph, committed as
``docs/LOCK_HIERARCHY.md``.  This module is the dynamic half: when
``DUKE_LOCKCHECK=1`` is set *before the package imports*, the
``threading.Lock``/``RLock``/``Condition`` factories are wrapped so that
every lock **created inside this package** becomes a thin recording
proxy.  Each proxy is named by its creation site, which the committed
hierarchy doc maps back to the static lock identity
(``Workload.lock``, ``WriteBehindBuffer._cv``, ...) — the same names the
static graph uses, so the two halves talk about the same objects.

What it checks, per acquisition, per thread:

  * **inversions against the static hierarchy** — acquiring ``B`` while
    holding ``A`` when the static graph orders ``B`` (transitively)
    before ``A``.  This is the would-be-deadlock class the static
    checker proves absent; observing one at runtime means the resolution
    tables (scripts/dukecheck/config.py) or the analysis drifted, and
    the tier-1 ``DUKE_LOCKCHECK=1`` leg fails.
  * **dynamic inversions** — ``(A, B)`` and ``(B, A)`` both observed at
    runtime, regardless of what the static graph knows.  Catches orders
    the static analyzer cannot see (callbacks, getattr dispatch).
  * **unknown edges** — observed nestings absent from the static graph.
    Reported (not fatal): each one is analyzer drift to triage, exactly
    the "dynamic validates static" loop the suite is built around.
  * **held-across-dispatch** — which locks were held while a blocking
    multi-host broadcast ran (``parallel/dispatch.py`` notes the region).
    Reported: holding the mesh op lock there is by design; anything else
    showing up deserves a look.

Zero overhead when disabled: the factories are only patched when the
flag is set at import, and ``note_blocking`` no-ops.

Usage::

    DUKE_LOCKCHECK=1 python -m pytest tests/ ...   # conftest fails the
                                                   # session on inversions
    # or, in-process:
    from sesam_duke_microservice_tpu.utils import lockcheck
    lockcheck.assert_clean()      # raises on recorded inversions
    lockcheck.report()            # full dict for tooling
"""

from __future__ import annotations

import atexit
import os
import sys
import threading
from typing import Dict, List, Optional, Set, Tuple

# Read raw: this runs from the package __init__ BEFORE telemetry (or any
# other module) imports, so that their module-level locks get wrapped
# too; importing telemetry.env here would create its locks unwrapped.
_ENABLED = os.environ.get(  # dukecheck: ignore[DK301] must run before telemetry.env can import
    "DUKE_LOCKCHECK", ""
).strip().lower() in ("1", "true", "yes", "on")

# originals, saved before any patching
_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock
_REAL_CONDITION = threading.Condition

_THIS_FILE = os.path.abspath(__file__)
_PACKAGE_NAME = "sesam_duke_microservice_tpu"
_PACKAGE_DIR = os.path.dirname(os.path.dirname(_THIS_FILE))
_REPO_ROOT = os.path.dirname(_PACKAGE_DIR)
DOC_RELPATH = os.path.join("docs", "LOCK_HIERARCHY.md")

_state_lock = _REAL_LOCK()
_installed = False

# (site-name A, site-name B) -> witness "file:line" of B's acquisition:
# B was acquired while A was held
_observed_edges: Dict[Tuple[str, str], str] = {}
# static-order violations found live: (held, acquired, witness)
_inversions: List[Tuple[str, str, str]] = []
# blocking-region name -> set of held lock names observed
_blocking_holds: Dict[str, Set[str]] = {}

_tls = threading.local()

# static hierarchy, parsed lazily from the committed doc
_static_names: Optional[Dict[Tuple[str, int], str]] = None
_static_reach: Optional[Dict[str, Set[str]]] = None


def enabled() -> bool:
    return _ENABLED and _installed


# -- static hierarchy doc ------------------------------------------------------


def _parse_doc(text: str):
    """``(site -> name, name -> transitive successors)`` from the
    generated ``docs/LOCK_HIERARCHY.md`` tables."""
    names: Dict[Tuple[str, int], str] = {}
    succ: Dict[str, Set[str]] = {}
    section = ""
    for line in text.splitlines():
        if line.startswith("## "):
            section = line[3:].strip()
            continue
        if not (line.startswith("|") and "`" in line):
            continue
        cells = [c.strip() for c in line.strip("|").split("|")]
        if section == "Locks" and len(cells) >= 3:
            name = cells[0].strip("`")
            rel, _, lineno = cells[2].rpartition(":")
            if rel and lineno.isdigit():
                names[(rel, int(lineno))] = name
        elif section.startswith("Acquisition-order") and len(cells) >= 2:
            a, b = cells[0].strip("`"), cells[1].strip("`")
            succ.setdefault(a, set()).add(b)
    # transitive closure (the graph is acyclic by DK101, but guard anyway)
    reach: Dict[str, Set[str]] = {}

    def visit(node: str) -> Set[str]:
        if node in reach:
            return reach[node]
        reach[node] = set()
        acc: Set[str] = set()
        for nxt in succ.get(node, ()):
            acc.add(nxt)
            acc |= visit(nxt)
        reach[node] = acc
        return acc

    for node in list(succ):
        visit(node)
    return names, reach


def _load_static() -> None:
    global _static_names, _static_reach
    if _static_names is not None:
        return
    try:
        with open(os.path.join(_REPO_ROOT, DOC_RELPATH),
                  encoding="utf-8") as f:
            _static_names, _static_reach = _parse_doc(f.read())
    except OSError:
        # no committed hierarchy (e.g. installed package): dynamic-only
        _static_names, _static_reach = {}, {}


def _site_name(filename: str, lineno: int) -> str:
    """Static lock identity for a creation site, else ``rel:line``."""
    _load_static()
    rel = os.path.relpath(filename, _REPO_ROOT).replace(os.sep, "/")
    return _static_names.get((rel, lineno), f"{rel}:{lineno}")


# -- per-thread bookkeeping ----------------------------------------------------


def _held() -> List[List]:
    # [[proxy, count, acquire-witness], ...] — acquisition order,
    # reentrancy-counted; the witness tells package-driven holds apart
    # from foreign (test-harness) holds
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


def _note_acquire(proxy: "_LockProxy") -> None:
    stack = _held()
    for entry in stack:
        if entry[0] is proxy:
            entry[1] += 1  # reentrant re-acquire: no new edge
            return
    caller = sys._getframe(1)
    while (caller is not None
           and caller.f_code.co_filename.endswith("lockcheck.py")):
        caller = caller.f_back
    if caller is None:  # pragma: no cover - interpreter teardown
        witness = "?"
    else:
        witness = "%s:%d" % (
            os.path.relpath(caller.f_code.co_filename,
                            _REPO_ROOT).replace(os.sep, "/"),
            caller.f_lineno,
        )
    _load_static()
    new_edges = []
    violations = []
    for entry in stack:
        held_name = entry[0].name
        if held_name == proxy.name:
            continue  # distinct instances of one class: ordered elsewhere
        if not entry[2].startswith(_PACKAGE_NAME + "/"):
            # the hold was taken by foreign code (a test driver pinning a
            # workload lock, a bench harness): not package nesting
            continue
        edge = (held_name, proxy.name)
        new_edges.append((edge, witness))
        # static contradiction: the hierarchy orders proxy.name before
        # held_name, so this acquisition closes a cycle
        if held_name in _static_reach.get(proxy.name, ()):
            violations.append((held_name, proxy.name, witness))
    if new_edges or violations:
        # only nested acquisitions pay for the global state lock — the
        # common flat-acquire case must not serialize every package lock
        # in the sanitizer leg through one process-wide mutex
        with _state_lock:
            for edge, wit in new_edges:
                _observed_edges.setdefault(edge, wit)
            for v in violations:
                _inversions.append(v)
    stack.append([proxy, 1, witness])


def _note_release(proxy: "_LockProxy") -> None:
    stack = _held()
    for i in range(len(stack) - 1, -1, -1):
        if stack[i][0] is proxy:
            stack[i][1] -= 1
            if stack[i][1] <= 0:
                del stack[i]
            return


def note_blocking(region: str) -> None:
    """Record which instrumented locks the calling thread holds while
    entering a blocking region (multi-host broadcast).  No-op unless the
    sanitizer is installed."""
    if not enabled():
        return
    names = {entry[0].name for entry in _held()}
    if not names:
        return
    with _state_lock:
        _blocking_holds.setdefault(region, set()).update(names)


# -- proxies -------------------------------------------------------------------


class _LockProxy:
    """Recording wrapper over a real Lock/RLock."""

    __slots__ = ("_inner", "name", "site")

    def __init__(self, inner, name: str, site: str):
        self._inner = inner
        self.name = name
        self.site = site

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            _note_acquire(self)
        return got

    def release(self) -> None:
        self._inner.release()
        _note_release(self)

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<lockcheck {self.name} over {self._inner!r}>"


class _ConditionProxy:
    """Recording wrapper over a real Condition (own internal RLock).

    ``wait()`` releases the underlying lock, so the held-stack entry is
    popped for the duration — a lock acquired by ANOTHER thread while
    this one waits must not appear nested under the condition."""

    __slots__ = ("_inner", "name", "site")

    def __init__(self, inner, name: str, site: str):
        self._inner = inner
        self.name = name
        self.site = site

    def acquire(self, *args) -> bool:
        got = self._inner.acquire(*args)
        if got:
            _note_acquire(self)
        return got

    def release(self) -> None:
        self._inner.release()
        _note_release(self)

    def __enter__(self):
        self._inner.__enter__()
        _note_acquire(self)
        return self

    def __exit__(self, *exc):
        result = self._inner.__exit__(*exc)
        _note_release(self)
        return result

    def wait(self, timeout: Optional[float] = None) -> bool:
        _note_release(self)
        try:
            return self._inner.wait(timeout)
        finally:
            _note_acquire(self)

    def wait_for(self, predicate, timeout: Optional[float] = None):
        _note_release(self)
        try:
            return self._inner.wait_for(predicate, timeout)
        finally:
            _note_acquire(self)

    def notify(self, n: int = 1) -> None:
        self._inner.notify(n)

    def notify_all(self) -> None:
        self._inner.notify_all()

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<lockcheck {self.name} over {self._inner!r}>"


def _from_package(frame) -> bool:
    filename = frame.f_code.co_filename
    try:
        return os.path.abspath(filename).startswith(_PACKAGE_DIR + os.sep)
    except (TypeError, ValueError):  # pragma: no cover - exotic frames
        return False


def _make_factory(real, kind: str):
    def factory(*args, **kwargs):
        frame = sys._getframe(1)
        if args or kwargs or not _from_package(frame):
            # foreign creation site, or a Condition over an explicit
            # lock: hand back the real object untouched
            return real(*args, **kwargs)
        name = _site_name(frame.f_code.co_filename, frame.f_lineno)
        site = f"{frame.f_code.co_filename}:{frame.f_lineno}"
        if kind == "Condition":
            return _ConditionProxy(real(), name, site)
        return _LockProxy(real(), name, site)

    factory.__name__ = kind
    return factory


# -- lifecycle / reporting -----------------------------------------------------


def install_if_enabled() -> bool:
    """Patch the ``threading`` factories when ``DUKE_LOCKCHECK=1``.
    Called from the package ``__init__`` so every later module-level and
    instance lock in the package is wrapped.  Idempotent."""
    global _installed
    if not _ENABLED or _installed:
        return _installed
    threading.Lock = _make_factory(_REAL_LOCK, "Lock")
    threading.RLock = _make_factory(_REAL_RLOCK, "RLock")
    threading.Condition = _make_factory(_REAL_CONDITION, "Condition")
    _installed = True
    atexit.register(_atexit_report)
    return True


def reset() -> None:
    """Clear recorded state (tests)."""
    with _state_lock:
        _observed_edges.clear()
        _inversions.clear()
        _blocking_holds.clear()


def report() -> dict:
    with _state_lock:
        edges = dict(_observed_edges)
        inversions = list(_inversions)
        blocking = {k: sorted(v) for k, v in _blocking_holds.items()}
    # dynamic inversions: both orders of one pair observed at runtime
    dynamic = sorted(
        {tuple(sorted((a, b))) for (a, b) in edges if (b, a) in edges}
    )
    _load_static()
    # an edge whose REVERSE is statically ordered is an inversion, already
    # reported above — listing it under unknown_edges too would steer the
    # triager toward MANUAL_EDGES, which would just close a DK101 cycle
    unknown = sorted(
        f"{a} -> {b} @ {wit}" for (a, b), wit in edges.items()
        if b not in _static_reach.get(a, ())
        and a not in _static_reach.get(b, ())
        and ":" not in a + b
    )
    # edges involving a lock the hierarchy doc could not name (its
    # creation site has only the `rel:line` fallback identity): the
    # static graph cannot order these AT ALL, which is analyzer-naming
    # drift, not config drift — report them separately, never drop them
    unmapped = sorted(
        f"{a} -> {b} @ {wit}" for (a, b), wit in edges.items()
        if ":" in a or ":" in b
    )
    return {
        "enabled": enabled(),
        "edges_observed": len(edges),
        "static_inversions": [
            f"acquired `{b}` while holding `{a}` at {wit} — the static "
            f"hierarchy orders {b} before {a}"
            for (a, b, wit) in inversions
        ],
        "dynamic_inversions": [
            f"`{a}` and `{b}` acquired in both orders "
            f"({edges.get((a, b))} vs {edges.get((b, a))})"
            for (a, b) in dynamic
        ],
        "unknown_edges": unknown,
        "unmapped_lock_edges": unmapped,
        "held_across_dispatch": blocking,
    }


def inversions() -> List[str]:
    rep = report()
    return rep["static_inversions"] + rep["dynamic_inversions"]


def assert_clean() -> None:
    """Raise if any lock-order inversion was recorded (the tier-1
    ``DUKE_LOCKCHECK=1`` leg's acceptance gate)."""
    found = inversions()
    if found:
        raise AssertionError(
            "lockcheck recorded lock-order inversions:\n  "
            + "\n  ".join(found)
        )


def _atexit_report() -> None:  # pragma: no cover - process teardown
    rep = report()
    found = rep["static_inversions"] + rep["dynamic_inversions"]
    if found:
        print("lockcheck: LOCK-ORDER INVERSIONS RECORDED:",
              file=sys.stderr)
        for line in found:
            print("  " + line, file=sys.stderr)
    if rep["unknown_edges"]:
        print(
            "lockcheck: %d observed edge(s) missing from the static "
            "graph (analyzer drift — triage scripts/dukecheck/config.py):"
            % len(rep["unknown_edges"]),
            file=sys.stderr,
        )
        for line in rep["unknown_edges"]:
            print("  " + line, file=sys.stderr)
