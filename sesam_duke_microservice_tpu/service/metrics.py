"""Per-app metric families and the workload-walking collector.

Each ``DukeApp`` owns a ``MetricRegistry`` (``app.metrics``): the HTTP
families live as registry children written by the handler threads, while
everything the engine already tracks lock-free — ProfileStats,
PhaseRecorders, corpus sizes, link-store rows — is surfaced by a
scrape-time collector that walks the app's LIVE workload registries.
Walking at scrape time (instead of registering per-workload children)
means a hot config reload drops the replaced workloads' series
automatically and the scoring path never writes a registry child.

All collector reads are lock-free snapshots of single-writer state, the
same guarantee the /stats endpoint has always given
(engine/device_matcher.py live_records).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, Optional, Tuple

from .. import __version__
from ..telemetry import (
    DEFAULT_LATENCY_BUCKETS,
    FamilySnapshot,
    MetricRegistry,
)
from ..telemetry import memory as hbm

class HttpMetrics:
    """HTTP-layer families, bound to one app registry."""

    def __init__(self, registry: MetricRegistry):
        self.requests = registry.counter(
            "duke_http_requests_total",
            "HTTP requests by route template, method and status",
            ("route", "method", "status"),
        )
        self.latency = registry.histogram(
            "duke_http_request_seconds",
            "HTTP request wall time by route template and method",
            ("route", "method"),
            buckets=DEFAULT_LATENCY_BUCKETS,
        )
        self.in_flight = registry.gauge(
            "duke_http_requests_in_flight",
            "Requests currently being served",
        )
        self.request_bytes = registry.counter(
            "duke_http_request_bytes_total",
            "Request body bytes received, by route template",
            ("route",),
        )
        self.response_bytes = registry.counter(
            "duke_http_response_bytes_total",
            "Response body bytes sent (including streamed chunks), by "
            "route template",
            ("route",),
        )
        self.busy = registry.counter(
            "duke_http_busy_total",
            "503 busy responses (workload lock unavailable within the "
            "read timeout), by route template",
            ("route",),
        )


_backend_info_cache: Optional[Tuple[str, int]] = None


def backend_info() -> Tuple[str, int]:
    """(platform, device_count) — cached after the first successful read.

    ``jax.devices()`` initializes the backend on first call; on a
    host-backend-only service that is a one-off CPU-client init paid by
    the first scrape, never by the serving path.
    """
    global _backend_info_cache
    if _backend_info_cache is None:
        try:
            import jax

            _backend_info_cache = (jax.default_backend(), jax.device_count())
        except Exception:
            return ("unavailable", 0)
    return _backend_info_cache


def _jax_version() -> str:
    try:
        import jax

        return jax.__version__
    except Exception:
        return "unavailable"


def make_process_collector():
    """Scrape-time build-info + process gauges (ISSUE 2 satellite).

    ``duke_build_info`` carries the identifying labels (service version,
    jax version, backend platform) with a constant value of 1 — the
    Prometheus idiom for joinable build metadata; the process gauges
    read ``resource.getrusage`` / ``/proc`` at scrape time so nothing is
    maintained between scrapes."""
    import resource
    import sys

    def collect():
        labels = (
            ("version", __version__),
            ("jax", _jax_version()),
            ("platform", backend_info()[0]),
        )
        out = [FamilySnapshot(
            "duke_build_info", "gauge",
            "Build/runtime identity (value is always 1)",
            [("", labels, 1.0)],
        )]
        ru = resource.getrusage(resource.RUSAGE_SELF)
        # ru_maxrss is KiB on Linux, bytes on macOS
        rss = ru.ru_maxrss * (1 if sys.platform == "darwin" else 1024)
        out.append(FamilySnapshot(
            "duke_process_max_rss_bytes", "gauge",
            "Peak resident set size (resource.getrusage ru_maxrss)",
            [("", (), float(rss))],
        ))
        try:
            fds = len(os.listdir("/proc/self/fd"))
        except OSError:
            fds = None  # non-procfs platform: omit rather than lie
        if fds is not None:
            out.append(FamilySnapshot(
                "duke_process_open_fds", "gauge",
                "Open file descriptors", [("", (), float(fds))],
            ))
        out.append(FamilySnapshot(
            "duke_process_threads", "gauge",
            "Live Python threads (threading.active_count)",
            [("", (), float(threading.active_count()))],
        ))
        # digest-keyed feature cache (ops/feature_cache.py): scrape-time
        # snapshots of the cache's plain counters, so the encode hot path
        # never writes a registry child.  Emitted (at zero) even with the
        # cache disabled — dashboards keep their series across a
        # DUKE_FEATURE_CACHE_MB=0 rollback.
        from ..ops import feature_cache as FC

        hits, misses, evicted, cache_bytes = FC.stats()
        out.append(FamilySnapshot(
            "duke_encode_rows_total", "counter",
            "Feature-encode rows by outcome: served from the digest-keyed "
            "cache (hit), freshly extracted (miss), or evicted from the "
            "cache by the byte budget (evicted)",
            [("", (("outcome", "hit"),), float(hits)),
             ("", (("outcome", "miss"),), float(misses)),
             ("", (("outcome", "evicted"),), float(evicted))],
        ))
        out.append(FamilySnapshot(
            "duke_feature_cache_bytes", "gauge",
            "Bytes held by the digest-keyed feature cache "
            "(DUKE_FEATURE_CACHE_MB bounds this)",
            [("", (), float(cache_bytes))],
        ))
        return out

    return collect


def _workload_iter(app):
    for kind, registry in (("deduplication", app.deduplications),
                           ("recordlinkage", app.record_linkages)):
        for name, wl in list(registry.items()):
            yield kind, name, wl


def make_app_collector(app):
    """Scrape-time collector over ``app``'s live workloads."""

    def collect():
        uptime = [("", (), time.monotonic() - app.started_monotonic)]
        platform, devices = backend_info()
        info = [("", (
            ("backend", app.backend), ("platform", platform),
            ("devices", str(devices)),
        ), 1.0)]

        phase_samples = []
        counter_samples: Dict[str, list] = {
            "batches": [], "records": [], "candidates": [], "pairs": [],
        }
        rows_samples = []
        capacity_samples = []
        shard_samples = []
        emb_samples = []
        ivf_cell_samples = []
        ivf_probe_samples = []
        link_samples = []
        journal_batch_samples = []
        journal_byte_samples = []
        queue_samples = []
        hold_samples = []
        warm_samples = []
        warm_seconds_samples = []
        finalize_samples = []
        finalize_threads = []
        dd_residue_samples = []
        decision_samples = []
        disagreement_samples = []
        pair_logit_samples = []
        margin_slack_samples = []
        similarity_samples = []
        cost_samples = []
        hbm_samples = []
        mesh_dd_gather_samples = []
        mesh_dd_row_samples = []
        mesh_aot_samples = []
        for kind, name, wl in _workload_iter(app):
            labels = (("kind", kind), ("workload", name))
            proc = wl.processor
            phases = getattr(proc, "phases", None)
            if phases is not None:
                phase_samples.extend(phases.collect_samples(labels))
                # device-time attribution (ISSUE 17): the same
                # PhaseRecorder totals, flattened to per-phase counters
                # that reconcile against duke_cost_busy_seconds_total
                for phase, seconds in sorted(
                        phases.phase_seconds().items()):
                    cost_samples.append(
                        ("", labels + (("phase", phase),), seconds))
            # HBM attribution (ISSUE 17): this workload's registered
            # device-buffer components from the process-wide ledger
            for comp, nbytes in sorted(hbm.components_for(wl).items()):
                hbm_samples.append(
                    ("", labels + (("component", comp),), nbytes))
            stats = getattr(proc, "stats", None)
            if stats is not None:
                counter_samples["batches"].append(
                    ("", labels, stats.batches))
                counter_samples["records"].append(
                    ("", labels, stats.records_processed))
                counter_samples["candidates"].append(
                    ("", labels, stats.candidates_retrieved))
                counter_samples["pairs"].append(
                    ("", labels, stats.pairs_compared))
            recorder = getattr(proc, "decisions", None)
            if recorder is not None and recorder.enabled:
                # quality-drift monitors (ISSUE 5): single-writer
                # engine-side state, snapshotted here at scrape time —
                # the decision path never writes a registry child
                for outcome, value in recorder.outcomes.items():
                    decision_samples.append(
                        ("", labels + (("outcome", outcome),), value))
                disagreement_samples.append(
                    ("", labels, recorder.disagreements))
                pair_logit_samples.extend(
                    recorder.pair_logit_hist.samples(labels))
                margin_slack_samples.extend(
                    recorder.margin_slack_hist.samples(labels))
                for prop, hist in list(recorder.similarity_hists.items()):
                    similarity_samples.extend(
                        hist.samples(labels + (("property", prop),)))
            finalizer = getattr(proc, "finalizer", None)
            if finalizer is not None and stats is not None:
                # finalization split: survivors rescored host-exact vs
                # skipped by the decisive band vs certified-rejected on
                # device by the dd rescore (engine.finalize, ISSUE 12)
                finalize_samples.append((
                    "", labels + (("outcome", "rescored"),),
                    stats.pairs_rescored))
                finalize_samples.append((
                    "", labels + (("outcome", "skipped"),),
                    stats.pairs_skipped))
                finalize_samples.append((
                    "", labels + (("outcome", "device_certified"),),
                    stats.pairs_device_certified))
                finalize_threads.append(("", labels, finalizer.threads))
                # why rescored pairs could not be device-certified
                dd_residue_samples.append((
                    "", labels + (("reason", "margin"),),
                    stats.dd_residue_margin))
                dd_residue_samples.append((
                    "", labels + (("reason", "kind"),),
                    stats.dd_residue_kind))
                dd_residue_samples.append((
                    "", labels + (("reason", "truncation"),),
                    stats.dd_residue_truncation))
            live = getattr(wl.index, "live_records", None)
            indexed = None
            corpus = getattr(wl.index, "corpus", None)
            if corpus is not None:
                indexed = corpus.size
                capacity_samples.append(("", labels, corpus.capacity))
                mesh = getattr(corpus, "mesh", None)
                if mesh is not None and mesh.size:
                    # record-axis sharded corpus: per-shard capacity (the
                    # HBM budget figure the sharding exists to bound)
                    shard_samples.append(
                        ("", labels, corpus.capacity // mesh.size))
                # ANN embedding footprint (ISSUE 9): host-mirror bytes of
                # the embedding tree == the device-resident bytes (same
                # dtypes/shapes), so the int8 HBM win is scrape-visible
                from ..ops import encoder as _E

                emb_tree = corpus.feats.get(_E.ANN_PROP)
                if emb_tree is not None:
                    emb_samples.append(("", labels, float(sum(
                        arr.nbytes for arr in emb_tree.values()
                    ))))
            else:
                try:
                    indexed = len(wl.index)
                except TypeError:
                    pass
            ivf = getattr(wl.index, "ivf", None)
            if ivf is not None:
                # DUKE_IVF state (0 cells = enabled but still untrained
                # below DUKE_IVF_MIN_ROWS)
                ivf_cell_samples.append(("", labels, float(ivf.ncells)))
                ivf_probe_samples.append(("", labels, float(ivf.nprobe0)))
            if indexed is not None:
                rows_samples.append(
                    ("", labels + (("state", "indexed"),), indexed))
            rows_samples.append((
                "", labels + (("state", "live"),),
                live if live is not None else (indexed or 0),
            ))
            try:
                link_samples.append(("", labels, wl.link_database.count()))
            except Exception:
                pass  # a closed/raced link DB must never fail the scrape
            # durable link journal (ISSUE 10): lock-free snapshots of the
            # journal's plain int mirrors — pending (journaled, not yet
            # applied to the durable store) batches and file bytes.  A
            # pending count that grows without draining is the flusher
            # falling behind; bytes that never compact mean the
            # watermark stopped advancing.
            journal = getattr(wl.link_database, "journal", None)
            if journal is not None:
                journal_batch_samples.append(
                    ("", labels, float(journal.pending_batches)))
                journal_byte_samples.append(
                    ("", labels, float(journal.size_bytes)))
            queue_samples.append(("", labels, len(wl._mb_queue)))
            if wl._hold_ewma is not None:
                hold_samples.append(("", labels, wl._hold_ewma))
            cache = getattr(wl.index, "scorer_cache", None) \
                if corpus is not None else None
            if cache is not None:
                warm_samples.append(
                    ("", labels, getattr(cache, "_warm_compiled", 0)))
                warm_seconds_samples.append(
                    ("", labels, getattr(cache, "_warm_seconds", 0.0)))
                mesh = getattr(wl.index, "mesh", None)
                if mesh is not None and mesh.size:
                    # sharded mesh backend (ISSUE 18): single-writer
                    # plain-int counters on the scorer cache, snapshotted
                    # here at scrape time — the scoring path never writes
                    # a registry child
                    mesh_dd_gather_samples.append(
                        ("", labels,
                         float(getattr(cache, "_dd_gathers", 0))))
                    mesh_dd_row_samples.append(
                        ("", labels,
                         float(getattr(cache, "_dd_gather_rows", 0))))
                    mesh_aot_samples.append(
                        ("", labels,
                         float(len(getattr(cache, "_aot", ()) or ()))))

        # ingest-scheduler families (ISSUE 6): scrape-time snapshots of
        # the scheduler's single-writer tenant-queue counters — the
        # dispatch path never writes a registry child, and queues for
        # reloaded-away workloads age out with their traffic
        sched_depth = []
        sched_records = []
        sched_admission = []
        sched_batches = []
        sched_merged = []
        sched_wait = []
        sched_fill = []
        sched_throttled = []
        scheduler = getattr(app, "scheduler", None)
        if scheduler is not None:
            for q in scheduler.queues():
                labels = (("kind", q.kind), ("workload", q.name))
                sched_depth.append(("", labels, len(q.pending)))
                sched_records.append(("", labels, q.queued_records()))
                sched_admission.append(
                    ("", labels + (("outcome", "admitted"),), q.admitted))
                sched_admission.append(
                    ("", labels + (("outcome", "rejected"),), q.rejected))
                sched_batches.append(("", labels, q.microbatches))
                sched_merged.append(("", labels, q.merged_requests))
                sched_wait.extend(q.wait_hist.samples(labels))
                sched_fill.extend(q.fill_hist.samples(labels))
                sched_throttled.append(("", labels, float(q.throttled)))

        out = [
            FamilySnapshot("duke_uptime_seconds", "gauge",
                           "Seconds since this DukeApp was constructed",
                           uptime),
            FamilySnapshot("duke_backend_info", "gauge",
                           "Serving backend info (value is always 1)",
                           info),
            FamilySnapshot(
                "duke_engine_phase_seconds", "histogram",
                "Per-batch engine phase durations (encode, retrieve, "
                "score, persist) by workload", phase_samples),
            FamilySnapshot("duke_engine_batches_total", "counter",
                           "Batches processed", counter_samples["batches"]),
            FamilySnapshot("duke_engine_records_processed_total", "counter",
                           "Records matched", counter_samples["records"]),
            FamilySnapshot(
                "duke_engine_candidates_retrieved_total", "counter",
                "Candidates retrieved", counter_samples["candidates"]),
            FamilySnapshot("duke_engine_pairs_compared_total", "counter",
                           "Record pairs scored", counter_samples["pairs"]),
            FamilySnapshot("duke_corpus_rows", "gauge",
                           "Corpus rows by state (indexed includes "
                           "tombstones; live excludes them)", rows_samples),
            FamilySnapshot("duke_ingest_queue_depth", "gauge",
                           "Queued ingest requests awaiting the merged "
                           "device batch", queue_samples),
            FamilySnapshot("duke_links_rows", "gauge",
                           "Rows in the workload's link store",
                           link_samples),
            FamilySnapshot("duke_write_hold_seconds", "gauge",
                           "EWMA of recent write-side workload lock holds "
                           "(the Retry-After hint source; absent until the "
                           "first write)", hold_samples),
            FamilySnapshot(
                "duke_cost_device_seconds_total", "counter",
                "Attributed device-busy seconds by workload and engine "
                "phase; sums to duke_cost_busy_seconds_total (the ledger "
                "reconciliation invariant)", cost_samples),
        ]
        if hbm_samples:
            out.append(FamilySnapshot(
                "duke_device_bytes", "gauge",
                "Registered device-buffer bytes by workload and component "
                "(corpus tensors, embeddings, int8 scales, IVF "
                "membership)", hbm_samples))
        if scheduler is not None:
            out.append(FamilySnapshot(
                "duke_sched_queue_depth", "gauge",
                "Requests pending in the ingest-scheduler queue",
                sched_depth))
            out.append(FamilySnapshot(
                "duke_sched_queue_records", "gauge",
                "Records pending in the ingest-scheduler queue",
                sched_records))
            out.append(FamilySnapshot(
                "duke_sched_admission_total", "counter",
                "Ingest requests admitted to vs rejected (429) by the "
                "scheduler's DUKE_SCHED_QUEUE_MAX bound", sched_admission))
            out.append(FamilySnapshot(
                "duke_sched_microbatches_total", "counter",
                "Coalesced microbatches dispatched to the engine",
                sched_batches))
            out.append(FamilySnapshot(
                "duke_sched_merged_requests_total", "counter",
                "Ingest requests completed through dispatched microbatches",
                sched_merged))
            out.append(FamilySnapshot(
                "duke_sched_wait_seconds", "histogram",
                "Queue wait from request enqueue to microbatch dispatch",
                sched_wait))
            out.append(FamilySnapshot(
                "duke_sched_microbatch_records", "histogram",
                "Records per dispatched microbatch (coalesced fill toward "
                "the query-padding buckets)", sched_fill))
            out.append(FamilySnapshot(
                "duke_tenant_throttled_total", "counter",
                "DRR rounds where the tenant's head request exceeded its "
                "accumulated deficit (quota throttling: delayed to later "
                "rounds, never starved — the DUKE_TENANT_MIN_SHARE floor "
                "keeps earning)", sched_throttled))
        with app._feed_abort_lock:
            abort_counts = dict(app.feed_aborts)
        out.append(FamilySnapshot(
            "duke_feed_aborts_total", "counter",
            "Feed streams aborted mid-response (chunked framing truncated) "
            "by reason: the mid-stream lock-backoff wall-clock deadline "
            "(DUKE_FEED_RETRY_DEADLINE), or workload removal by config "
            "reload (lock_starved is the pre-deadline series, kept for "
            "continuity)",
            [("", (("reason", reason),), float(count))
             for reason, count in sorted(abort_counts.items())],
        ))
        if journal_batch_samples:
            out.append(FamilySnapshot(
                "duke_journal_batches", "gauge",
                "Journaled link batches not yet applied to the durable "
                "store (the crash-recovery replay set if the process "
                "died now)", journal_batch_samples))
            out.append(FamilySnapshot(
                "duke_journal_bytes", "gauge",
                "Bytes in the append-only link journal (compacts to 0 "
                "once the applied watermark catches the head)",
                journal_byte_samples))
        if capacity_samples:
            out.append(FamilySnapshot(
                "duke_corpus_capacity_rows", "gauge",
                "Pre-allocated device corpus capacity", capacity_samples))
        if emb_samples:
            out.append(FamilySnapshot(
                "duke_emb_bytes", "gauge",
                "Bytes of the ANN embedding tree (codes + int8 scale "
                "vector when DUKE_EMB_INT8) resident per corpus row set",
                emb_samples))
        if ivf_cell_samples:
            out.append(FamilySnapshot(
                "duke_ivf_cells", "gauge",
                "Trained IVF k-means cells (0 = DUKE_IVF on but below "
                "DUKE_IVF_MIN_ROWS, flat scan serving)", ivf_cell_samples))
            out.append(FamilySnapshot(
                "duke_ivf_probe_cells", "gauge",
                "Cells probed per query at the initial candidate width "
                "(escalation widens this in lockstep with top-C)",
                ivf_probe_samples))
        if shard_samples:
            out.append(FamilySnapshot(
                "duke_corpus_capacity_rows_per_shard", "gauge",
                "Per-shard slice of the corpus capacity (sharded "
                "backends)", shard_samples))
        if mesh_dd_gather_samples:
            out.append(FamilySnapshot(
                "duke_mesh_dd_gathers_total", "counter",
                "Replicated dd survivor gathers run on the mesh — the "
                "collective that lets a fully-addressable sharded "
                "backend certify finalize verdicts on device",
                mesh_dd_gather_samples))
            out.append(FamilySnapshot(
                "duke_mesh_dd_gather_rows_total", "counter",
                "Survivor rows moved by dd gathers (queries x top_k "
                "per gather)", mesh_dd_row_samples))
            out.append(FamilySnapshot(
                "duke_mesh_aot_executables", "gauge",
                "Mesh-partitioned AOT executables resident in the "
                "sharded scorer cache", mesh_aot_samples))
        if warm_samples:
            out.append(FamilySnapshot(
                "duke_prewarm_compiles", "gauge",
                "Successful background AOT scorer compiles",
                warm_samples))
            out.append(FamilySnapshot(
                "duke_prewarm_seconds", "gauge",
                "Duration of the last AOT ladder load pass for this "
                "workload's scorer cache (the synchronous deserialize "
                "that makes a restart's first batch compile-free)",
                warm_seconds_samples))
        if finalize_samples:
            out.append(FamilySnapshot(
                "duke_finalize_pairs_total", "counter",
                "Device-scored survivors by finalization outcome: "
                "rescored host-exact, skipped by decisive-band pruning, "
                "or certified-rejected on device by the dd rescore",
                finalize_samples))
            out.append(FamilySnapshot(
                "duke_finalize_threads", "gauge",
                "Worker threads in the host-finalization pool "
                "(DUKE_FINALIZE_THREADS)", finalize_threads))
            out.append(FamilySnapshot(
                "duke_dd_residue_total", "counter",
                "Host-rescored survivors the dd rescore could not "
                "certify, by reason: ambiguous margin band, "
                "uncertifiable property kind, or an unsafe pair "
                "(tensor truncation / JW branch-boundary guard)",
                dd_residue_samples))
        if decision_samples:
            out.append(FamilySnapshot(
                "duke_decisions_total", "counter",
                "Match decisions by outcome (match, maybe, reject, "
                "pruned by the decisive band, or device_certified by "
                "the dd rescore)", decision_samples))
            out.append(FamilySnapshot(
                "duke_decision_disagreements_total", "counter",
                "Decisions where the float32 device verdict crossed a "
                "threshold the exact f64 rescore did not (or vice versa)",
                disagreement_samples))
            out.append(FamilySnapshot(
                "duke_pair_logit", "histogram",
                "Distribution of finalized pair logits (log-odds of the "
                "emitted f64 probability)", pair_logit_samples))
            out.append(FamilySnapshot(
                "duke_decisive_margin_slack", "histogram",
                "Slack below the decisive-band prune bound for skipped "
                "survivors (logit units; small slack = near-threshold "
                "skip)", margin_slack_samples))
            if similarity_samples:
                out.append(FamilySnapshot(
                    "duke_property_similarity", "histogram",
                    "Per-property comparator similarity of sampled "
                    "decisions (best value pair)", similarity_samples))
        return out

    return collect


def make_group_collector(group):
    """Scrape-time collector over one federation group's live workloads
    (ISSUE 16 fleet rollup).

    Each group gets its own ``MetricRegistry`` carrying only this
    collector; the federation plane merges all of them through
    ``telemetry.rollup.GroupRollup`` — counters and histograms summed
    key-wise across groups (lossless: every group shares the family's
    bucket ladder), gauges relabeled with ``group=``.  The collector
    therefore emits the SAME family names the leader app does, so fleet
    dashboards reuse replica queries unchanged.

    Reads are the same lock-free single-writer snapshots the app
    collector takes; nothing here acquires a workload or group lock, so
    a scrape can never stall an ingest (or another group's scrape).
    """

    def collect():
        counter_samples: Dict[str, list] = {
            "batches": [], "records": [], "candidates": [], "pairs": [],
        }
        phase_samples = []
        rows_samples = []
        link_samples = []
        queue_samples = []
        hold_samples = []
        cost_samples = []
        hbm_samples = []
        for (kind, name), wl in list(group.workloads.items()):
            labels = (("kind", kind), ("workload", name))
            proc = wl.processor
            phases = getattr(proc, "phases", None)
            if phases is not None:
                phase_samples.extend(phases.collect_samples(labels))
                for phase, seconds in sorted(
                        phases.phase_seconds().items()):
                    cost_samples.append(
                        ("", labels + (("phase", phase),), seconds))
            for comp, nbytes in sorted(hbm.components_for(wl).items()):
                hbm_samples.append(
                    ("", labels + (("component", comp),), nbytes))
            stats = getattr(proc, "stats", None)
            if stats is not None:
                counter_samples["batches"].append(
                    ("", labels, stats.batches))
                counter_samples["records"].append(
                    ("", labels, stats.records_processed))
                counter_samples["candidates"].append(
                    ("", labels, stats.candidates_retrieved))
                counter_samples["pairs"].append(
                    ("", labels, stats.pairs_compared))
            live = getattr(wl.index, "live_records", None)
            indexed = None
            corpus = getattr(wl.index, "corpus", None)
            if corpus is not None:
                indexed = corpus.size
            else:
                try:
                    indexed = len(wl.index)
                except TypeError:
                    pass
            if indexed is not None:
                rows_samples.append(
                    ("", labels + (("state", "indexed"),), indexed))
            rows_samples.append((
                "", labels + (("state", "live"),),
                live if live is not None else (indexed or 0),
            ))
            try:
                link_samples.append(("", labels, wl.link_database.count()))
            except Exception:
                pass  # a closed/raced link DB must never fail the scrape
            queue_samples.append(("", labels, len(wl._mb_queue)))
            if wl._hold_ewma is not None:
                hold_samples.append(("", labels, wl._hold_ewma))
        return [
            FamilySnapshot(
                "duke_engine_phase_seconds", "histogram",
                "Per-batch engine phase durations (encode, retrieve, "
                "score, persist) by workload", phase_samples),
            FamilySnapshot("duke_engine_batches_total", "counter",
                           "Batches processed", counter_samples["batches"]),
            FamilySnapshot("duke_engine_records_processed_total", "counter",
                           "Records matched", counter_samples["records"]),
            FamilySnapshot(
                "duke_engine_candidates_retrieved_total", "counter",
                "Candidates retrieved", counter_samples["candidates"]),
            FamilySnapshot("duke_engine_pairs_compared_total", "counter",
                           "Record pairs scored", counter_samples["pairs"]),
            FamilySnapshot("duke_corpus_rows", "gauge",
                           "Corpus rows by state (indexed includes "
                           "tombstones; live excludes them)", rows_samples),
            FamilySnapshot("duke_links_rows", "gauge",
                           "Rows in the workload's link store",
                           link_samples),
            FamilySnapshot("duke_ingest_queue_depth", "gauge",
                           "Queued ingest requests awaiting the merged "
                           "device batch", queue_samples),
            FamilySnapshot("duke_write_hold_seconds", "gauge",
                           "EWMA of recent write-side workload lock holds "
                           "(the Retry-After hint source; absent until the "
                           "first write)", hold_samples),
            FamilySnapshot(
                "duke_cost_device_seconds_total", "counter",
                "Attributed device-busy seconds by workload and engine "
                "phase; sums to duke_cost_busy_seconds_total (the ledger "
                "reconciliation invariant)", cost_samples),
            FamilySnapshot(
                "duke_device_bytes", "gauge",
                "Registered device-buffer bytes by workload and component "
                "(corpus tensors, embeddings, int8 scales, IVF "
                "membership)", hbm_samples),
        ]

    return collect
