"""CLI entrypoint: ``python -m sesam_duke_microservice_tpu.service``.

Equivalent of the reference's ``java -jar`` entrypoint (Dockerfile:8): loads
CONFIG_STRING or the bundled demo config and serves on port 4567 (PORT env /
--port override).  ``--backend device`` selects the TPU matcher.
"""

import argparse
import logging

from ..telemetry.env import env_int, env_str
from .app import DEFAULT_PORT, create_app, install_shutdown_handlers, serve


def main() -> None:
    parser = argparse.ArgumentParser(description="TPU-native Duke record-matching microservice")
    parser.add_argument("--port", type=int,
                        default=env_int("PORT", DEFAULT_PORT))
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--backend",
                        choices=["host", "device", "ann", "sharded",
                                 "sharded-brute"],
                        default=env_str("DUKE_TPU_BACKEND", "host"))
    parser.add_argument("--ephemeral", action="store_true",
                        help="keep all state in memory (no data folder writes)")
    parser.add_argument("--federation", type=int,
                        default=env_int("DUKE_FED_GROUPS", 0), metavar="N",
                        help="serve a digest-range partition federation of "
                             "N serving groups (ISSUE 14) instead of one "
                             "group — scatter-gather ingest/feeds, live "
                             "range migration via POST /federation/migrate")
    args = parser.parse_args()

    logging.basicConfig(level=logging.INFO)
    # structured logging: every line carries the per-request id the HTTP
    # handler stamps (telemetry.logctx) — engine lines produced on the
    # request thread inherit it through the context var
    from ..telemetry.logctx import install as install_request_ids

    install_request_ids()
    if args.backend in ("device", "ann", "sharded", "sharded-brute"):
        from ..utils.jit_cache import enable_persistent_cache

        enable_persistent_cache()

    log = logging.getLogger("duke-tpu-service")

    if args.federation >= 1:
        # federation tier (ISSUE 14): N independent serving groups in
        # this process behind the digest-range partition router.  N=1 is
        # a legitimate degenerate federation (one group, federated data
        # layout and /federation/* surface) — silently falling back to
        # the standalone service would read a DIFFERENT data layout and
        # hide existing federated state behind a 200.  (The
        # production shape — each group its own HA serving group on its
        # own hosts — slots an RPC client into the LocalGroup seam;
        # this entrypoint is the single-box topology.)
        import signal
        import threading

        from ..core.config import load_default_config
        from ..federation import Federation
        from .federation_plane import serve_federation

        fed = Federation(load_default_config(),
                         n_groups=args.federation, backend=args.backend)
        server = serve_federation(fed, port=args.port, host=args.host)
        log.info("Federation of %d groups serving on %s:%d (backend=%s)",
                 args.federation, args.host,
                 server.server_address[1], args.backend)
        stop = threading.Event()

        def _stop(signum, frame):
            log.info("signal %d: federation shutdown", signum)
            stop.set()

        signal.signal(signal.SIGTERM, _stop)
        signal.signal(signal.SIGINT, _stop)
        stop.wait()
        server.shutdown()
        fed.close()
        log.info("shutdown complete")
        return

    # multi-host serving (SURVEY.md section 5.8): join the jax.distributed
    # job first; process 0 becomes the HTTP frontend + op dispatcher,
    # every other process runs the follower replay loop (no HTTP).
    dispatcher = None
    from ..parallel import multihost

    multihost.initialize()
    import jax

    if jax.process_count() > 1:
        from ..parallel.dispatch import follower_main, start_dispatcher

        if jax.process_index() != 0:
            log.info(
                "process %d/%d: follower mode (frontend is process 0%s%s)",
                jax.process_index(), jax.process_count(),
                "; replica read plane on :%s" % env_int(
                    "DUKE_REPLICA_HTTP_PORT", 0)
                if env_int("DUKE_REPLICA_HTTP_PORT", 0) else "",
                "; promotes on leader loss to :%s" % env_int(
                    "DUKE_PROMOTE_PORT", 0)
                if env_int("DUKE_PROMOTE_PORT", 0) else "",
            )
            follower_main()
            return
        app = create_app(backend=args.backend,
                         persistent=not args.ephemeral)
        dispatcher = start_dispatcher(app)
    else:
        app = create_app(backend=args.backend, persistent=not args.ephemeral)
    server = serve(app, port=args.port, host=args.host)
    log.info(
        "Serving on %s:%d (backend=%s%s)", args.host, args.port, args.backend,
        f", {jax.process_count()} hosts" if dispatcher else "",
    )

    # graceful shutdown on SIGTERM (docker stop) / SIGINT (ISSUE 10):
    # drain scheduler -> flush write-behind (journal compacts to empty)
    # -> save snapshots -> close, so orchestrated restarts never need
    # journal recovery (service.app.install_shutdown_handlers)
    install_shutdown_handlers(app, server)
    # (SIGINT is rebound above, so no KeyboardInterrupt path exists)
    try:
        server.serve_forever()
    finally:
        app.close()  # idempotent: no-op when the handler already closed
        if dispatcher is not None:
            dispatcher.close()
        log.info("shutdown complete")


if __name__ == "__main__":
    main()
