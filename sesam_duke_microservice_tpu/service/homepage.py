"""Homepage HTML (GET /) — endpoint directory + config upload form.

Serves the role of the reference's Jinjava template
(src/main/resources/templates/index.html, rendered at App.java:653-660):
lists every workload's endpoints and offers the config upload form.  The
reference's label bug (SURVEY.md quirk Q4: recordlinkage link *text* rendered
with the wrong variable) is fixed here.
"""

from __future__ import annotations

from html import escape


def render_homepage(app) -> str:
    rows = []

    def link(href: str) -> str:
        return f'<a href="{escape(href)}">{escape(href)}</a>'

    rows.append("<h2>Deduplications</h2>")
    if not app.deduplications:
        rows.append("<p><i>none configured</i></p>")
    for name, wl in sorted(app.deduplications.items()):
        rows.append(f"<h3>{escape(name)}</h3><ul>")
        rows.append(f"<li>GET {link(f'/deduplication/{name}')} &mdash; incremental link feed (?since=N)</li>")
        for dataset_id in sorted(wl.datasources):
            rows.append(
                f"<li>POST {link(f'/deduplication/{name}/{dataset_id}')} &mdash; ingest+match a JSON batch</li>"
            )
            rows.append(
                f"<li>POST {link(f'/deduplication/{name}/{dataset_id}/httptransform')} &mdash; side-effect-free transform</li>"
            )
        rows.append("</ul>")

    rows.append("<h2>Record linkages</h2>")
    if not app.record_linkages:
        rows.append("<p><i>none configured</i></p>")
    for name, wl in sorted(app.record_linkages.items()):
        rows.append(f"<h3>{escape(name)}</h3><ul>")
        rows.append(f"<li>GET {link(f'/recordlinkage/{name}')} &mdash; incremental link feed (?since=N)</li>")
        for dataset_id in sorted(wl.datasources):
            rows.append(
                f"<li>POST {link(f'/recordlinkage/{name}/{dataset_id}')} &mdash; ingest+match a JSON batch</li>"
            )
            rows.append(
                f"<li>POST {link(f'/recordlinkage/{name}/{dataset_id}/httptransform')} &mdash; side-effect-free transform</li>"
            )
        rows.append("</ul>")

    rows.append("<h2>Operations</h2><ul>")
    rows.append(
        f"<li>GET {link('/healthz')} &mdash; liveness probe "
        "(alias: /health)</li>"
    )
    rows.append(
        f"<li>GET {link('/readyz')} &mdash; readiness probe (config, "
        "workloads, device backend)</li>"
    )
    rows.append(
        f"<li>GET {link('/metrics')} &mdash; Prometheus metrics "
        "(HTTP, engine phases, corpus, JIT)</li>"
    )
    rows.append(
        f"<li>GET {link('/stats')} &mdash; per-workload counters "
        "(records, batches, pairs, timings)</li>"
    )
    rows.append(
        "<li>POST /{deduplication|recordlinkage}/:name/rematch &mdash; "
        "ring bulk re-match / link-DB backfill (device backends)</li>"
    )
    rows.append(
        f"<li>GET {link('/debug/traces')} &mdash; flight recorder "
        "(retained traces; /debug/traces/&lt;id&gt;?format=chrome for "
        "Perfetto)</li>"
    )
    rows.append(
        f"<li>GET {link('/debug/requests')} &mdash; last-N request "
        "digests with per-phase timings</li>"
    )
    rows.append(
        "<li>POST /debug/profile?seconds=N &mdash; on-demand "
        "jax.profiler device capture</li>"
    )
    rows.append("</ul>")

    body = "\n".join(rows)
    return f"""<!DOCTYPE html>
<html>
<head><title>Duke microservice (TPU)</title></head>
<body>
<h1>Duke record-matching microservice &mdash; TPU-native</h1>
<p>The active configuration is served at <a href="/config">/config</a>.</p>
{body}
<h2>Upload new configuration</h2>
<form method="post" action="/config" enctype="multipart/form-data">
  <input type="file" name="configfile"/>
  <input type="submit" value="Upload"/>
</form>
</body>
</html>
"""
