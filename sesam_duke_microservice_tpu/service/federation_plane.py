"""Federation HTTP frontend (ISSUE 14 tentpole).

The single-group service (``service/app.py``) serves one workload stack;
this plane serves a ``federation.Federation`` — N serving groups behind
the digest-range partition router:

  * ``POST /{kind}/{name}/{datasetId}`` — federated ingest: the batch
    partitions by owner group and fans out.  Frozen ranges (a live
    migration) answer 429 + Retry-After for the whole batch; a scatter
    partial failure answers 503 with the degraded-range list in the
    error body and Retry-After = the max across contacted groups
    (backpressure propagates through the router).
  * ``GET /{kind}/{name}?since=<token>`` — the merged federated feed.
    ``since`` is the OPAQUE composite cursor (``federation.ranges``
    encode/decode; a bare integer is accepted as the legacy pre-
    federation cursor).  One bounded page per request: the next token
    rides ``X-Fed-Next-Since`` and ``X-Fed-Drained: true`` marks the
    end of the backlog — clients poll, they do not stream.  With a dead
    group, live ranges' rows still flow; the dead ranges are listed in
    ``X-Fed-Degraded-Ranges`` with a Retry-After hint, and their
    cursors in the returned token are untouched, so the client resumes
    them loss-free once the group returns.
  * ``POST /federation/migrate`` (``{"range": id, "target": group}``) —
    live range rebalancing (federation/migrate.py); ``GET
    /federation/map`` and ``GET /federation/migration`` expose the
    partition map and migration status.
  * ``/healthz`` / ``/readyz`` / ``/stats`` / ``/metrics`` — health with
    per-group detail; ``/readyz`` answers ``recovering`` while ANY
    group's journal replay runs (scoped: other processes' groups do not
    leak in) and ``degraded`` when a group is down.

``duke_fed_*`` metric families (scrape-time snapshots — the router hot
path writes plain counters under its own lock, never a registry child):
``duke_fed_groups``, ``duke_fed_group_up``,
``duke_fed_group_seconds_since_contact``, ``duke_fed_degraded_ranges``,
``duke_fed_migration_phase``, ``duke_fed_migrations_total``,
``duke_fed_requests_total``, and per-range scatter series
``duke_fed_range_requests_total`` / ``duke_fed_range_latency_seconds``.

Observability plane (ISSUE 16): every request opens a W3C-propagating
root span (inbound ``traceparent`` honored, ``X-Request-Id`` /
``X-Trace-Id`` reply headers), ``/debug/traces`` + ``/debug/requests``
serve the plane's flight recorder — a retained federated ingest shows
the plane root, the router's partition/fan-out/merge spans AND each
group's re-anchored engine subtree as one causal tree —
``/debug/migrations`` returns the migrator's retained phase-timeline
ring, and ``/metrics`` additionally renders the fleet rollup: every
group's registry merged through ``telemetry.rollup.GroupRollup``
(counters/histograms summed, gauges relabeled ``group=``).
"""

from __future__ import annotations

import json
import logging
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

from .. import telemetry
from ..federation import Federation
from ..federation.migrate import PHASE_CODES
from ..federation.ranges import BadCursor, StaleRouterEpoch
from ..federation.router import (
    FrozenRange,
    PartialIngestFailure,
    UnknownFederatedWorkload,
)
from ..telemetry import FamilySnapshot, MetricRegistry, heat, slo, tracing
from ..telemetry.logctx import new_request_id, request_id_var
from ..telemetry.probes import probes_enabled
from ..telemetry.registry import DEFAULT_LATENCY_BUCKETS, histogram_snapshot
from ..telemetry.rollup import GroupRollup
from . import debug as debug_api
from .app import (
    _DEBUG_TRACE_PATH,
    _ENTITY_PATH,
    _FEED_PATH,
    _feed_page_size,
    _kind_label,
)
from .metrics import make_group_collector

logger = logging.getLogger("federation-plane")


def make_federation_collector(fed: Federation):
    """Scrape-time ``duke_fed_*`` families off the router's and
    migrator's plain single-writer counters."""

    def collect():
        router = fed.router
        health = router.group_health()
        degraded = router.degraded_range_ids()
        now = time.monotonic()
        up_samples = []
        contact_samples = []
        for row in health:
            labels = (("group", str(row["group"])),)
            up_samples.append(("", labels, 1.0 if row["up"] else 0.0))
            last = router.last_contact(row["group"])
            contact_samples.append(
                ("", labels, round(now - last, 3) if last else -1.0))
        outcomes = router.outcomes_snapshot()
        range_req_samples = []
        range_lat_samples = []
        for rid, (by_outcome, hist) in sorted(
                router.range_stats_snapshot().items()):
            for outcome, n in sorted(by_outcome.items()):
                range_req_samples.append(
                    ("", (("range", rid), ("outcome", outcome)), float(n)))
            counts, total, count = hist
            range_lat_samples.extend(histogram_snapshot(
                DEFAULT_LATENCY_BUCKETS, counts, total, count,
                (("range", rid),)))
        return [
            FamilySnapshot(
                "duke_fed_groups", "gauge",
                "Serving groups in the federation",
                [("", (), float(len(fed.groups)))]),
            FamilySnapshot(
                "duke_fed_group_up", "gauge",
                "1 while the group's last scatter contact succeeded "
                "(0 = its ranges are degraded)", up_samples),
            FamilySnapshot(
                "duke_fed_group_seconds_since_contact", "gauge",
                "Seconds since the router last reached the group "
                "(-1 = never contacted): replication-style lag for the "
                "scatter plane", contact_samples),
            FamilySnapshot(
                "duke_fed_degraded_ranges", "gauge",
                "Digest ranges currently owned by an unreachable group "
                "(their queries 503 with Retry-After; the rest serve)",
                [("", (), float(len(degraded)))]),
            FamilySnapshot(
                "duke_fed_migration_phase", "gauge",
                "Live range-migration phase (0 idle, 1 frozen, 2 "
                "copied, 3 cutover, 4 drain)",
                [("", (), float(fed.migrator.phase_code()))]),
            FamilySnapshot(
                "duke_fed_migrations_total", "counter",
                "Range migrations by outcome (completed, resumed after "
                "a crash, failed)",
                [("", (("outcome", k),), float(v))
                 for k, v in sorted(fed.migrator.outcomes.items())]),
            FamilySnapshot(
                "duke_fed_requests_total", "counter",
                "Federated router requests by outcome (ok, degraded = "
                "scatter partial failure, frozen = 429 on a migrating "
                "range)",
                [("", (("outcome", k),), float(v))
                 for k, v in sorted(outcomes.items())]),
            FamilySnapshot(
                "duke_fed_range_requests_total", "counter",
                "Scatter calls that touched the range, by per-group "
                "outcome (ok, retried = ok after transient retries, "
                "degraded = group unreachable, stale-epoch = fenced by "
                "a concurrent cutover)", range_req_samples),
            FamilySnapshot(
                "duke_fed_range_latency_seconds", "histogram",
                "Per-range scatter-call latency (group call including "
                "router-side retries)", range_lat_samples),
            # sub-range heat rollup (ISSUE 17): 256-bucket load
            # histogram per owned range, non-zero buckets only
            heat.collect_family(router.heat),
        ]

    return collect


_FED_STATIC_ROUTES = frozenset((
    "/health", "/healthz", "/readyz", "/stats", "/metrics",
    "/federation/map", "/federation/migration", "/federation/migrate",
    "/debug/traces", "/debug/requests", "/debug/migrations",
    "/debug/profile", "/debug/profile/reset",
    "/debug/costs", "/debug/memory", "/debug/loadmap", "/debug/slo",
    "/debug/probes",
))


def _fed_route_template(path: str) -> str:
    """Low-cardinality route label for span names (same collapse rules
    as the group plane's ``_route_template``)."""
    if path in _FED_STATIC_ROUTES:
        return path
    if _DEBUG_TRACE_PATH.match(path):
        return "/debug/traces/:id"
    if m := _ENTITY_PATH.match(path):
        suffix = "/httptransform" if m.group(4) else ""
        return f"/{m.group(1)}:name/:datasetId{suffix}"
    if m := _FEED_PATH.match(path):
        return f"/{m.group(1)}:name"
    return "<unmatched>"


class FederationHandler(BaseHTTPRequestHandler):
    fed: Federation = None  # set by serve_federation()
    registry: MetricRegistry = None
    rollup: GroupRollup = None
    range_prober = None  # set by serve_federation() when DUKE_PROBE=1
    protocol_version = "HTTP/1.1"

    # class-level defaults keep _reply safe for direct/test callers that
    # bypass _handle_request
    request_id: str = "-"
    trace_id: str = "-"

    def log_message(self, fmt, *args):
        logger.info("%s %s", self.address_string(), fmt % args)

    # -- plumbing -------------------------------------------------------------

    def _handle_request(self, method: str, route_fn) -> None:
        """Root-span wrapper (ISSUE 16): every plane request opens a
        trace that honors an inbound W3C ``traceparent`` — the router's
        partition/fan-out/merge spans and each group's re-anchored
        subtree parent under it, so ``/debug/traces`` shows one causal
        tree per federated request.  ``POST /federation/migrate`` forces
        retention (``sampled=True``): migrations are rare, operator-
        initiated, and their phase timeline must survive sampling."""
        parsed = urlparse(self.path)
        route = _fed_route_template(parsed.path)
        self.request_id = new_request_id()
        request_id_var.set(self.request_id)
        with tracing.start_trace(
            f"{method} {route}",
            traceparent=self.headers.get("traceparent"),
            sampled=True if route == "/federation/migrate" else None,
            attributes={
                "http.method": method,
                "http.route": route,
                "http.target": parsed.path,
                "request_id": self.request_id,
            },
        ) as root:
            self.trace_id = root.trace_id
            try:
                route_fn(parsed)
            finally:
                request_id_var.set("-")

    def _reply(self, status: int, body: bytes,
               content_type: str = "application/json",
               extra_headers: Optional[dict] = None) -> None:
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.send_header("X-Request-Id", self.request_id)
        self.send_header("X-Trace-Id", self.trace_id)
        for k, v in (extra_headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        try:
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            logger.info("Ignoring client disconnect on %s", self.path)

    def _reply_json(self, status: int, obj, extra_headers=None) -> None:
        self._reply(status, json.dumps(obj).encode("utf-8"),
                    extra_headers=extra_headers)

    def _read_body(self) -> bytes:
        try:
            length = int(self.headers.get("Content-Length") or 0)
        except ValueError:
            length = 0
        return self.rfile.read(length) if length > 0 else b""

    # -- routing --------------------------------------------------------------

    def do_GET(self):
        try:
            self._handle_request("GET", self._route_get)
        except Exception:
            logger.exception("federation plane: error serving %s", self.path)
            self._reply(500, b"Internal server error", "text/plain")

    def do_POST(self):
        body = self._read_body()
        try:
            self._handle_request(
                "POST", lambda parsed: self._route_post(parsed, body))
        except Exception:
            logger.exception("federation plane: error serving %s", self.path)
            self._reply(500, b"Internal server error", "text/plain")

    def _route_get(self, parsed) -> None:
        path = parsed.path
        if path in ("/health", "/healthz"):
            self._handle_healthz()
        elif path == "/readyz":
            self._handle_readyz()
        elif path == "/stats":
            self._handle_stats()
        elif path == "/metrics":
            # plane families + process-wide GLOBAL + the fleet rollup
            # (each group's registry collected sequentially, merged
            # sum/relabel — see telemetry/rollup.py)
            body = telemetry.render(self.registry, telemetry.GLOBAL,
                                    self.rollup).encode("utf-8")
            self._reply(200, body, telemetry.CONTENT_TYPE)
        elif path == "/federation/map":
            self._reply_json(200, self.fed.map.to_json())
        elif path == "/federation/migration":
            self._reply_json(200, self.fed.migration_status())
        elif path == "/debug/traces":
            self._reply(*debug_api.handle_traces())
        elif m := _DEBUG_TRACE_PATH.match(path):
            fmt = (parse_qs(parsed.query).get("format") or ["json"])[0]
            self._reply(*debug_api.handle_trace(m.group(1), fmt))
        elif path == "/debug/requests":
            self._reply(*debug_api.handle_requests())
        elif path == "/debug/migrations":
            self._reply_json(200, {
                "migrations": self.fed.migrator.timelines_snapshot()})
        elif path == "/debug/profile":
            self._reply(*debug_api.handle_profile_status())
        elif path == "/debug/costs":
            # reconcile against every group's workloads: the federation
            # process runs them all, so the plane-wide attribution must
            # cover them all to balance the process-wide busy ledger
            self._reply(*debug_api.handle_costs(
                (kind, name, wl)
                for g in self.fed.groups
                for (kind, name), wl in list(g.workloads.items())))
        elif path == "/debug/memory":
            self._reply(*debug_api.handle_memory())
        elif path == "/debug/loadmap":
            self._reply(*debug_api.handle_loadmap(self.fed.router.heat))
        elif path == "/debug/slo":
            self._reply(*debug_api.handle_slo())
        elif path == "/debug/probes":
            if self.range_prober is None:
                self._reply_json(200, {"enabled": False})
            else:
                self._reply_json(200, self.range_prober.snapshot())
        elif m := _FEED_PATH.match(path):
            self._handle_feed(m, parse_qs(parsed.query))
        else:
            self._reply(404, b"Not found", "text/plain")

    def _route_post(self, parsed, body: bytes) -> None:
        path = parsed.path
        if path == "/federation/migrate":
            self._handle_migrate(body)
        elif path == "/debug/profile":
            # ISSUE 17 satellite: device captures through the federated
            # front door; the owner tag makes a cross-plane conflict
            # 409 carry who holds the profiler and its deadline
            self._reply(*debug_api.handle_profile_start(
                parse_qs(parsed.query), owner="federation"))
        elif path == "/debug/profile/reset":
            self._reply(*debug_api.handle_profile_reset())
        elif m := _ENTITY_PATH.match(path):
            self._handle_ingest(m, body)
        else:
            self._reply(404, b"Not found", "text/plain")

    # -- health ---------------------------------------------------------------

    def _recovering_scopes(self):
        from ..links import journal as link_journal

        return [f for f in self.fed.group_folders()
                if link_journal.recovery_active(f)]

    def _handle_healthz(self) -> None:
        degraded = self.fed.router.degraded_range_ids()
        health = {
            "status": "ok" if not degraded else "degraded",
            "role": "federation-router",
            "groups": len(self.fed.groups),
            "epoch": self.fed.map.epoch,
            "degraded_ranges": degraded,
        }
        # black-box overlay: a range whose reachability probe failed is
        # degraded even when the router hasn't contacted it yet
        if self.range_prober is not None:
            failing = self.range_prober.failing_ranges()
            if failing:
                health["status"] = "degraded"
                health["probe_failing_ranges"] = failing
        self._reply_json(200, health)

    def _handle_readyz(self) -> None:
        recovering = self._recovering_scopes()
        degraded = self.fed.router.degraded_range_ids()
        checks = {
            "recovery_complete": not recovering,
            "groups_reachable": not degraded,
            "migration_idle": not self.fed.migration_status()["active"],
        }
        if recovering:
            status = "recovering"
        elif degraded:
            status = "degraded"
        elif not checks["migration_idle"]:
            # still 200: the federation serves during a migration (only
            # the moving range's writes 429) — the status string is the
            # operator signal, not a readiness failure
            status = "migrating"
        else:
            status = "ready"
        ready = checks["recovery_complete"] and checks["groups_reachable"]
        self._reply_json(200 if ready else 503, {
            "status": status,
            "checks": checks,
            "recovering_scopes": recovering,
            "degraded_ranges": degraded,
        })

    def _handle_stats(self) -> None:
        fed = self.fed
        groups = []
        for g, health in zip(fed.groups, fed.router.group_health()):
            row = dict(health)
            row["workloads"] = []
            for (kind, name), wl in g.workloads.items():
                live = getattr(wl.index, "live_records", None)
                wrow = {
                    "kind": kind,
                    "name": name,
                    "records_indexed": (live if live is not None
                                        else len(wl.index)),
                }
                try:
                    wrow["links_rows"] = wl.link_database.count()
                except Exception:
                    pass
                row["workloads"].append(wrow)
            groups.append(row)
        self._reply_json(200, {
            "role": "federation-router",
            "map": fed.map.to_json(),
            "migration": fed.migration_status(),
            "requests": fed.router.outcomes_snapshot(),
            "groups": groups,
        })

    # -- ingest ---------------------------------------------------------------

    def _handle_ingest(self, m, body: bytes) -> None:
        kind, name, dataset_id, transform = (
            m.group(1), m.group(2), m.group(3), bool(m.group(4)))
        label = _kind_label(kind)
        if transform:
            self._reply(400, b"httptransform is not federated; POST it "
                        b"to a group plane directly", "text/plain")
            return
        try:
            payload = json.loads(body.decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            self._reply(400, b"Request body must be a JSON array or "
                        b"object", "text/plain")
            return
        batch = [payload] if isinstance(payload, dict) else payload
        if (not isinstance(batch, list)
                or any(not isinstance(e, dict) for e in batch)):
            self._reply(400, b"Request body must be a JSON array or "
                        b"object", "text/plain")
            return
        from ..service.datasource import IngestError

        try:
            result = self.fed.router.submit(kind, name, dataset_id, batch)
        except IngestError as e:
            # routing needs each entity's record id, so a missing/empty
            # _id surfaces here rather than inside a group
            self._reply(400, str(e).encode(), "text/plain")
        except UnknownFederatedWorkload:
            self._reply(404, (f"Unknown {label} '{name}' or dataset "
                              f"'{dataset_id}'!").encode(), "text/plain")
        except FrozenRange as e:
            self._reply_json(429, {
                "error": str(e),
                "frozen_ranges": e.range_ids,
                "retry_after": e.retry_after,
            }, extra_headers={"Retry-After": str(e.retry_after)})
        except StaleRouterEpoch as e:
            # refreshed once and still stale: topology is moving faster
            # than this router; the client retries shortly
            self._reply_json(503, {"error": str(e)},
                            extra_headers={"Retry-After": "1"})
        except PartialIngestFailure as e:
            self._reply_json(503, {
                "error": str(e),
                "degraded_ranges": e.degraded_ranges,
                "group_errors": e.errors,
                "retry_after": e.retry_after,
            }, extra_headers={"Retry-After": str(e.retry_after)})
        except Exception as e:
            logger.exception("federated ingest failed")
            self._reply(500, f"Batch processing failed: {e}".encode(),
                        "text/plain")
        else:
            self._reply_json(200, result)

    # -- federated feed -------------------------------------------------------

    def _handle_feed(self, m, query) -> None:
        kind, name = m.group(1), m.group(2)
        label = _kind_label(kind)
        if not name:
            self._reply(400, f"The {label}Name cannot be an empty "
                        f"string!".encode(), "text/plain")
            return
        token = (query.get("since") or [""])[0]
        t0 = time.monotonic()
        try:
            page = self.fed.router.feed_page(kind, name, token,
                                             _feed_page_size())
        except BadCursor as e:
            self._reply(400, f"Invalid since value: {e}".encode(),
                        "text/plain")
            return
        except UnknownFederatedWorkload:
            self._reply(400, (f"Unknown {label} '{name}'! (All {label}s "
                              f"must be specified in the "
                              f"configuration)").encode(), "text/plain")
            return
        # always-on feed SLO signal + lag meter (ISSUE 16): page latency
        # against DUKE_SLO_FEED_MS; a fully-drained page marks the feed
        # caught up, so duke_feed_lag_seconds stops aging
        slo.tracker("feed", kind, name).record(time.monotonic() - t0)
        if page["drained"]:
            slo.feed_meter(kind, name).note_drain()
        headers = {
            "X-Fed-Next-Since": page["next_since"],
            "X-Fed-Drained": "true" if page["drained"] else "false",
        }
        if page["degraded_ranges"]:
            headers["X-Fed-Degraded-Ranges"] = ",".join(
                page["degraded_ranges"])
            headers["Retry-After"] = str(page["retry_after"]
                                         or 1)
        body = ("[" + ",\n".join(json.dumps(r) for r in page["rows"])
                + "]").encode("utf-8")
        self._reply(200, body, extra_headers=headers)

    # -- admin: migration -----------------------------------------------------

    def _handle_migrate(self, body: bytes) -> None:
        try:
            req = json.loads(body.decode("utf-8"))
            range_id = str(req["range"])
            target = int(req["target"])
        except (ValueError, KeyError, TypeError, UnicodeDecodeError):
            self._reply(400, b'Body must be {"range": "<id>", '
                        b'"target": <group>}', "text/plain")
            return
        try:
            result = self.fed.migrate_range(range_id, target)
        except KeyError:
            self._reply(404, f"Unknown range '{range_id}'".encode(),
                        "text/plain")
        except (ValueError, RuntimeError) as e:
            self._reply(409, str(e).encode(), "text/plain")
        except Exception as e:
            logger.exception("migration failed")
            self._reply(500, f"Migration failed: {e}".encode(),
                        "text/plain")
        else:
            self._reply_json(200, result)


def serve_federation(fed: Federation, port: int = 0,
                     host: str = "127.0.0.1") -> ThreadingHTTPServer:
    """Bind the federation plane and serve it on a daemon thread;
    returns the server (caller owns ``shutdown()``)."""
    registry = MetricRegistry()
    registry.register_collector(make_federation_collector(fed))
    # fleet rollup (ISSUE 16): one registry per group, each carrying a
    # lock-free workload-walking collector; GroupRollup snapshots them
    # sequentially at scrape, so no group lock is ever held across
    # another group's collection
    # black-box range reachability probing (ISSUE 20): per-range probes
    # through the group read path; each group's registry carries the
    # collector for ITS ranges so the rollup merges the fleet view
    prober = None
    if probes_enabled():
        from .prober import RangeProber

        prober = RangeProber(fed)
    group_regs = []
    for g in fed.groups:
        reg = MetricRegistry()
        reg.register_collector(make_group_collector(g))
        if prober is not None:
            reg.register_collector(prober.collector_for(g.idx))
        group_regs.append((str(g.idx), reg))
    rollup = GroupRollup(group_regs)
    handler = type("BoundFederationHandler", (FederationHandler,),
                   {"fed": fed, "registry": registry, "rollup": rollup,
                    "range_prober": prober})
    server = ThreadingHTTPServer((host, port), handler)
    if prober is not None:
        prober.start()
        # stop the probe thread with the plane: shutdown() is the
        # caller-owned teardown seam
        orig_shutdown = server.shutdown

        def _shutdown():
            prober.stop()
            orig_shutdown()

        server.shutdown = _shutdown
    thread = threading.Thread(target=server.serve_forever,
                              name="federation-plane", daemon=True)
    thread.start()
    logger.info("federation plane serving %d group(s) on %s:%d",
                len(fed.groups), host, server.server_address[1])
    return server


__all__ = ["FederationHandler", "make_federation_collector",
           "serve_federation", "PHASE_CODES"]
