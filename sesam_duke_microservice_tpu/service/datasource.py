"""Ingest datasource: JSON entity batches -> Records.

Reproduces IncrementalDataSource.java:36-102: each entity requires a
non-empty ``_id``; configured columns map JSON fields through optional
cleaners into properties; the record id is synthesized as
``[groupNo__]datasetId__entityId`` and the hidden properties
(dukeOriginalEntityId, dukeDatasetId, dukeGroupNo, dukeDeleted) are attached.

Divergence (SURVEY.md quirk Q1, deliberate fix): the reference crashes on
multi-element array values (it stringifies the *array* per element,
IncrementalDataSource.java:69-73); here each element is converted
individually, so array-valued columns behave as multi-valued properties.
"""

from __future__ import annotations

from typing import Iterable, List, Optional

from ..core.config import DataSourceConfig
from ..core.records import (
    DATASET_ID_PROPERTY_NAME,
    DELETED_PROPERTY_NAME,
    GROUP_NO_PROPERTY_NAME,
    ID_PROPERTY_NAME,
    ORIGINAL_ENTITY_ID_PROPERTY_NAME,
    Record,
)


class IngestError(Exception):
    pass


def _json_value_to_string(value) -> Optional[str]:
    """JSON scalar -> string, Gson getAsString conventions: booleans are
    'true'/'false', numbers use their plain representation."""
    if value is None:
        return None
    if isinstance(value, bool):
        return "true" if value else "false"
    if isinstance(value, float) and value.is_integer():
        return str(int(value))
    return str(value)


class IncrementalDataSource:
    def __init__(self, config: DataSourceConfig):
        self.config = config
        self.dataset_id = config.dataset_id
        self.group_no = config.group_no

    def record_id_for_entity(self, entity: dict) -> str:
        """The store record id this datasource will synthesize for
        ``entity`` (``[groupNo__]datasetId__entityId``) — THE one copy of
        the id rule, shared with ``record_for_entity`` so the federation
        router's digest-range routing key (federation.ranges.route_key
        over this id) can never drift from the id the ingest path
        actually stores."""
        entity_id = _json_value_to_string(entity.get("_id"))
        if not entity_id:
            raise IngestError("Got an entity with no '_id' attribute!")
        if self.group_no is not None:
            return f"{self.group_no}__{self.dataset_id}__{entity_id}"
        return f"{self.dataset_id}__{entity_id}"

    def record_for_entity(self, entity: dict) -> Record:
        entity_id = _json_value_to_string(entity.get("_id"))
        if not entity_id:
            raise IngestError("Got an entity with no '_id' attribute!")

        record = Record()
        for column in self.config.columns:
            raw = entity.get(column.name)
            if raw is None:
                continue
            values = raw if isinstance(raw, list) else [raw]
            for v in values:
                s = _json_value_to_string(v)
                if s is None or s == "":
                    continue
                if column.cleaner is not None:
                    s = column.cleaner(s)
                record.add_value(column.property, s)

        if self.group_no is not None:
            record.add_value(GROUP_NO_PROPERTY_NAME, str(self.group_no))
        record_id = self.record_id_for_entity(entity)

        record.add_value(ID_PROPERTY_NAME, record_id)
        record.add_value(ORIGINAL_ENTITY_ID_PROPERTY_NAME, entity_id)
        record.add_value(DATASET_ID_PROPERTY_NAME, self.dataset_id)

        if entity.get("_deleted"):
            record.add_value(DELETED_PROPERTY_NAME, "true")
        return record

    def records_for_batch(self, batch: Iterable[dict]) -> List[Record]:
        return [self.record_for_entity(e) for e in batch]
