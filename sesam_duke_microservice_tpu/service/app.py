"""The HTTP frontend — full reference REST surface.

Route-for-route reproduction of App.java:649-887 on the stdlib threading
HTTP server (the reference uses Spark-Java/Jetty on port 4567):

    GET  /                                                homepage
    GET  /config                                          active XML verbatim
    POST /config                                          multipart hot reload
    POST /deduplication/:name/:datasetId                  ingest+match
    POST /deduplication/:name/:datasetId/httptransform    transform
    GET  /deduplication/:name/:datasetId[/httptransform]  405 after validation
    GET  /deduplication/:name?since=N                     incremental feed
    (same six shapes under /recordlinkage)

Semantics preserved: writers take the workload lock unconditionally; feed
readers try for 1 s and answer 503 with the reference's message
(App.java:718-725, 827-834); POST body may be a JSON array or a single
object, and a single-entity transform answers a single object
(App.java:952-965, 1196-1198); unknown names 404 on entity endpoints and 400
on feeds; valid-name GETs on POST-only endpoints answer 405.

Documented divergences: the reference 500s (NPE) on an unknown recordlinkage
feed name — here both feeds answer 400; malformed JSON answers 400 rather
than a Jetty stack-trace 500; hot reload closes the replaced workloads'
resources (fixing quirk Q7's index/connection leak).
"""

from __future__ import annotations

import json
import logging
import re
import threading
import time
from email.parser import BytesParser
from email.policy import default as email_policy
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from .. import telemetry
from ..core.config import ConfigError, ServiceConfig, load_default_config, parse_config
from ..engine.scheduler import (
    DatasetGone,
    IngestScheduler,
    SchedulerClosed,
    SchedulerReject,
    WorkloadGone,
    scheduler_enabled,
)
from ..engine.workload import Workload, build_workload
from ..ops.arena import ArenaAdmissionError
from ..telemetry import slo, tracing
from ..telemetry.env import env_flag, env_str
from ..telemetry.logctx import new_request_id, request_id_var
from ..telemetry.probes import is_probe_name, probes_enabled
from . import debug as debug_api
from .homepage import render_homepage
from .metrics import (
    HttpMetrics,
    backend_info,
    make_app_collector,
    make_process_collector,
)

logger = logging.getLogger("duke-tpu-service")

DEFAULT_PORT = 4567  # the reference's Spark default (Dockerfile EXPOSE 4567)

READ_LOCK_TIMEOUT_SECONDS = 1.0
_BUSY_TEMPLATE = (
    "The {kind} is being written to, so reading is not currently possible. "
    "Please wait a bit and try again later."
)

# Request-body ceiling (bytes).  The reference gets effective limits for
# free from its Jetty bootstrap (App.java:649); the stdlib server would
# otherwise read Content-Length bytes unconditionally into memory.  64 MiB
# comfortably fits the stresstest batch shapes (500-row batches are ~100 KB)
# while bounding a hostile/misconfigured POST; override via env.
DEFAULT_MAX_REQUEST_BYTES = 64 * 1024 * 1024


def _max_request_bytes() -> int:
    raw = env_str("MAX_REQUEST_BYTES")
    if not raw:
        return DEFAULT_MAX_REQUEST_BYTES
    try:
        limit = int(raw)
    except ValueError:
        logger.warning(
            "Unparseable MAX_REQUEST_BYTES=%r; using the %d default",
            raw, DEFAULT_MAX_REQUEST_BYTES,
        )
        return DEFAULT_MAX_REQUEST_BYTES
    # <= 0 means unlimited (the common convention; a literal 0 limit would
    # silently write-disable the service)
    return limit if limit > 0 else (1 << 62)


# Links per feed page: one page's fetch + record resolution is the unit of
# workload-lock hold while streaming GET ?since= responses.  5000 links
# resolve in well under 100 ms on every backend.
DEFAULT_FEED_PAGE_SIZE = 5000

# Mid-stream feed lock retries (ISSUE 8 satellite): bounded exponential
# backoff + full jitter under a wall-clock deadline, replacing the 120
# fixed 1 s retries — a wedged writer stops pinning the handler thread at
# a predictable instant, and the retry traffic decays instead of polling
# at 1 Hz for two minutes.
DEFAULT_FEED_RETRY_DEADLINE_S = 120.0
_FEED_BACKOFF_BASE_S = 0.05
_FEED_BACKOFF_CAP_S = 2.0


def _feed_retry_deadline() -> float:
    from ..telemetry.env import env_float

    return max(1.0, env_float("DUKE_FEED_RETRY_DEADLINE",
                              DEFAULT_FEED_RETRY_DEADLINE_S))


def write_chunk(wfile, data: bytes) -> int:
    """One HTTP/1.1 chunk — THE framing primitive, shared by the leader
    feed handler and the replica read plane so the wire format cannot
    drift between the two serving planes.  Zero-length data writes
    nothing (a zero-length chunk would terminate the stream).  Returns
    the payload bytes written."""
    if not data:
        return 0
    wfile.write(b"%x\r\n" % len(data) + data + b"\r\n")
    return len(data)


def _feed_backoff_delay(attempt: int) -> float:
    """Exponential backoff with full jitter for mid-stream lock retries
    (ONE policy copy — utils.backoff — shared with the dispatcher's
    send retries)."""
    from ..utils.backoff import full_jitter_delay

    return full_jitter_delay(attempt, _FEED_BACKOFF_BASE_S,
                             _FEED_BACKOFF_CAP_S)


def _feed_page_size() -> int:
    raw = env_str("FEED_PAGE_SIZE")
    try:
        value = int(raw) if raw else DEFAULT_FEED_PAGE_SIZE
    except ValueError:
        value = DEFAULT_FEED_PAGE_SIZE
    return max(1, value)


class DukeApp:
    """Application state: parsed config + live workloads, hot-swappable."""

    def __init__(self, config: ServiceConfig, *, backend: str = "host",
                 persistent: bool = True,
                 prebuilt: Optional[Tuple[Dict[str, Workload],
                                          Dict[str, Workload]]] = None):
        self.backend = backend
        self.persistent = persistent
        self._swap_lock = threading.Lock()
        self.config: Optional[ServiceConfig] = None
        self.deduplications: Dict[str, Workload] = {}
        self.record_linkages: Dict[str, Workload] = {}
        self.started_monotonic = time.monotonic()
        # per-app metrics registry: HTTP families are children written by
        # the handler threads; engine/corpus/link state is surfaced by a
        # scrape-time collector over the LIVE workload registries (so hot
        # reloads drop replaced workloads' series automatically).
        # /metrics renders this registry plus telemetry.GLOBAL.
        self.metrics = telemetry.MetricRegistry()
        self.http_metrics = HttpMetrics(self.metrics)
        self.metrics.register_collector(make_app_collector(self))
        self.metrics.register_collector(make_process_collector())
        # feed-stream abort visibility (ISSUE 6 satellite): the mid-stream
        # bail-outs (bounded lock-starvation retries exhausted; workload
        # removed by reload) truncate the chunked framing, which a scrape
        # can't see — plain counters surfaced by the app collector and
        # /stats.  Handler threads increment under the lock (rare events).
        self.feed_aborts = {
            "lock_starved": 0, "workload_removed": 0, "deadline": 0,
        }
        self._feed_abort_lock = threading.Lock()
        # promoted-leader marker: adopted workloads hold the ONLY copy of
        # the replicated link state (in-memory replicas; the deposed
        # leader's disk is gone), so apply_config refuses to rebuild them
        self.adopted = prebuilt is not None
        # close() runs from the signal-driven graceful-shutdown thread
        # AND the CLI's serve_forever finally — one caller runs the
        # drain sequence, every other caller BLOCKS until it completes
        # (a no-op second call would let the CLI's main thread exit and
        # take the daemon shutdown thread down mid drain/flush/snapshot)
        self._close_lock = threading.Lock()
        self._closed = False  # guarded by: self._close_lock [writes]
        self._close_done = threading.Event()
        # cold-start observability (ISSUE 15): stamped once by whichever
        # handler thread serves the first successful scoring batch.
        # Plain flag (GIL-atomic; a tied race would double-set a
        # near-identical value, harmless) — service/ is not a metrics
        # hot module, and the gauge is the measured time-to-first-200.
        self._first_batch_served = False
        if prebuilt is not None:
            # leader-failover promotion (parallel.dispatch
            # .promote_follower): the workloads already exist — built
            # around the replica corpus + replicated link DBs — so adopt
            # them instead of rebuilding from durable stores
            self.config = config
            self.deduplications, self.record_linkages = prebuilt
        else:
            self.apply_config(config)
        # continuous cross-request microbatching (ISSUE 6): queues are
        # keyed by (kind, name) and dispatch re-resolves from the live
        # registries, so a hot reload retargets queued requests at the
        # replacement workload.  DUKE_SCHEDULER=0 restores the
        # lock-winner merge inside Workload.submit_batch.
        self.scheduler = (IngestScheduler(self._resolve_workload)
                          if scheduler_enabled() else None)
        # black-box canary prober (ISSUE 20): one shadow workload per
        # user workload under the reserved __probe__ namespace, cycling
        # the derived canary corpus through the REAL path (scheduler,
        # scoring, finalize, link journal, feed materialization) on a
        # background interval.  Shadows live only here — never in the
        # HTTP registries — and DUKE_PROBE=0 restores today's behavior
        # exactly (no prober object, no thread, no collector).
        self.prober = None
        if probes_enabled():
            from .prober import CanaryProber

            self.prober = CanaryProber(self)
            self.prober.start()

    def _resolve_workload(self, kind: str, name: str) -> Optional[Workload]:
        if is_probe_name(name):
            # scheduler dispatch for canary batches: probe names resolve
            # through the prober's shadow registry, invisible to HTTP
            prober = getattr(self, "prober", None)
            return prober.resolve(kind, name) if prober is not None else None
        registry = (self.deduplications if kind == "deduplication"
                    else self.record_linkages)
        return registry.get(name)

    def count_feed_abort(self, reason: str) -> None:
        with self._feed_abort_lock:
            self.feed_aborts[reason] = self.feed_aborts.get(reason, 0) + 1

    def link_flush_errors(self) -> Dict[str, str]:
        """Latched write-behind flush failures by workload (ISSUE 8
        satellite): a dead persistence thread used to be invisible to
        orchestrators until a read drained into the latch — now /readyz
        goes unready and /healthz names the exception.  Lock-free reads
        of the buffers' latched error slots."""
        out: Dict[str, str] = {}
        for kind, registry in (("deduplication", self.deduplications),
                               ("recordlinkage", self.record_linkages)):
            for name, wl in registry.items():
                try:
                    err = wl.link_database.flush_error
                except Exception:
                    continue  # closed/raced workload: not a latch
                if err is not None:
                    out[f"{kind}/{name}"] = repr(err)
        return out

    def recovering(self) -> bool:
        """Whether any of THIS app's workloads is still replaying its
        link journal.  Scoped per workload data folder (ISSUE 14):
        another serving group's replay in the same process does not
        count.  Runs on every HTTP response (the X-Recovering header),
        so the steady-state path is ONE process-wide bool check — the
        per-folder scoping work only runs while some replay, somewhere,
        is actually active."""
        from ..links import journal as link_journal

        if not link_journal.recovery_active(None):
            return False  # nothing recovering anywhere: the common case
        if self.config is None:
            return True
        folders = [
            wc.data_folder
            for wc in (list(self.config.deduplications.values())
                       + list(self.config.record_linkages.values()))
            if wc.data_folder
        ]
        if not folders:
            return link_journal.recovery_active("")
        return any(link_journal.recovery_active(f) for f in folders)

    def note_first_batch(self) -> None:
        """Stamp ``duke_cold_start_seconds`` on the first successfully
        served scoring batch (time-to-first-200, ISSUE 15)."""
        if not self._first_batch_served:
            self._first_batch_served = True
            telemetry.COLD_START_SECONDS.set(
                time.monotonic() - self.started_monotonic)

    def prewarm_errors(self) -> Dict[str, str]:
        """Latched scorer pre-warm failures by workload (ISSUE 15
        satellite): a silently-cold replica — scoring works, but every
        first-contact shape pays a live compile — used to be findable
        only in logs; /healthz now names the last error.  Lock-free
        reads of the caches' error slots."""
        out: Dict[str, str] = {}
        for kind, registry in (("deduplication", self.deduplications),
                               ("recordlinkage", self.record_linkages)):
            for name, wl in registry.items():
                cache = getattr(wl.index, "scorer_cache", None)
                err = getattr(cache, "_warm_error", None)
                if err is not None:
                    out[f"{kind}/{name}"] = err
        return out

    def readiness(self) -> Tuple[bool, Dict[str, bool]]:
        """GET /readyz substance: config parsed, every configured workload
        built and swapped in, (non-host backends) the device backend
        initialized with at least one device, no workload's write-behind
        link persistence latched on a flush failure, and no link-journal
        recovery replay still running (ISSUE 10: /readyz answers
        ``recovering`` until startup replay completes).  With overlapped
        recovery (ISSUE 15, default) a recovering app still serves reads
        — ``write_ready`` is the key that flips only after replay
        completes, and the HTTP layer answers 200 ``recovering`` so
        orchestrators can route read traffic while writes 503."""
        checks = {"config_loaded": self.config is not None}
        # recovery is scoped per workload data folder (ISSUE 14): this
        # app goes "recovering" only for replays of ITS OWN workloads'
        # journals (plus anonymous process-wide entries) — another
        # serving group's replay in the same process no longer flips
        # every group's /readyz
        checks["recovery_complete"] = not self.recovering()
        checks["workloads_built"] = bool(
            self.config is not None
            and set(self.deduplications) == set(self.config.deduplications)
            and set(self.record_linkages) == set(self.config.record_linkages)
        )
        if self.backend == "host":
            checks["device_backend"] = True
        else:
            checks["device_backend"] = backend_info()[1] > 0
        checks["link_persistence"] = not self.link_flush_errors()
        # the read/write readiness split (ISSUE 15): during overlapped
        # recovery reads serve (the whole app is read-ready whenever
        # everything but the replay checks out) while writes stay fenced
        checks["write_ready"] = (checks["recovery_complete"]
                                 and checks["link_persistence"])
        return all(checks.values()), checks

    @property
    def config_string(self) -> str:
        return self.config.config_string if self.config else ""

    def apply_config(self, sc: ServiceConfig) -> None:
        """Quiesce, rebuild, atomically swap (App.java:543-546), close.

        The reference swaps its registries without taking the workload locks
        (quirk Q9), so an in-flight batch can commit records after the new
        workloads snapshot their state.  Here every old workload's lock is
        held while the replacements replay the durable stores, so nothing
        lands between the replay cursor and the swap; the replaced
        workloads' resources are then closed (quirk Q7 fix).

        Reload is stop-the-world for its duration (large corpora replay
        under the locks).  That is the deliberate trade: reload is a rare
        admin operation and the reference's reload pauses service the same
        way while offering weaker consistency.
        """
        if getattr(self, "adopted", False):
            # a promoted leader's workloads wrap replica link DBs that
            # exist nowhere else; rebuilding via build_workload would
            # swap in fresh EMPTY link databases and close the only copy
            # — silent total link loss behind a 200.  Reload again once
            # the group re-forms around durable state.
            raise RuntimeError(
                "config reload is disabled on a promoted leader: its "
                "workloads hold the only copy of the replicated link "
                "state (restart the job to re-form the serving group, "
                "then reload)"
            )
        with self._swap_lock:
            old = list(self.deduplications.values()) + list(self.record_linkages.values())
            for wl in old:
                wl.lock.acquire()
            try:
                # snapshot the quiesced corpora FIRST: the replacements are
                # built before the old workloads close, so without this a
                # device-backend reload would replay the store through full
                # feature re-extraction instead of the snapshot fast path
                for wl in old:
                    wl.save_corpus_snapshot()
                built = []
                try:
                    new_dedups = {}
                    for name, wc in sc.deduplications.items():
                        new_dedups[name] = build_workload(
                            wc, sc, backend=self.backend,
                            persistent=self.persistent)
                        built.append(new_dedups[name])
                    new_linkages = {}
                    for name, wc in sc.record_linkages.items():
                        new_linkages[name] = build_workload(
                            wc, sc, backend=self.backend,
                            persistent=self.persistent)
                        built.append(new_linkages[name])
                except Exception:
                    # failed reload keeps the old config (App.java:543-546);
                    # release whatever the partial build already opened
                    for wl in built:
                        try:
                            wl.close()
                        except Exception:
                            logger.exception("Error closing partially-built workload")
                    raise
                # multi-host serving: ship followers the new config + the
                # just-built corpora so their replicas swap in lockstep
                # (old locks held -> nothing in flight on the op stream)
                from ..parallel import dispatch

                d = dispatch.current()
                if d is not None:
                    with d.op_lock:
                        d.on_reload(sc, new_dedups, new_linkages)
                self.config = sc
                self.deduplications = new_dedups
                self.record_linkages = new_linkages
                for wl in old:
                    try:
                        # snapshot already written above and the corpus is
                        # unchanged (locks held) — skip the duplicate save
                        wl.close(save_snapshot=False)
                    except Exception:
                        logger.exception("Error closing replaced workload")
            finally:
                for wl in old:
                    wl.lock.release()

    def reload_from_string(self, config_string: str) -> None:
        self.apply_config(parse_config(config_string))

    def close(self) -> None:
        """Graceful shutdown: drain the ingest scheduler, then close every
        workload — each close drains its write-behind link flush (leaving
        an EMPTY journal: the watermark catches the head and the file
        compacts to zero bytes) and saves the device-corpus snapshot, so
        an orchestrated restart (docker stop / k8s SIGTERM) starts warm
        with nothing to recover.  Idempotent; called by the signal
        handlers (``install_shutdown_handlers``) and the CLI's
        ``finally`` — the reference has no shutdown hook at all (state
        safety there rests on Lucene/H2 syncing every commit)."""
        with self._close_lock:
            if self._closed:
                already = True
            else:
                self._closed = True
                already = False
        if already:
            # wait for the winning caller's drain sequence: the CLI's
            # finally must not let the process exit while the signal
            # thread is still flushing/snapshotting
            self._close_done.wait()
            return
        try:
            # stop the canary prober before the scheduler drain: its
            # cycles submit through the scheduler this is shutting down
            if getattr(self, "prober", None) is not None:
                self.prober.stop()
            # drain the ingest scheduler FIRST: queued requests complete
            # against still-open workloads (no lost requests), and the
            # dispatcher must be able to take the workload locks this
            # method is about to hold
            if getattr(self, "scheduler", None) is not None:
                self.scheduler.shutdown()
            with self._swap_lock:
                workloads = (list(self.deduplications.values())
                             + list(self.record_linkages.values()))
                self.deduplications = {}
                self.record_linkages = {}
            for wl in workloads:
                with wl.lock:
                    try:
                        wl.close()
                    except Exception:
                        logger.exception(
                            "Error closing workload on shutdown")
        finally:
            self._close_done.set()


class _HttpError(Exception):
    def __init__(self, status: int, message: str, content_type: str = "text/plain",
                 extra_headers: Optional[dict] = None):
        self.status = status
        self.message = message
        self.content_type = content_type
        self.extra_headers = dict(extra_headers or {})


class _BusyError(_HttpError):
    """503 from a workload-lock read timeout (the reference's busy reply,
    App.java:718-725) — its own type so the busy counter counts exactly
    lock-pressure 503s, never e.g. an unready /readyz.

    ``retry_after`` (seconds, from the workload's recent write-hold EWMA)
    rides a ``Retry-After`` header; the reference reply body is
    unchanged."""

    def __init__(self, kind_label: str, retry_after: Optional[int] = None):
        headers = ({"Retry-After": str(retry_after)}
                   if retry_after is not None else None)
        super().__init__(503, _BUSY_TEMPLATE.format(kind=kind_label),
                         extra_headers=headers)


_ENTITY_PATH = re.compile(
    r"^/(deduplication|recordlinkage)/([^/]*)/([^/]*?)(/httptransform)?$"
)
_FEED_PATH = re.compile(r"^/(deduplication|recordlinkage)/([^/]*)$")
_REMATCH_PATH = re.compile(r"^/(deduplication|recordlinkage)/([^/]+)/rematch$")
_DEBUG_TRACE_PATH = re.compile(r"^/debug/traces/([0-9a-f]{32})$")
_DEBUG_DECISION_PATH = re.compile(r"^/debug/decisions/(d\d+)$")

_STATIC_ROUTES = frozenset((
    "/", "/config", "/health", "/healthz", "/readyz", "/metrics", "/stats",
    "/debug/traces", "/debug/requests", "/debug/decisions", "/explain",
    "/debug/profile", "/debug/profile/reset",
    "/debug/costs", "/debug/memory", "/debug/loadmap", "/debug/slo",
    "/debug/probes",
))


def _kind_label(kind: str) -> str:
    """User-facing workload-kind label in error bodies (the reference
    camel-cases recordLinkage — App.java:718)."""
    return "deduplication" if kind == "deduplication" else "recordLinkage"


def _route_template(path: str) -> str:
    """Low-cardinality route label for metrics: path parameters collapse
    to placeholders so a hostile/typo'd URL space cannot mint unbounded
    label values."""
    if path in _STATIC_ROUTES:
        return path
    if _DEBUG_TRACE_PATH.match(path):
        return "/debug/traces/:id"
    if _DEBUG_DECISION_PATH.match(path):
        return "/debug/decisions/:id"
    if m := _REMATCH_PATH.match(path):
        return f"/{m.group(1)}/:name/rematch"
    if m := _ENTITY_PATH.match(path):
        suffix = "/httptransform" if m.group(4) else ""
        return f"/{m.group(1)}/:name/:datasetId{suffix}"
    if m := _FEED_PATH.match(path):
        return f"/{m.group(1)}/:name"
    return "(unmatched)"


class DukeRequestHandler(BaseHTTPRequestHandler):
    app: DukeApp = None  # set by serve()
    protocol_version = "HTTP/1.1"

    # per-request instrumentation state (class-level defaults keep _reply
    # safe for any direct/test caller outside _handle_request)
    _resp_status: Optional[int] = None
    _resp_bytes: int = 0
    request_id: str = "-"
    trace_id: str = "-"

    # -- plumbing -----------------------------------------------------------

    def log_message(self, fmt, *args):
        logger.info("%s %s", self.address_string(), fmt % args)

    def _handle_request(self, method: str, route_fn) -> None:
        """One instrumented request: request-id context, root trace span
        (honoring an inbound W3C ``traceparent``), in-flight gauge,
        route/status counters, latency histogram, byte counters, busy-503
        counter.  The registry children lock for nanoseconds per request
        — HTTP handler threads are never the device scoring path.  The
        root span's exit applies the flight recorder's tail latch, so a
        slow request is retained even when head sampling skipped it."""
        parsed = urlparse(self.path)
        route = _route_template(parsed.path)
        self.request_id = new_request_id()
        request_id_var.set(self.request_id)
        self._resp_status = None
        self._resp_bytes = 0
        busy = False
        hm = self.app.http_metrics
        hm.in_flight.inc()
        t0 = time.monotonic()
        with tracing.start_trace(
            f"{method} {route}",
            traceparent=self.headers.get("traceparent"),
            attributes={
                "http.method": method,
                "http.route": route,
                "http.target": parsed.path,
                "request_id": self.request_id,
            },
        ) as root:
            self.trace_id = root.trace_id
            try:
                try:
                    route_fn(parsed)
                except _HttpError as e:
                    busy = isinstance(e, _BusyError)
                    self._reply(e.status, e.message.encode("utf-8"),
                                e.content_type, e.extra_headers or None)
                except Exception:
                    logger.exception("Error serving %s %s", method, self.path)
                    self._reply_text(500, "Internal server error")
            finally:
                status_code = self._resp_status or 0
                root.set_attribute("http.status", status_code)
                if status_code >= 500:
                    root.status = "error"
                hm.in_flight.dec()
                elapsed = time.monotonic() - t0
                status = str(status_code)
                hm.requests.labels(route=route, method=method,
                                   status=status).inc()
                hm.latency.labels(route=route, method=method).observe(elapsed)
                try:
                    req_bytes = int(self.headers.get("Content-Length") or 0)
                except ValueError:
                    req_bytes = 0
                if req_bytes > 0:
                    hm.request_bytes.labels(route=route).inc(req_bytes)
                if self._resp_bytes:
                    hm.response_bytes.labels(route=route).inc(self._resp_bytes)
                if busy:
                    hm.busy.labels(route=route).inc()
                request_id_var.set("-")

    def _reply(self, status: int, body: bytes, content_type: str = "application/json",
               extra_headers: Optional[dict] = None) -> None:
        self._resp_status = status
        self._resp_bytes += len(body)
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.send_header("X-Request-Id", self.request_id)
        self.send_header("X-Trace-Id", self.trace_id)
        # staleness contract during overlapped recovery (ISSUE 15):
        # every response — feeds, /stats, /metrics, errors — carries the
        # header while this app's journal replay runs, so a reader can
        # tell "prefix of the recovered state" from "caught up"
        if self.app is not None and self.app.recovering():
            self.send_header("X-Recovering", "1")
        for k, v in (extra_headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        try:
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            # client went away mid-response; the reference swallows Jetty's
            # EofException the same way (App.java:780-786)
            logger.info("Ignoring client disconnect on %s", self.path)

    def _reply_text(self, status: int, message: str) -> None:
        self._reply(status, message.encode("utf-8"), "text/plain")

    def send_error(self, code, message=None, explain=None):
        """Stdlib error paths (malformed request line, unsupported
        method) bypass ``_reply`` — without this override those are the
        only responses missing the ``X-Request-Id``/``X-Trace-Id``
        correlation headers (ISSUE 2 satellite).

        These calls happen OUTSIDE ``_handle_request`` (the stdlib
        rejects the request before routing), so on a keep-alive
        connection the handler still holds the PREVIOUS request's ids —
        always mint a fresh request id and clear the trace id, or the
        error would correlate to the wrong trace."""
        self.request_id = new_request_id()
        self.trace_id = "-"
        try:
            short = message or BaseHTTPRequestHandler.responses.get(
                code, ("Error",))[0]
        except Exception:
            short = "Error"
        self.close_connection = True
        self._reply(code, short.encode("utf-8", errors="replace"),
                    "text/plain", {"Connection": "close"})

    def _read_body(self) -> bytes:
        try:
            length = int(self.headers.get("Content-Length") or 0)
        except ValueError:
            # unread body bytes would desync the next keep-alive request
            self.close_connection = True
            raise _HttpError(400, "Invalid Content-Length header")
        if length < 0:
            # a negative length would turn rfile.read(length) into
            # read-to-EOF — unbounded buffering, the exact attack the cap
            # exists to stop
            self.close_connection = True
            raise _HttpError(400, "Invalid Content-Length header")
        limit = _max_request_bytes()
        if length > limit:
            # the unread body would be parsed as the next keep-alive
            # request, so the connection closes with the 413
            self.close_connection = True
            raise _HttpError(
                413,
                f"Request body of {length} bytes exceeds the "
                f"{limit}-byte limit (MAX_REQUEST_BYTES)",
            )
        return self.rfile.read(length) if length else b""

    # -- routing ------------------------------------------------------------

    def do_GET(self):
        self._handle_request("GET", self._route_get)

    def do_POST(self):
        self._handle_request("POST", self._route_post)

    def _route_get(self, parsed) -> None:
        self._read_body()  # drain; unread bytes would corrupt keep-alive
        path = parsed.path
        if path == "/":
            self._reply(200, render_homepage(self.app).encode("utf-8"), "text/html")
        elif path == "/config":
            self._reply(200, self.app.config_string.encode("utf-8"), "application/xml")
        elif path in ("/health", "/healthz"):
            # liveness: the process answers — still 200 with a latched
            # flush failure (the process IS alive; /readyz is what goes
            # unready), but the exception is REPORTED here so operators
            # see the dead persistence thread without waiting for a read
            # to drain into it (ISSUE 8 satellite).  /health predates the
            # probe split and stays for compat.
            health = {"status": "ok"}
            flush_errors = self.app.link_flush_errors()
            if flush_errors:
                health["link_flush_errors"] = flush_errors
            # a silently-cold replica is diagnosable (ISSUE 15
            # satellite): the last scorer pre-warm failure per workload
            prewarm_errors = self.app.prewarm_errors()
            if prewarm_errors:
                health["prewarm_errors"] = prewarm_errors
            # canary verdict mismatches are a CORRECTNESS incident: the
            # status flips to degraded (still 200 — the process is
            # alive) and names the offending workloads (ISSUE 20)
            prober = getattr(self.app, "prober", None)
            probe_detail = (prober.health_detail()
                            if prober is not None else None)
            if probe_detail is not None:
                health["status"] = "degraded"
                health["probe_verdict_mismatches"] = probe_detail
            self._reply(200, json.dumps(health).encode("utf-8"),
                        "application/json")
        elif path == "/readyz":
            self._handle_readyz()
        elif path == "/metrics":
            self._handle_metrics()
        elif path == "/stats":
            self._handle_stats()
        elif path == "/debug/traces":
            self._reply(*debug_api.handle_traces())
        elif m := _DEBUG_TRACE_PATH.match(path):
            fmt = (parse_qs(parsed.query).get("format") or ["json"])[0]
            self._reply(*debug_api.handle_trace(m.group(1), fmt))
        elif path == "/debug/requests":
            self._reply(*debug_api.handle_requests())
        elif path == "/debug/decisions":
            self._reply(*debug_api.handle_decisions(self.app))
        elif m := _DEBUG_DECISION_PATH.match(path):
            self._reply(*debug_api.handle_decision(self.app, m.group(1)))
        elif path == "/debug/profile":
            self._reply(*debug_api.handle_profile_status())
        elif path == "/debug/costs":
            self._reply(*debug_api.handle_costs(
                debug_api._app_workloads(self.app)))
        elif path == "/debug/memory":
            self._reply(*debug_api.handle_memory())
        elif path == "/debug/loadmap":
            # the single-process plane routes nothing through a
            # federation router; the payload reports zero ranges (the
            # federation plane serves its router's live heat map)
            self._reply(*debug_api.handle_loadmap(None))
        elif path == "/debug/slo":
            self._reply(*debug_api.handle_slo())
        elif path == "/debug/probes":
            self._reply(*debug_api.handle_probes(
                getattr(self.app, "prober", None)))
        elif m := _ENTITY_PATH.match(path):
            self._validate_entity_path(m)
            raise _HttpError(405, "This endpoint only supports POST requests.")
        elif m := _FEED_PATH.match(path):
            self._handle_feed(m, parse_qs(parsed.query))
        else:
            raise _HttpError(404, "Not found")

    def _route_post(self, parsed) -> None:
        # read the body up front: replying with the body unread would
        # leave its bytes to be parsed as the next keep-alive request
        body = self._read_body()
        path = parsed.path
        if path == "/config":
            self._handle_config_upload(body)
        elif path == "/explain":
            self._reply(*debug_api.handle_explain(self.app, body))
        elif path == "/debug/profile":
            self._reply(*debug_api.handle_profile_start(
                parse_qs(parsed.query)))
        elif path == "/debug/profile/reset":
            self._reply(*debug_api.handle_profile_reset())
        elif m := _REMATCH_PATH.match(path):
            self._handle_rematch(m, body)
        elif m := _ENTITY_PATH.match(path):
            self._handle_post_batch(m, body)
        else:
            raise _HttpError(404, "Not found")

    # -- handlers -----------------------------------------------------------

    def _handle_readyz(self) -> None:
        ready, checks = self.app.readiness()
        http_status = 200 if ready else 503
        if ready:
            status = "ready"
        elif not checks.get("recovery_complete", True):
            # startup journal replay still running: a distinct status so
            # orchestrators (and humans) can tell "redoing the link log"
            # from a genuinely broken dependency
            status = "recovering"
            # overlapped recovery (ISSUE 15, default on): reads already
            # serve the replay's committed prefix, so when the replay is
            # the ONLY thing unready, /readyz answers 200 — the
            # "recovering" 503 window shrinks to the write path (POSTs
            # 503 per-request until write_ready flips).  The legacy
            # serial mode keeps the whole-app 503.
            read_ready = all(v for k, v in checks.items()
                             if k not in ("recovery_complete",
                                          "write_ready"))
            if read_ready and env_flag("DUKE_RECOVERY_OVERLAP", True):
                http_status = 200
        else:
            status = "unready"
        body = json.dumps(
            {"status": status, "checks": checks}
        ).encode("utf-8")
        self._reply(http_status, body, "application/json")

    def _handle_metrics(self) -> None:
        body = telemetry.render(
            self.app.metrics, telemetry.GLOBAL
        ).encode("utf-8")
        self._reply(200, body, telemetry.CONTENT_TYPE)

    def _handle_stats(self):
        """Observability endpoint (new in this build — the reference has no
        metrics/health surface, SURVEY.md section 5.5): per-workload
        ProfileStats counters plus corpus sizes.

        Reads the same lock-free single-writer state the /metrics
        collector scrapes (ProfileStats, live_records, PhaseRecorder,
        LinkDatabase.count) — the JSON shape predates /metrics and stays
        backward-compatible; uptime/platform/device_count/links_rows and
        the per-phase seconds are additive."""
        platform, device_count = backend_info()
        out = {
            "backend": self.app.backend,
            "platform": platform,
            "device_count": device_count,
            "uptime_seconds": round(
                time.monotonic() - self.app.started_monotonic, 3
            ),
            "workloads": [],
        }
        # operator summary of the digest-keyed feature cache (PR 4):
        # until now the hit rate existed only as raw Prometheus series
        from ..ops import feature_cache as FC

        hits, misses, evicted, cache_bytes = FC.stats()
        looked_up = hits + misses
        out["feature_cache"] = {
            "hits": hits,
            "misses": misses,
            "evicted": evicted,
            "bytes": cache_bytes,
            "hit_rate": round(hits / looked_up, 4) if looked_up else None,
        }
        # audit-loss visibility: drop-on-overflow is by design, but an
        # operator treating the JSONL as evidence needs to SEE the loss
        from ..telemetry.decisions import audit_log

        # ingest-scheduler health (ISSUE 6): queue depths, admission
        # split, microbatch fill and the live Retry-After hint per tenant
        if self.app.scheduler is not None:
            out["scheduler"] = self.app.scheduler.stats_snapshot()
        # feed-stream abort visibility (satellite): mid-stream bail-outs
        # truncate chunked framing, invisible to any scrape until now
        with self.app._feed_abort_lock:
            out["feed_aborts"] = dict(self.app.feed_aborts)
        audit = audit_log()
        if audit is not None:
            out["audit_log"] = {
                "path": audit.path,
                "entries": audit.entries,
                "dropped_batches": audit.dropped,
                "disabled": audit.disabled,
            }
        for kind, registry in (
            ("deduplication", self.app.deduplications),
            ("recordlinkage", self.app.record_linkages),
        ):
            for name, wl in registry.items():
                stats = getattr(wl.processor, "stats", None)
                # live (non-dukeDeleted) indexed records, via the O(1)
                # counters the backends maintain (device/ann:
                # live_records; host: len(index)) — lock-free, so a
                # long-running ingest batch never stalls /stats and
                # /stats never stalls ingest
                live = getattr(wl.index, "live_records", None)
                row = {
                    "kind": kind,
                    "name": name,
                    "records_indexed": (
                        live if live is not None else len(wl.index)
                    ),
                }
                try:
                    row["links_rows"] = wl.link_database.count()
                except Exception:
                    pass  # closed/raced link DB: omit rather than 500
                if stats is not None:
                    row.update(
                        batches=stats.batches,
                        records_processed=stats.records_processed,
                        candidates_retrieved=stats.candidates_retrieved,
                        pairs_compared=stats.pairs_compared,
                        retrieval_seconds=round(stats.retrieval_seconds, 3),
                        compare_seconds=round(stats.compare_seconds, 3),
                    )
                    # decisive-band split (PR 3): survivors rescored
                    # host-exact vs certifiably skipped, previously only
                    # visible as duke_finalize_pairs_total series
                    if getattr(wl.processor, "finalizer", None) is not None:
                        finalized = stats.pairs_rescored + stats.pairs_skipped
                        row["finalize"] = {
                            "rescored": stats.pairs_rescored,
                            "skipped": stats.pairs_skipped,
                            "skip_rate": (
                                round(stats.pairs_skipped / finalized, 4)
                                if finalized else None
                            ),
                        }
                recorder = getattr(wl.processor, "decisions", None)
                if recorder is not None and recorder.enabled:
                    row["decisions"] = {
                        "outcomes": dict(recorder.outcomes),
                        "disagreements": recorder.disagreements,
                        "ring": len(recorder.ring),
                        "latched": recorder.latched,
                    }
                phases = getattr(wl.processor, "phases", None)
                if phases is not None:
                    row["phase_seconds"] = {
                        k: round(v, 3)
                        for k, v in phases.phase_seconds().items()
                    }
                out["workloads"].append(row)
        self._reply(200, json.dumps(out).encode("utf-8"), "application/json")

    def _workloads(self, kind: str) -> Dict[str, Workload]:
        return (self.app.deduplications if kind == "deduplication"
                else self.app.record_linkages)

    def _validate_entity_path(self, m) -> Tuple[str, Workload, str, bool]:
        kind, name, dataset_id, transform = m.group(1), m.group(2), m.group(3), bool(m.group(4))
        label = _kind_label(kind)
        if not name:
            raise _HttpError(404, f"The {label}Name cannot be an empty string!")
        if not dataset_id:
            raise _HttpError(404, "The datasetId cannot be an empty string!")
        if is_probe_name(name) or is_probe_name(dataset_id):
            # namespace-exclusion contract (ISSUE 20): probe shadows are
            # never HTTP-addressable, even by their real names
            raise _HttpError(
                404, "The '__probe__' namespace is reserved for the "
                     "synthetic canary prober.")
        workload = self._workloads(kind).get(name)
        if workload is None:
            raise _HttpError(
                404,
                f"Unknown {label} '{name}'! (All {label}s must be specified in "
                f"the configuration)",
            )
        if dataset_id not in workload.datasources:
            raise _HttpError(
                404, f"Unknown dataset-id '{dataset_id}' for the {label} '{name}'!"
            )
        return kind, workload, dataset_id, transform

    def _handle_post_batch(self, m, body: bytes) -> None:
        try:
            payload = json.loads(body.decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            raise _HttpError(400, "Request body must be a JSON array or object")
        if isinstance(payload, dict):
            batch, single = [payload], True
        elif isinstance(payload, list):
            batch, single = payload, False
        else:
            raise _HttpError(400, "Request body must be a JSON array or object")
        for entity in batch:
            if not isinstance(entity, dict):
                raise _HttpError(400, "Batch elements must be JSON objects")

        kind, workload, dataset_id, transform = self._validate_entity_path(m)
        if not transform:
            self._check_write_fence(kind, m.group(2), workload)
        sched = self.app.scheduler
        if sched is not None and not transform:
            # continuous microbatching (ISSUE 6): the scheduler coalesces
            # concurrent POSTs into device-shaped microbatches, applies
            # queue-depth admission control, and dispatches fairly across
            # workloads.  Transforms stay on the direct lock path — their
            # response rows are per-request state on the shared listener.
            name, label = m.group(2), _kind_label(kind)
            try:
                sched.submit(kind, name, dataset_id, batch)
            except SchedulerReject as e:
                raise _HttpError(
                    429,
                    f"The {label} '{name}' ingest queue is full "
                    f"({e.depth} requests pending). Please retry after "
                    f"{e.retry_after}s.",
                    extra_headers={"Retry-After": str(e.retry_after)},
                )
            except WorkloadGone:
                raise _HttpError(
                    404,
                    f"Unknown {label} '{name}'! (All {label}s must be "
                    f"specified in the configuration)",
                )
            except DatasetGone as e:
                # a reload replaced the workload with one lacking the
                # dataset after admission validated it — same 404 the
                # up-front validation answers
                raise _HttpError(
                    404,
                    f"Unknown dataset-id '{e.dataset_id}' for the "
                    f"{label} '{name}'!",
                )
            except SchedulerClosed:
                raise _HttpError(503, "The service is shutting down.")
            except ArenaAdmissionError as e:
                # the corpus no longer fits the HBM budget even after
                # spilling every other tenant (ISSUE 19): a loud,
                # actionable 503 — never an allocator OOM
                raise _HttpError(
                    503, f"HBM budget exhausted: {e}",
                    extra_headers={"Retry-After": "30"},
                )
            except _HttpError:
                raise
            except Exception as e:
                logger.exception("Batch processing failed")
                raise _HttpError(500, f"Batch processing failed: {e}")
            rows = []
        else:
            while True:
                # re-resolve until a live workload accepts the batch: a
                # config reload can replace the registry entry between
                # lookup and lock (submit_batch returns None for a replaced
                # workload); ingest requests merge into per-workload device
                # microbatches inside submit_batch
                kind, workload, dataset_id, transform = \
                    self._validate_entity_path(m)
                try:
                    rows = workload.submit_batch(dataset_id, batch,
                                                 http_transform=transform)
                except ArenaAdmissionError as e:
                    raise _HttpError(
                        503, f"HBM budget exhausted: {e}",
                        extra_headers={"Retry-After": "30"},
                    )
                except Exception as e:
                    logger.exception("Batch processing failed")
                    raise _HttpError(500, f"Batch processing failed: {e}")
                if rows is not None:
                    break

        if transform:
            out = rows[0] if single and len(rows) == 1 else rows
            self._reply(200, json.dumps(out).encode("utf-8"))
        else:
            # time-to-first-200 (ISSUE 15): the cold-start gauge stamps
            # on the first successfully served scoring batch
            self.app.note_first_batch()
            self._reply(200, b'{"success": true}')

    def _check_write_fence(self, kind: str, name: str, workload) -> None:
        """503 a scoring POST while this workload's link journal is
        still replaying (overlapped recovery, ISSUE 15): the wrapper
        itself would fence the write anyway — blocking the handler
        thread for the whole replay — so the HTTP layer answers fast
        with Retry-After instead.  Reads are unaffected."""
        db = workload.link_database
        if getattr(db, "recovering", False):
            label = _kind_label(kind)
            # no explicit X-Recovering here: _reply adds it for every
            # response while the app recovers, and this error only fires
            # then — a second copy would duplicate the header
            raise _HttpError(
                503,
                f"The {label} '{name}' is replaying its link journal; "
                "writes resume when recovery completes.",
                extra_headers={"Retry-After": "1"},
            )

    def _handle_feed(self, m, query) -> None:
        """Stream the incremental link feed in bounded pages.

        The reference materializes and writes every row while holding the
        workload lock (App.java:827-874); at millions of links that 503s
        every other reader and blocks writers for the whole response.
        Here each page (FEED_PAGE_SIZE links) takes the lock only for the
        link fetch + record resolution; JSON serialization and the socket
        write happen outside it, and the response is chunked so no full
        materialization ever exists.  The wire format is unchanged
        (same bytes as the reference's single array).
        """
        kind, name = m.group(1), m.group(2)
        label = _kind_label(kind)
        if not name:
            raise _HttpError(400, f"The {label}Name cannot be an empty string!")
        if is_probe_name(name):
            # feed filter half of the namespace-exclusion contract: no
            # probe shadow's links are ever served to a ?since= poller
            raise _HttpError(
                400, "The '__probe__' namespace is reserved for the "
                     "synthetic canary prober.")
        since = 0
        since_params = query.get("since")
        if since_params and since_params[0]:
            try:
                since = int(since_params[0])
            except ValueError:
                raise _HttpError(400, f"Invalid since value '{since_params[0]}'")

        if self.request_version == "HTTP/1.0":
            # HTTP/1.0 clients don't decode chunked framing; serve them the
            # buffered single-array reply (same bytes, Content-Length'd)
            self._handle_feed_buffered(m, kind, name, label, since)
            return

        page_size = _feed_page_size()
        cursor = since
        t0 = time.monotonic()
        started = False   # headers sent (can't switch to an error reply after)
        first_row = True
        lock_attempts = 0
        lock_deadline: Optional[float] = None
        try:
            while True:
                workload = self._workloads(kind).get(name)
                if workload is None:
                    if started:
                        # config reload removed the workload mid-stream: a
                        # clean ']' would make the truncated feed look
                        # complete — kill the chunked framing instead so
                        # the client sees a protocol error
                        logger.warning(
                            "Aborting %s feed stream: workload removed "
                            "by config reload mid-stream", name,
                        )
                        self.app.count_feed_abort("workload_removed")
                        self.close_connection = True
                        return
                    raise _HttpError(
                        400,
                        f"Unknown {label} '{name}'! (All {label}s must be "
                        f"specified in the configuration)",
                    )
                # chaos hook (DUKE_FAULTS slow_lock): deterministic stall
                # before the acquire, driving the deadline path in tests
                from ..utils import faults

                plan = faults.active()
                if plan is not None:
                    stall = plan.lock_delay()
                    if stall:
                        time.sleep(stall)
                if not workload.lock.acquire(timeout=READ_LOCK_TIMEOUT_SECONDS):
                    if not started:
                        # pre-stream: the abort response is the busy 503,
                        # Retry-After derived from the recent write-hold
                        # EWMA (the reference's 1 s try-then-503)
                        raise _BusyError(label, workload.busy_retry_after())
                    # mid-stream contention: no in-band error channel
                    # exists once streaming, so retry — with exponential
                    # backoff + jitter under a wall-clock deadline
                    # (ISSUE 8 satellite; was 120 fixed 1 s retries).  A
                    # wedged writer truncates the chunked framing at the
                    # deadline so the client sees a protocol error, never
                    # silent partial success.
                    now = time.monotonic()
                    if lock_deadline is None:
                        lock_deadline = now + _feed_retry_deadline()
                    lock_attempts += 1
                    if now >= lock_deadline:
                        logger.warning(
                            "Aborting %s feed stream: workload lock "
                            "unavailable past the %.0f s deadline "
                            "(%d attempts)", name, _feed_retry_deadline(),
                            lock_attempts,
                        )
                        self.app.count_feed_abort("deadline")
                        self.close_connection = True
                        return
                    time.sleep(min(_feed_backoff_delay(lock_attempts),
                                   max(0.0, lock_deadline - now)))
                    continue
                lock_attempts = 0
                lock_deadline = None
                try:
                    if workload.closed:
                        continue  # replaced by reload: re-resolve registry
                    rows, cursor = workload.links_page(cursor, page_size)
                finally:
                    workload.lock.release()
                if not started:
                    self._resp_status = 200
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Transfer-Encoding", "chunked")
                    self.send_header("X-Request-Id", self.request_id)
                    self.send_header("X-Trace-Id", self.trace_id)
                    # staleness signal: this stream is a monotonic
                    # PREFIX of the recovered feed while replay runs
                    if self.app.recovering():
                        self.send_header("X-Recovering", "1")
                    self.end_headers()
                    self._write_chunk(b"[")
                    started = True
                if rows:
                    payload = ",\n".join(json.dumps(r) for r in rows)
                    if not first_row:
                        payload = ",\n" + payload
                    first_row = False
                    self._write_chunk(payload.encode("utf-8"))
                if len(rows) < page_size:
                    break
            # always-on feed SLO signal (ISSUE 16): backlog walk wall
            # time against DUKE_SLO_FEED_MS; reaching the short page
            # means the feed is caught up, so the lag meter stops aging
            slo.tracker("feed", kind, name).record(
                time.monotonic() - t0,
                trace_id=tracing.sampled_trace_id())
            slo.feed_meter(kind, name).note_drain()
            if started:
                self._write_chunk(b"]")
                self.wfile.write(b"0\r\n\r\n")
        except (BrokenPipeError, ConnectionResetError):
            # client went away mid-stream (reference swallows Jetty's
            # EofException the same way, App.java:878-884)
            logger.info("Ignoring client disconnect on %s", self.path)
            self.close_connection = True
        except Exception:
            if not started:
                raise  # pre-headers: the generic 500 path still works
            # mid-stream failure: no in-band error channel; truncate the
            # chunked stream (clients see a protocol error, not silent
            # partial success)
            logger.exception("Error mid-stream on %s", self.path)
            self.close_connection = True

    def _write_chunk(self, data: bytes) -> None:
        self._resp_bytes += write_chunk(self.wfile, data)

    def _handle_feed_buffered(self, m, kind: str, name: str, label: str,
                              since: int) -> None:
        """Pre-streaming feed path for HTTP/1.0 clients: one buffered
        array with Content-Length (holds the lock for the full fetch,
        like the reference)."""
        t0 = time.monotonic()
        while True:
            workload = self._workloads(kind).get(name)
            if workload is None:
                raise _HttpError(
                    400,
                    f"Unknown {label} '{name}'! (All {label}s must be "
                    f"specified in the configuration)",
                )
            if not workload.lock.acquire(timeout=READ_LOCK_TIMEOUT_SECONDS):
                raise _BusyError(label, workload.busy_retry_after())
            try:
                if workload.closed:
                    continue
                rows = workload.links_since(since)
                break
            finally:
                workload.lock.release()
        slo.tracker("feed", kind, name).record(
            time.monotonic() - t0, trace_id=tracing.sampled_trace_id())
        slo.feed_meter(kind, name).note_drain()
        body = "[" + ",\n".join(json.dumps(r) for r in rows) + "]"
        self._reply(200, body.encode("utf-8"))

    def _handle_rematch(self, m, body: bytes) -> None:
        """Admin extension: bulk corpus-vs-corpus re-match through the
        ring layout (engine.rematch) — link-DB backfill / re-population.
        The reference has no bulk operations; a dataset literally named
        'rematch' still wins the route (ingest takes precedence, with the
        posted batch intact)."""
        kind, name = m.group(1), m.group(2)
        workload = self._workloads(kind).get(name)
        if workload is not None and "rematch" in workload.datasources:
            self._handle_post_batch(
                _ENTITY_PATH.match(f"/{kind}/{name}/rematch"), body
            )
            return
        label = _kind_label(kind)
        if workload is None:
            raise _HttpError(
                404,
                f"Unknown {label} '{name}'! (All {label}s must be specified "
                f"in the configuration)",
            )
        from ..engine.rematch import ring_rematch

        # bulk re-match writes the link DB: same recovery fence as ingest
        self._check_write_fence(kind, name, workload)
        with workload.lock:
            if workload.closed:
                raise _BusyError(label)
            try:
                stats = ring_rematch(workload)
            except ValueError as e:
                raise _HttpError(400, str(e))
            except Exception as e:
                logger.exception("ring re-match failed")
                raise _HttpError(500, f"Re-match failed: {e}")
        self._reply(200, json.dumps(stats).encode("utf-8"))

    def _handle_config_upload(self, body: bytes) -> None:
        content_type = self.headers.get("Content-Type", "")
        config_string = None
        if content_type.startswith("multipart/form-data"):
            config_string = _extract_multipart_field(content_type, body, "configfile")
            if config_string is None:
                raise _HttpError(400, "Missing multipart field 'configfile'")
        else:
            # convenience divergence: accept the raw XML as the request body
            config_string = body.decode("utf-8", errors="replace")
        try:
            self.app.reload_from_string(config_string)
        except ConfigError as e:
            raise _HttpError(400, f"Invalid configuration: {e}")
        except Exception as e:
            logger.exception("Config reload failed")
            raise _HttpError(500, f"Config reload failed: {e}")
        # success: redirect to the homepage (App.java:682)
        self._reply(302, b"ok", "text/plain", {"Location": "/"})


def _extract_multipart_field(content_type: str, body: bytes,
                             field: str) -> Optional[str]:
    """Minimal multipart/form-data parsing via the stdlib email parser."""
    message = BytesParser(policy=email_policy).parsebytes(
        b"Content-Type: " + content_type.encode("latin-1") + b"\r\n\r\n" + body
    )
    if not message.is_multipart():
        return None
    for part in message.iter_parts():
        if part.get_param("name", header="content-disposition") == field:
            payload = part.get_payload(decode=True)
            return payload.decode("utf-8", errors="replace")
    return None


def install_shutdown_handlers(app: DukeApp, server) -> None:
    """SIGTERM/SIGINT graceful shutdown (ISSUE 10 satellite): stop
    accepting, drain the ingest scheduler, flush the write-behind link
    batches, save corpus snapshots, close — so an orchestrated restart
    (docker stop, k8s rolling update) finds an empty journal and a warm
    snapshot and never even enters recovery.

    The handler itself only spawns the shutdown thread (signal context
    must not block on workload locks); ``server.shutdown()`` unblocks
    ``serve_forever`` and ``DukeApp.close()`` runs the drain sequence.
    A second signal is a no-op (``close`` is idempotent), NOT an
    escalation — a hard kill is what the crash-recovery journal exists
    for."""
    import signal

    def _shutdown(signum, frame):
        logger.info("signal %d: graceful shutdown (drain -> flush -> "
                    "snapshot -> close)", signum)

        def _run():
            server.shutdown()  # stop accepting; in-flight requests finish
            app.close()

        threading.Thread(target=_run, daemon=True,
                         name="graceful-shutdown").start()

    signal.signal(signal.SIGTERM, _shutdown)
    signal.signal(signal.SIGINT, _shutdown)


def create_app(config: Optional[ServiceConfig] = None, *, backend: str = "host",
               persistent: bool = True) -> DukeApp:
    if config is None:
        config = load_default_config()
    return DukeApp(config, backend=backend, persistent=persistent)


def serve(app: DukeApp, port: int = DEFAULT_PORT,
          host: str = "0.0.0.0") -> ThreadingHTTPServer:
    handler = type("BoundHandler", (DukeRequestHandler,), {"app": app})
    server = ThreadingHTTPServer((host, port), handler)
    return server
