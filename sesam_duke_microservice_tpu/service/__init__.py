from .datasource import IncrementalDataSource, IngestError

__all__ = ["IncrementalDataSource", "IngestError"]
