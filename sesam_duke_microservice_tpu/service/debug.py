"""``/debug`` surface: flight recorder + on-demand device profiling.

Rendering helpers for the four debug endpoints (ISSUE 2):

    GET  /debug/traces              recent retained-trace summaries
    GET  /debug/traces/<id>         one tree, ?format=json|chrome
    GET  /debug/requests            always-on last-N request digests
    POST /debug/profile?seconds=N   on-demand jax.profiler capture
    POST /debug/profile/reset       re-arm the PROFILE_TRACE_DIR budget

Each helper returns ``(status, body_bytes, content_type)`` so the HTTP
layer stays a thin switch (service/app.py) and the logic is unit-testable
without a socket.  Everything here reads recorder snapshots under the
recorder's own short lock — never engine state, never the workload locks,
so ``/debug`` cannot stall ingest.
"""

from __future__ import annotations

import json
from typing import Tuple

from ..telemetry import tracing
from ..utils import profiling

_JSON = "application/json"

Reply = Tuple[int, bytes, str]


def _reply_json(status: int, payload) -> Reply:
    return status, json.dumps(payload).encode("utf-8"), _JSON


def handle_traces(recorder: tracing.FlightRecorder = None) -> Reply:
    recorder = recorder if recorder is not None else tracing.RECORDER
    return _reply_json(200, {"traces": recorder.summaries()})


def handle_trace(trace_id: str, fmt: str = "json",
                 recorder: tracing.FlightRecorder = None) -> Reply:
    recorder = recorder if recorder is not None else tracing.RECORDER
    if fmt not in ("json", "chrome"):
        return _reply_json(
            400, {"error": f"unknown format {fmt!r} (json|chrome)"})
    record = recorder.get(trace_id)
    if record is None:
        return _reply_json(404, {
            "error": f"trace {trace_id!r} is not in the flight recorder "
                     "(unretained, evicted, or never existed)"})
    if fmt == "chrome":
        return _reply_json(200, tracing.chrome_trace(record))
    return _reply_json(200, tracing.trace_to_json(record))


def handle_requests(recorder: tracing.FlightRecorder = None) -> Reply:
    recorder = recorder if recorder is not None else tracing.RECORDER
    return _reply_json(200, {"requests": recorder.digests()})


def handle_profile_status() -> Reply:
    """``GET /debug/profile``: the live capture's dir/deadline, or
    ``{"capturing": null}`` when idle — so an operator can see (and wait
    out) a running capture instead of probing with 409s."""
    return _reply_json(200, {"capturing": profiling.capture_status()})


def handle_profile_start(query: dict) -> Reply:
    raw = (query.get("seconds") or ["5"])[0]
    try:
        seconds = float(raw)
    except ValueError:
        return _reply_json(400, {"error": f"unparseable seconds {raw!r}"})
    try:
        info = profiling.start_capture(seconds)
    except profiling.CaptureActiveError as e:
        return _reply_json(409, {"error": str(e)})
    except ValueError as e:
        return _reply_json(400, {"error": str(e)})
    return _reply_json(200, {"capturing": info})


def handle_profile_reset() -> Reply:
    return _reply_json(200, {
        "trace_budget_reset": True,
        "budget_batches": profiling.reset_trace_budget(),
    })
