"""``/debug`` surface: flight recorders, decisions, explain, profiling.

Rendering helpers for the debug/explainability endpoints (ISSUE 2 + 5):

    GET  /debug/traces              recent retained-trace summaries
    GET  /debug/traces/<id>         one tree, ?format=json|chrome
    GET  /debug/requests            always-on last-N request digests
    GET  /debug/decisions           decision flight-recorder ring
    GET  /debug/decisions/<id>      one full decision record
    POST /explain                   replay a pair in explain mode
    POST /debug/profile?seconds=N   on-demand jax.profiler capture
    POST /debug/profile/reset       re-arm the PROFILE_TRACE_DIR budget
    GET  /debug/costs               device-time ledger + reconciliation
    GET  /debug/memory              HBM ledger + headroom forecast
    GET  /debug/loadmap             sub-range heat + split suggestions
    GET  /debug/slo                 SLO violations w/ exemplar traces

Each helper returns ``(status, body_bytes, content_type)`` so the HTTP
layer stays a thin switch (service/app.py) and the logic is unit-testable
without a socket.  Everything here reads recorder snapshots under the
recorders' own short locks — never engine state, never the workload locks
— EXCEPT ``handle_explain``, which replays the pipeline under the target
workload's lock with the feed endpoints' 1 s read-timeout semantics, so
``/debug`` still cannot stall ingest.
"""

from __future__ import annotations

import json
from typing import Tuple

from ..telemetry import tracing
from ..utils import profiling

_JSON = "application/json"

Reply = Tuple[int, bytes, str]


def _reply_json(status: int, payload) -> Reply:
    return status, json.dumps(payload).encode("utf-8"), _JSON


def handle_traces(recorder: tracing.FlightRecorder = None) -> Reply:
    recorder = recorder if recorder is not None else tracing.RECORDER
    return _reply_json(200, {"traces": recorder.summaries()})


def handle_trace(trace_id: str, fmt: str = "json",
                 recorder: tracing.FlightRecorder = None) -> Reply:
    recorder = recorder if recorder is not None else tracing.RECORDER
    if fmt not in ("json", "chrome"):
        return _reply_json(
            400, {"error": f"unknown format {fmt!r} (json|chrome)"})
    record = recorder.get(trace_id)
    if record is None:
        return _reply_json(404, {
            "error": f"trace {trace_id!r} is not in the flight recorder "
                     "(unretained, evicted, or never existed)"})
    if fmt == "chrome":
        return _reply_json(200, tracing.chrome_trace(record))
    return _reply_json(200, tracing.trace_to_json(record))


def handle_requests(recorder: tracing.FlightRecorder = None) -> Reply:
    recorder = recorder if recorder is not None else tracing.RECORDER
    return _reply_json(200, {"requests": recorder.digests()})


def _decision_workloads(app):
    for kind, registry in (("deduplication", app.deduplications),
                           ("recordlinkage", app.record_linkages)):
        for name, wl in list(registry.items()):
            recorder = getattr(wl.processor, "decisions", None)
            if recorder is not None:
                yield kind, name, wl, recorder


def handle_decisions(app) -> Reply:
    """``GET /debug/decisions``: the decision flight-recorder ring across
    every live workload, most recent first.  Full per-property payloads
    stay behind ``/debug/decisions/<id>`` — the listing is a summary."""
    rows = []
    for kind, name, _wl, recorder in _decision_workloads(app):
        for record in recorder.records():
            rows.append({
                "id": record["id"],
                "kind": kind,
                "workload": name,
                "time_unix": record.get("time_unix"),
                "query": record.get("query"),
                "candidate": record.get("candidate"),
                "outcome": record.get("outcome"),
                "probability": record.get("probability"),
                "device_logit": record.get("device_logit"),
                "latched": record.get("latched"),
                "sampled": record.get("sampled"),
                "trace_id": record.get("trace_id"),
            })
    # numeric sort on the sequence part: the zero-padding runs out at
    # 10^8 decisions and lexicographic order would put newest last
    rows.sort(key=lambda r: int(r["id"][1:]), reverse=True)
    return _reply_json(200, {"decisions": rows})


def handle_decision(app, decision_id: str) -> Reply:
    """``GET /debug/decisions/<id>``: one full decision record."""
    for kind, name, _wl, recorder in _decision_workloads(app):
        record = recorder.get(decision_id)
        if record is not None:
            out = dict(record)
            out["kind"] = kind
            out["workload"] = name
            return _reply_json(200, out)
    return _reply_json(404, {
        "error": f"decision {decision_id!r} is not in the ring "
                 "(evicted, unsampled, or never existed)"})


def handle_explain(app, body: bytes) -> Reply:
    """``POST /explain``: replay one pair through the pipeline in explain
    mode (engine.explain).  Body selects the workload (``kind``/``name``,
    optional when exactly one workload exists) and the two records
    (``id1``/``id2`` or raw ``record1``/``record2``)."""
    from ..engine import explain as X

    try:
        payload = json.loads(body.decode("utf-8"))
    except (ValueError, UnicodeDecodeError):
        return _reply_json(400, {"error": "Request body must be JSON"})
    if not isinstance(payload, dict):
        return _reply_json(400, {"error": "Request body must be a JSON object"})

    registries = {"deduplication": app.deduplications,
                  "recordlinkage": app.record_linkages}
    kind = payload.get("kind")
    name = payload.get("name") or payload.get("workload")
    candidates = []
    for k, registry in registries.items():
        if kind is not None and k != str(kind):
            continue
        for n, wl in registry.items():
            if name is None or n == str(name):
                candidates.append((k, n, wl))
    if not candidates:
        return _reply_json(404, {
            "error": f"no workload matches kind={kind!r} name={name!r}"})
    if len(candidates) > 1:
        return _reply_json(400, {
            "error": "ambiguous workload — pass \"kind\" and \"name\"",
            "workloads": [{"kind": k, "name": n} for k, n, _ in candidates],
        })
    _, _, workload = candidates[0]
    try:
        return _reply_json(200, X.explain_request(workload, payload))
    except X.ExplainBusy:
        return (503,
                b"The workload is being written to, so explaining is not "
                b"currently possible. Please wait a bit and try again "
                b"later.", "text/plain")
    except X.ExplainError as e:
        return _reply_json(e.status, {"error": str(e)})


def handle_profile_status() -> Reply:
    """``GET /debug/profile``: the live capture's dir/deadline, or
    ``{"capturing": null}`` when idle — so an operator can see (and wait
    out) a running capture instead of probing with 409s."""
    return _reply_json(200, {"capturing": profiling.capture_status()})


def handle_profile_start(query: dict, owner: str = "app") -> Reply:
    """``POST /debug/profile?seconds=N`` — served by all three planes
    (app / replica / federation), which share the process's ONE
    profiler; ``owner`` names the requesting plane so a conflict 409
    says who holds the capture and until when."""
    raw = (query.get("seconds") or ["5"])[0]
    try:
        seconds = float(raw)
    except ValueError:
        return _reply_json(400, {"error": f"unparseable seconds {raw!r}"})
    try:
        info = profiling.start_capture(seconds, owner=owner)
    except profiling.CaptureActiveError as e:
        return _reply_json(409, {
            "error": str(e),
            "owner": e.owner,
            "deadline_unix": e.deadline_unix,
            "remaining_seconds": e.remaining_seconds,
        })
    except ValueError as e:
        return _reply_json(400, {"error": str(e)})
    return _reply_json(200, {"capturing": info})


def handle_profile_reset() -> Reply:
    return _reply_json(200, {
        "trace_budget_reset": True,
        "budget_batches": profiling.reset_trace_budget(),
    })


# -- cost & capacity attribution (ISSUE 17) ----------------------------------


def _app_workloads(app):
    """(kind, name, workload) across both registries — the cost/memory
    debug surfaces' workload iterator for the main plane."""
    for kind, registry in (("deduplication", app.deduplications),
                           ("recordlinkage", app.record_linkages)):
        for name, wl in list(registry.items()):
            yield kind, name, wl


def handle_costs(workload_iter=()) -> Reply:
    """``GET /debug/costs``: the device-time ledger reconciled against
    per-workload phase attribution.  ``attributed_seconds`` sums every
    live PhaseRecorder; the residual vs the busy ledger is reported as
    ``unattributed_seconds`` (PhaseRecorders die with reloaded-away
    workloads, the ledger survives) and ``reconciles`` asserts the two
    agree within max(50 ms, 1%) — the tested invariant."""
    from ..telemetry import costs

    snap = costs.snapshot()
    workloads = []
    attributed = 0.0
    for kind, name, wl in workload_iter:
        phases = wl.processor.phases.phase_seconds()
        total = sum(phases.values())
        attributed += total
        workloads.append({
            "kind": kind,
            "workload": name,
            "phase_seconds": {p: round(s, 6)
                              for p, s in sorted(phases.items())},
            "device_seconds": round(total, 6),
        })
    busy = snap["busy_seconds_total"]
    residual = busy - attributed
    tolerance = max(0.05, 0.01 * busy)
    snap.update({
        "attributed_seconds": round(attributed, 6),
        "unattributed_seconds": round(residual, 6),
        "reconciles": abs(residual) <= tolerance,
        "tolerance_seconds": round(tolerance, 6),
        "workloads": workloads,
    })
    return _reply_json(200, snap)


def handle_memory() -> Reply:
    """``GET /debug/memory``: the HBM ledger (per-workload components,
    process components, headroom and overflow forecast)."""
    from ..telemetry import memory

    return _reply_json(200, memory.debug_snapshot())


def handle_loadmap(heatmap) -> Reply:
    """``GET /debug/loadmap``: sub-range heat per owned range with the
    suggested split point (``heatmap`` may be None — single-group
    deployments route nothing through a federation router)."""
    from ..telemetry import heat

    return _reply_json(200, heat.loadmap(heatmap))


def handle_slo() -> Reply:
    """``GET /debug/slo``: per-tracker burn-rate state plus recent
    violations with exemplar trace links."""
    from ..telemetry import slo

    return _reply_json(200, slo.debug_snapshot())


def handle_probes(prober) -> Reply:
    """``GET /debug/probes``: per-workload canary probe history with
    links into /debug/traces and /debug/decisions (``prober`` is None
    when DUKE_PROBE=0 — report disabled instead of 404 so dashboards
    can tell "off" from "missing")."""
    if prober is None:
        return _reply_json(200, {"enabled": False})
    return _reply_json(200, prober.debug_snapshot())
