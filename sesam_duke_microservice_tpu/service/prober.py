"""Background canary prober: ground-truth synthetic monitoring.

``CanaryProber`` owns one shadow workload per user workload.  The
shadow is built from a clone of the user's ``WorkloadConfig`` that
shares the SAME ``Property`` objects (identical plan fingerprint, so
PR 19's SharedLadderRegistry serves the probe's device scorers with
zero extra XLA compiles) but renames the workload and every dataset id
into the reserved ``__probe__`` namespace and swaps the link database
for an in-memory one.  Shadows are registered only here — the HTTP
registries never see them — so user-visible feed and link rows are
bit-identical with the prober on or off.

Each cycle stamps fresh entity ids onto the derived canary corpus
(telemetry.probes) and pushes them through the REAL path: the shared
``IngestScheduler`` admission (the prober is just another tenant),
device scoring, finalize, the link journal, and the same
``links_feed_page`` materialization that serves ``?since=``.  Observed
verdicts are then checked against the host f64 oracle expectations;
any divergence latches into a ring, flips the ``/healthz`` detail to
degraded, and records the offending trace/decision ids for
``GET /debug/probes``.

``RangeProber`` is the federation half: every owned range is probed
through its group's read path (``LocalGroup.links_walk``) so a downed
or mis-routed range surfaces as a per-range probe failure, rolled up
fleet-wide through the same ``GroupRollup`` as every other per-group
family.
"""

from __future__ import annotations

import dataclasses
import itertools
import logging
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..core.config import DataSourceConfig, DukeSchema, WorkloadConfig
from ..engine.workload import build_workload
from ..links.base import LinkKind, LinkStatus
from ..telemetry import JIT_COMPILES, probes, slo, tracing
from ..telemetry.env import env_int
from ..telemetry.probes import (PROBE_PREFIX, ProbeState, derive_canaries,
                                probe_interval_s, probe_name)
from ..telemetry.rings import LatchedRing
from ..utils import faults

logger = logging.getLogger(__name__)

_MISMATCH_SEQ = itertools.count(1)


def _probe_workload_config(wc: WorkloadConfig) -> WorkloadConfig:
    """Clone a user workload config into the probe namespace.

    Properties are shared BY REFERENCE: the device plan fingerprint
    hashes only the schema properties, so sharing them guarantees the
    shadow resolves to the user workload's shared AOT ladder.  Dataset
    configs are cloned with namespaced ids (same objects reused between
    ``data_sources`` and ``groups``, mirroring the parser)."""
    # dataset ids are unique within a workload, so they key the clone
    # memo (the parser reuses DataSourceConfig objects between
    # data_sources and groups; the clones must alias the same way)
    memo: Dict[str, DataSourceConfig] = {}

    def clone(ds: DataSourceConfig) -> DataSourceConfig:
        got = memo.get(ds.dataset_id)
        if got is None:
            got = DataSourceConfig(
                dataset_id=PROBE_PREFIX + ds.dataset_id,
                columns=ds.columns,
                group_no=ds.group_no,
            )
            memo[ds.dataset_id] = got
        return got

    duke = wc.duke
    probe_duke = DukeSchema(
        threshold=duke.threshold,
        maybe_threshold=duke.maybe_threshold,
        properties=duke.properties,
        data_sources=[clone(ds) for ds in duke.data_sources],
        groups=[[clone(ds) for ds in grp] for grp in duke.groups],
    )
    return WorkloadConfig(
        name=probe_name(wc.name),
        kind=wc.kind,
        duke=probe_duke,
        link_database_type="in-memory",
        link_mode=wc.link_mode,
        data_folder=None,
    )


class _Shadow:
    """One user workload's probe state: shadow workload + corpus."""

    __slots__ = ("workload", "corpus", "state", "ds_a", "ds_b", "cycle",
                 "compiles_base")

    def __init__(self, workload, corpus, state, ds_a, ds_b, compiles_base):
        self.workload = workload
        self.corpus = corpus
        self.state = state
        self.ds_a = ds_a
        self.ds_b = ds_b
        self.cycle = 0
        self.compiles_base = compiles_base


class CanaryProber:
    """Per-app synthetic monitor (one background thread; ``run_cycle``
    is also directly callable, which is how tests drive it)."""

    def __init__(self, app):
        self.app = app
        self._shadows: Dict[Tuple[str, str], _Shadow] = {}
        # serializes cycles against shutdown and shadow rebuilds
        self._cycle_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.ring = LatchedRing(max(1, env_int("DUKE_PROBE_RING", 64)))
        app.metrics.register_collector(self.collect)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name="canary-prober", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=30)
            self._thread = None
        with self._cycle_lock:
            for entry in self._shadows.values():
                self._close_shadow(entry)
            self._shadows.clear()

    def _loop(self) -> None:
        while not self._stop.wait(probe_interval_s()):
            try:
                self.run_cycle()
            except Exception:  # the prober must never take the app down
                logger.exception("probe cycle crashed")

    @staticmethod
    def _close_shadow(entry: _Shadow) -> None:
        try:
            entry.workload.close(save_snapshot=False)
        except Exception:
            logger.exception("probe shadow close failed")

    # -- scheduler integration -----------------------------------------------

    def resolve(self, kind: str, name: str):
        """Resolve a ``__probe__``-namespaced workload name for the
        scheduler's dispatch (DukeApp._resolve_workload delegates probe
        names here); None when no shadow exists, like the registries."""
        user = name[len(PROBE_PREFIX):]
        entry = self._shadows.get((kind, user))
        return entry.workload if entry is not None else None

    # -- cycle ----------------------------------------------------------------

    def run_cycle(self) -> Dict[Tuple[str, str], dict]:
        """One synchronous probe pass over every user workload."""
        results: Dict[Tuple[str, str], dict] = {}
        with self._cycle_lock:
            if self._stop.is_set():
                return results
            for kind, registry in (
                ("deduplication", self.app.deduplications),
                ("recordlinkage", self.app.record_linkages),
            ):
                for name, user_wl in list(registry.items()):
                    try:
                        entry = self._ensure_shadow(kind, name, user_wl)
                    except Exception:
                        logger.exception(
                            "probe shadow build failed for %s/%s", kind, name)
                        st = self._state_for(kind, name)
                        st.cycles += 1
                        st.note_failure("build")
                        continue
                    results[(kind, name)] = self._cycle_one(kind, name, entry)
        return results

    def _state_for(self, kind: str, name: str) -> ProbeState:
        entry = self._shadows.get((kind, name))
        if entry is not None:
            return entry.state
        # build failures keep their accounting without a shadow
        st = getattr(self, "_orphan_states", None)
        if st is None:
            st = self._orphan_states = {}
        key = (kind, name)
        if key not in st:
            st[key] = ProbeState(kind, name)
        return st[key]

    def _ensure_shadow(self, kind: str, name: str, user_wl) -> _Shadow:
        entry = self._shadows.get((kind, name))
        if entry is not None:
            max_records = max(16, env_int("DUKE_PROBE_MAX_RECORDS", 512))
            if (entry.cycle + 1) * 2 * len(entry.corpus) <= max_records:
                return entry
            # bounded shadow corpus: rebuild from scratch (cheap — the
            # shared AOT ladder stays warm) instead of growing forever
            self._close_shadow(entry)
            old_state = entry.state
        else:
            old_state = None

        pwc = _probe_workload_config(user_wl.config)
        sc = dataclasses.replace(
            self.app.config,
            # shadow corpora are tiny; retrieval relevance cutoffs tuned
            # for production-size corpora would starve the host index of
            # canary candidates and fake a scoring failure
            tunables=dataclasses.replace(
                self.app.config.tunables, min_relevance=0.0),
            threads=1,
        )
        compiles_base = JIT_COMPILES.single().value
        wl = build_workload(pwc, sc, backend=self.app.backend,
                            persistent=False)
        self._join_warm(wl)
        duke = pwc.duke
        if duke.groups:
            ds_a = wl.datasources[duke.groups[0][0].dataset_id]
            ds_b = wl.datasources[duke.groups[1][0].dataset_id]
        else:
            ds_a = ds_b = wl.datasources[duke.data_sources[0].dataset_id]
        corpus = derive_canaries(duke, ds_a, ds_b, wl.processor.compare)
        state = old_state if old_state is not None else ProbeState(kind, name)
        state.corpus_size = len(corpus)
        entry = _Shadow(wl, corpus, state, ds_a, ds_b, compiles_base)
        self._shadows[(kind, name)] = entry
        return entry

    @staticmethod
    def _join_warm(wl) -> None:
        """Wait out the AOT warm thread so compile accounting and first
        -cycle latency are deterministic (idiom: tests/aot_restart_child)."""
        cache = getattr(getattr(wl, "index", None), "scorer_cache", None)
        t = getattr(cache, "_warm_thread", None)
        if t is not None:
            t.join(timeout=600)

    def _cycle_one(self, kind: str, name: str, entry: _Shadow) -> dict:
        st = entry.state
        st.cycles += 1
        entry.cycle += 1
        cycle_no = entry.cycle
        pname = probe_name(name)
        summary: dict = {"cycle": cycle_no, "ok": False}

        pairs: List[tuple] = []  # (canary, record_id_a, record_id_b)
        batch_a: List[dict] = []
        batch_b: List[dict] = []
        for canary in entry.corpus:
            ea = dict(canary.values_a)
            ea["_id"] = f"{canary.key}-a-c{cycle_no}"
            eb = dict(canary.values_b)
            eb["_id"] = f"{canary.key}-b-c{cycle_no}"
            batch_a.append(ea)
            batch_b.append(eb)
            pairs.append((canary,
                          entry.ds_a.record_id_for_entity(ea),
                          entry.ds_b.record_id_for_entity(eb)))

        with tracing.start_trace(
            "probe.cycle",
            attributes={"kind": kind, "workload": name, "cycle": cycle_no},
        ) as root:
            summary["trace_id"] = root.trace_id
            t_start = time.monotonic()
            try:
                self._submit(kind, pname,
                             entry.ds_a.config.dataset_id, batch_a)
                if entry.ds_b is not entry.ds_a:
                    self._submit(kind, pname,
                                 entry.ds_b.config.dataset_id, batch_b)
                else:
                    self._submit(kind, pname,
                                 entry.ds_a.config.dataset_id, batch_b)
            except Exception as exc:
                st.note_failure("submit")
                summary["error"] = f"submit: {type(exc).__name__}: {exc}"
                st.stage_hists["ingest"].observe(time.monotonic() - t_start)
                self._finish_cycle(entry, summary)
                return summary
            t_ingest = time.monotonic()
            st.stage_hists["ingest"].observe(t_ingest - t_start)

            try:
                observed = self._observe_links(entry, pairs)
            except Exception as exc:
                st.note_failure("observe")
                summary["error"] = f"observe: {type(exc).__name__}: {exc}"
                self._finish_cycle(entry, summary)
                return summary
            t_score = time.monotonic()
            st.stage_hists["score"].observe(t_score - t_ingest)

            mismatches = self._check_verdicts(
                entry, pairs, observed, summary)

            feed_ok = True
            t_feed0 = time.monotonic()
            try:
                feed_ids = self._feed_ids(entry.workload)
            except Exception as exc:
                st.note_failure("feed")
                summary["error"] = f"feed: {type(exc).__name__}: {exc}"
                feed_ok = False
                feed_ids = set()
            st.stage_hists["feed"].observe(time.monotonic() - t_feed0)
            if feed_ok:
                for canary, id_a, id_b in pairs:
                    if canary.expected_verdict == "reject":
                        continue
                    ids = sorted((id_a, id_b))
                    row_id = f"{ids[0]}_{ids[1]}".replace(":", "_")
                    if row_id not in feed_ids:
                        st.note_failure("feed_missing")
                        feed_ok = False

            total_s = time.monotonic() - t_start
            summary["seconds"] = round(total_s, 6)
            summary["verdicts"] = {
                c.key: {"expected": c.expected_verdict,
                        "observed": observed.get(c.key)}
                for c, _, _ in pairs
            }
            summary["ok"] = feed_ok and not mismatches and "error" not in summary
            slo.tracker("probe", kind, name).record(
                total_s, trace_id=tracing.sampled_trace_id())

        if summary["ok"]:
            st.ok_cycles += 1
            st.last_ok_monotonic = time.monotonic()
        if entry.cycle == 1:
            st.probe_compiles = (
                JIT_COMPILES.single().value - entry.compiles_base)
        self._finish_cycle(entry, summary)
        return summary

    def _finish_cycle(self, entry: _Shadow, summary: dict) -> None:
        summary["time_unix"] = round(time.time(), 3)
        entry.state.last = summary
        entry.state.history.append(summary)

    def _submit(self, kind: str, pname: str, dataset_id: str,
                entities: List[dict]) -> None:
        sched = getattr(self.app, "scheduler", None)
        if sched is not None:
            sched.submit(kind, pname, dataset_id, entities)
            return
        wl = self.resolve(kind, pname)
        if wl is None:
            raise KeyError(pname)
        wl.submit_batch(dataset_id, entities)

    def _observe_links(self, entry: _Shadow, pairs) -> Dict[str, str]:
        """Served verdict per canary from the shadow's link journal."""
        ids = {rid for _, a, b in pairs for rid in (a, b)}
        wl = entry.workload
        with wl.lock:
            links = wl.link_database.get_links_for_ids(ids)
        by_key = {}
        for link in links:
            if link.status == LinkStatus.RETRACTED:
                continue
            by_key[link.key()] = link
        out: Dict[str, str] = {}
        for canary, id_a, id_b in pairs:
            link = by_key.get(tuple(sorted((id_a, id_b))))
            if link is None:
                out[canary.key] = "reject"
            elif link.kind == LinkKind.MAYBE:
                out[canary.key] = "maybe"
            else:
                out[canary.key] = "match"
        return out

    def _check_verdicts(self, entry: _Shadow, pairs,
                        observed: Dict[str, str], summary: dict) -> int:
        st = entry.state
        mismatches = 0
        plan = faults.active()
        for canary, id_a, id_b in pairs:
            verdict = observed.get(canary.key, "reject")
            if plan is not None and plan.probe_flip():
                # fault drill: corrupt this canary's served verdict at
                # the readback seam, as a real finalize corruption would
                verdict = "match" if canary.expected_verdict != "match" \
                    else "reject"
                observed[canary.key] = verdict
            if verdict == canary.expected_verdict:
                continue
            mismatches += 1
            st.mismatches += 1
            record = {
                "id": f"m{next(_MISMATCH_SEQ):06d}",
                "time_unix": round(time.time(), 3),
                "kind": st.kind,
                "workload": st.name,
                "pair": canary.key,
                "id1": id_a,
                "id2": id_b,
                "expected": canary.expected_verdict,
                "expected_prob": canary.expected_prob,
                "observed": verdict,
                "trace_id": tracing.current_trace_id(),
                "decision_ids": self._decision_ids(
                    entry.workload, {id_a, id_b}),
            }
            self.ring.put(record["id"], record, remarkable=True)
            logger.error(
                "probe verdict mismatch %s/%s pair=%s expected=%s observed=%s",
                st.kind, st.name, canary.key, canary.expected_verdict,
                verdict)
        return mismatches

    @staticmethod
    def _decision_ids(wl, record_ids) -> List[str]:
        """Decision-ring entries touching the mismatching pair, for the
        /debug/probes → /debug/decisions join."""
        ring = getattr(getattr(wl.processor, "decisions", None), "ring", None)
        if ring is None:
            return []
        out = []
        for rec in ring.records():
            if rec.get("query") in record_ids or \
                    rec.get("candidate") in record_ids:
                out.append(rec["id"])
            if len(out) >= 8:
                break
        return out

    @staticmethod
    def _feed_ids(wl) -> set:
        """Non-deleted row ids from a full ``?since=`` walk — the same
        ``links_feed_page`` materialization HTTP serves."""
        out = set()
        since = 0
        while True:
            rows, nxt = wl.links_page(since, 500)
            if not rows:
                return out
            for row in rows:
                if not row.get("_deleted"):
                    out.add(row["_id"])
                else:
                    out.discard(row["_id"])
            since = nxt

    # -- read surfaces --------------------------------------------------------

    def states(self) -> List[ProbeState]:
        states = [e.state for e in self._shadows.values()]
        states.extend(getattr(self, "_orphan_states", {}).values())
        return states

    def collect(self):
        return probes.probe_families(self.states())

    def health_detail(self) -> Optional[dict]:
        per = {
            f"{st.kind}/{st.name}": st.mismatches
            for st in self.states() if st.mismatches
        }
        if not per:
            return None
        return {"verdict_mismatches": sum(per.values()), "workloads": per}

    def debug_snapshot(self) -> dict:
        mismatches = []
        for rec in self.ring.records():
            row = dict(rec)
            if row.get("trace_id"):
                row["trace"] = f"/debug/traces/{row['trace_id']}"
            row["decisions"] = [
                f"/debug/decisions/{d}" for d in row.get("decision_ids", [])]
            mismatches.append(row)
        return {
            "enabled": True,
            "interval_seconds": probe_interval_s(),
            "workloads": sorted(
                (st.snapshot() for st in self.states()),
                key=lambda s: (s["kind"], s["workload"]),
            ),
            "mismatches": mismatches,
        }


# -- federation range probing -------------------------------------------------

class RangeProber:
    """Black-box per-range reachability probe through the group read
    path.  A range whose owner group is down, busy, or mis-routed fails
    its probe — surfaced per range on the federation plane before any
    consumer's ``?since=`` poll hits it."""

    def __init__(self, fed):
        self.fed = fed
        self._lock = threading.Lock()
        # guarded by: self._lock [writes]
        self._checks: Dict[str, Dict[str, int]] = {}
        self._groups: Dict[str, int] = {}
        self._errors: Dict[str, str] = {}
        self._cycles = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name="range-prober", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=30)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(probe_interval_s()):
            try:
                self.run_cycle()
            except Exception:
                logger.exception("range probe cycle crashed")

    def run_cycle(self) -> Dict[str, str]:
        """Probe every owned range once; returns range_id -> outcome."""
        pmap = self.fed.map
        outcomes: Dict[str, str] = {}
        with self._lock:
            self._cycles += 1
        for rng in pmap.ranges():
            group = self.fed.groups[rng.group]
            workloads = sorted(group.workloads)
            if not workloads:
                continue
            kind, name = workloads[0]
            outcome, err = "ok", None
            try:
                group.links_walk(kind, name, 0, 1)
            except Exception as exc:
                outcome, err = "fail", type(exc).__name__
            outcomes[rng.range_id] = outcome
            with self._lock:
                per = self._checks.setdefault(rng.range_id, {})
                per[outcome] = per.get(outcome, 0) + 1
                self._groups[rng.range_id] = rng.group
                if err is not None:
                    self._errors[rng.range_id] = err
                else:
                    self._errors.pop(rng.range_id, None)
        return outcomes

    def collector_for(self, idx: int):
        """Scrape collector for ONE group's owned ranges — registered on
        that group's rollup registry so GroupRollup merges the fleet
        view (telemetry.rollup) like every other per-group family."""
        def collect():
            with self._lock:
                checks = {
                    rid: dict(per) for rid, per in self._checks.items()
                    if self._groups.get(rid) == idx
                }
                groups = {rid: idx for rid in checks}
            if not checks:
                return []
            return [probes.range_probe_family(checks, groups)]
        return collect

    def failing_ranges(self) -> List[str]:
        with self._lock:
            return sorted(self._errors)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "enabled": True,
                "interval_seconds": probe_interval_s(),
                "cycles": self._cycles,
                "ranges": {
                    rid: {
                        "group": self._groups.get(rid),
                        "checks": dict(per),
                        "last_error": self._errors.get(rid),
                    }
                    for rid, per in sorted(self._checks.items())
                },
            }
