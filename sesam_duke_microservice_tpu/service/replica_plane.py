"""Follower-side HTTP read plane (ISSUE 8 tentpole).

The reference funnels every incremental ``?since=`` poll through the one
process that owns the link DB (App.java:742,843); our multi-host mode
inherited that — process 0 answered every poll under its workload locks
while followers only replayed.  This module serves the read-dominant
surface from a follower's REPLICA state instead:

  * ``GET /{kind}/{name}?since=N`` — the incremental link feed, served
    from the replica link DB with link endpoints resolved through the
    replica corpus mirror.  Rows materialize through the SAME
    ``links.replica.feed_row`` the leader uses, so a replica page is
    bit-identical to the leader's at the same watermark.  **No leader
    lock is ever taken** — that is the point: polling load from millions
    of downstream consumers scales with read replicas, not with the one
    ingest process.
  * ``GET /healthz`` / ``/readyz`` — liveness + readiness (bootstrapped
    replicas present), both reporting replication lag.
  * ``GET /stats`` — per-workload watermark/lag/row counts.
  * ``GET /metrics`` — the process-global telemetry registry, with the
    ``duke_replica_lag_ops`` gauge refreshed at scrape time from the
    replica watermarks (scrape-time snapshot — the replay hot path never
    writes a registry child).

Staleness contract: reads are **bounded-staleness** — a replica serves
whatever its applied watermark covers and stamps every feed response
with ``X-Replica-Lag: <ops>`` (link-stream batches seen but not yet
applied), so a consumer that needs read-your-writes can poll the leader
instead and everyone else gets horizontal scale.
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from .. import telemetry
from ..links.replica import links_feed_page
from ..telemetry import tracing
from . import debug as debug_api
from .app import (
    _DEBUG_TRACE_PATH,
    _FEED_PATH,
    _feed_page_size,
    _kind_label,
    write_chunk,
)

logger = logging.getLogger("replica-plane")


class ReplicaReadHandler(BaseHTTPRequestHandler):
    session = None  # the follower's _FollowerSession; set by serve()
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):
        logger.info("%s %s", self.address_string(), fmt % args)

    def _reply(self, status: int, body: bytes,
               content_type: str = "application/json",
               extra_headers=None) -> None:
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        for k, v in (extra_headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        try:
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            logger.info("Ignoring client disconnect on %s", self.path)

    def _reply_json(self, status: int, obj, extra_headers=None) -> None:
        self._reply(status, json.dumps(obj).encode("utf-8"),
                    extra_headers=extra_headers)

    # -- lag bookkeeping -----------------------------------------------------

    def _lag_snapshot(self):
        """{(kind, name): lag_ops} plus the scrape-time gauge refresh."""
        out = {}
        for key, db in list(self.session.link_replicas.items()):
            lag = db.lag_ops()
            out[key] = lag
            telemetry.REPLICA_LAG.labels(kind=key[0], workload=key[1]).set(lag)
        # the follower's adopted epoch (the leader sets this gauge from
        # Dispatcher.start/promotion; on a follower the session is the
        # authority)
        telemetry.DISPATCH_EPOCH.set(self.session.epoch)
        return out

    # -- routes --------------------------------------------------------------

    def do_GET(self):
        try:
            parsed = urlparse(self.path)
            # root span per request (ISSUE 16): same W3C propagation the
            # leader and federation planes do, so /debug/requests and
            # /debug/traces work on a read replica too
            with tracing.start_trace(
                f"GET {parsed.path}",
                traceparent=self.headers.get("traceparent"),
                attributes={"http.method": "GET",
                            "http.target": parsed.path},
            ):
                self._route(parsed)
        except Exception:
            logger.exception("replica plane: error serving %s", self.path)
            self._reply(500, b"Internal server error", "text/plain")

    def _route(self, parsed) -> None:
        path = parsed.path
        if path in ("/health", "/healthz"):
            lags = self._lag_snapshot()
            self._reply_json(200, {
                "status": "ok",
                "role": "replica",
                "epoch": self.session.epoch,
                "replication_lag_ops": sum(lags.values()),
                "stale_ops_rejected": self.session.stale_rejected,
            })
        elif path == "/readyz":
            ready = bool(self.session.replicas)
            self._reply_json(200 if ready else 503, {
                "status": "ready" if ready else "unready",
                "checks": {"replicas_bootstrapped": ready},
            })
        elif path == "/metrics":
            self._lag_snapshot()  # refresh the lag gauge children
            body = telemetry.render(telemetry.GLOBAL).encode("utf-8")
            self._reply(200, body, telemetry.CONTENT_TYPE)
        elif path == "/stats":
            self._handle_stats()
        elif path == "/debug/traces":
            self._reply(*debug_api.handle_traces())
        elif m := _DEBUG_TRACE_PATH.match(path):
            fmt = (parse_qs(parsed.query).get("format") or ["json"])[0]
            self._reply(*debug_api.handle_trace(m.group(1), fmt))
        elif path == "/debug/requests":
            self._reply(*debug_api.handle_requests())
        elif path == "/debug/profile":
            self._reply(*debug_api.handle_profile_status())
        elif path == "/debug/costs":
            # a replica runs no workload processors of its own; the
            # process-level ledger (compile/busy credited by replay)
            # still reconciles trivially with zero attributed seconds
            self._reply(*debug_api.handle_costs())
        elif path == "/debug/memory":
            self._reply(*debug_api.handle_memory())
        elif path == "/debug/slo":
            self._reply(*debug_api.handle_slo())
        elif m := _FEED_PATH.match(path):
            self._handle_feed(m, parse_qs(parsed.query))
        else:
            self._reply(404, b"Not found (replica read plane serves "
                        b"feeds, /stats, /metrics, /debug/traces, "
                        b"/debug/requests, /debug/profile, /debug/costs, "
                        b"/debug/memory, /debug/slo and health probes)",
                        "text/plain")

    def do_POST(self):
        try:
            parsed = urlparse(self.path)
            with tracing.start_trace(
                f"POST {parsed.path}",
                traceparent=self.headers.get("traceparent"),
                attributes={"http.method": "POST",
                            "http.target": parsed.path},
            ):
                self._route_post(parsed)
        except Exception:
            logger.exception("replica plane: error serving %s", self.path)
            self._reply(500, b"Internal server error", "text/plain")

    def _route_post(self, parsed) -> None:
        # drain any body so keep-alive framing survives the reply
        length = int(self.headers.get("Content-Length") or 0)
        if length:
            self.rfile.read(length)
        path = parsed.path
        if path == "/debug/profile":
            # ISSUE 17 satellite: a federated/replicated deployment can
            # capture a device trace through any plane's front door; the
            # owner tag makes a cross-plane conflict 409 say who holds
            # the profiler and until when
            self._reply(*debug_api.handle_profile_start(
                parse_qs(parsed.query), owner="replica"))
        elif path == "/debug/profile/reset":
            self._reply(*debug_api.handle_profile_reset())
        else:
            self._reply(404, b"Not found (replica read plane accepts "
                        b"POST /debug/profile and "
                        b"POST /debug/profile/reset)", "text/plain")

    def _handle_stats(self) -> None:
        lags = self._lag_snapshot()
        workloads = []
        for key, replica in list(self.session.replicas.items()):
            kind, name = key
            db = self.session.link_replicas.get(key)
            row = {
                "kind": kind,
                "name": name,
                "records_indexed": replica.index.corpus.size
                if getattr(replica.index, "corpus", None) is not None
                else len(replica.index),
            }
            if db is not None:
                row.update(
                    links_rows=db.count(),
                    applied_seq=db.applied_seq,
                    head_seq=db.head_seq,
                    lag_ops=lags.get(key, 0),
                )
            workloads.append(row)
        self._reply_json(200, {
            "role": "replica",
            "epoch": self.session.epoch,
            "follower_idx": self.session.follower_idx,
            "stale_ops_rejected": self.session.stale_rejected,
            "workloads": workloads,
        })

    def _handle_feed(self, m, query) -> None:
        kind, name = m.group(1), m.group(2)
        label = _kind_label(kind)
        if not name:
            self._reply(400, f"The {label}Name cannot be an empty string!"
                        .encode(), "text/plain")
            return
        key = (kind, name)
        replica = self.session.replicas.get(key)
        db = self.session.link_replicas.get(key)
        if replica is None or db is None:
            self._reply(
                400,
                (f"Unknown {label} '{name}'! (All {label}s must be "
                 f"specified in the configuration)").encode(),
                "text/plain",
            )
            return
        since = 0
        since_params = query.get("since")
        if since_params and since_params[0]:
            try:
                since = int(since_params[0])
            except ValueError:
                self._reply(400, f"Invalid since value '{since_params[0]}'"
                            .encode(), "text/plain")
                return
        # bounded-staleness read, STREAMED in bounded pages (the leader's
        # own discipline, same FEED_PAGE_SIZE knob): a multi-million-row
        # backlog never materializes in replica memory either.  Lag is
        # computed once at response start — the header describes the
        # watermark the page walk began at.  No registry write here: the
        # feed path stays metric-free (the lag gauge refreshes at scrape
        # time, _lag_snapshot).
        lag = db.lag_ops()
        page_size = _feed_page_size()
        if self.request_version == "HTTP/1.0":
            # no chunked framing pre-1.1: buffered single array
            rows, cursor = [], since
            while True:
                page, cursor = links_feed_page(db, replica.index, cursor,
                                               page_size)
                rows.extend(page)
                if len(page) < page_size:
                    break
            body = "[" + ",\n".join(json.dumps(r) for r in rows) + "]"
            self._reply(200, body.encode("utf-8"),
                        extra_headers={"X-Replica-Lag": str(lag)})
            return
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Transfer-Encoding", "chunked")
        self.send_header("X-Replica-Lag", str(lag))
        self.end_headers()
        try:
            self._write_chunk(b"[")
            first = True
            cursor = since
            while True:
                page, cursor = links_feed_page(db, replica.index, cursor,
                                               page_size)
                if page:
                    payload = ",\n".join(json.dumps(r) for r in page)
                    if not first:
                        payload = ",\n" + payload
                    first = False
                    self._write_chunk(payload.encode("utf-8"))
                if len(page) < page_size:
                    break
            self._write_chunk(b"]")
            self.wfile.write(b"0\r\n\r\n")
        except (BrokenPipeError, ConnectionResetError):
            logger.info("Ignoring client disconnect on %s", self.path)
            self.close_connection = True
        except Exception:
            # headers + chunks are on the wire: a second status line
            # (the do_GET 500 path) would land mid-chunked-body as
            # garbage framing.  Truncate instead — the client sees a
            # protocol error, never silent partial success (the leader
            # feed's own mid-stream stance).
            logger.exception("replica feed failed mid-stream on %s",
                             self.path)
            self.close_connection = True

    def _write_chunk(self, data: bytes) -> None:
        write_chunk(self.wfile, data)


def serve_replica_plane(session, port: int,
                        host: str = "0.0.0.0") -> ThreadingHTTPServer:
    """Bind the replica read plane for ``session`` and serve it on a
    daemon thread; returns the server (caller owns ``shutdown()``)."""
    handler = type("BoundReplicaHandler", (ReplicaReadHandler,),
                   {"session": session})
    server = ThreadingHTTPServer((host, port), handler)
    thread = threading.Thread(target=server.serve_forever,
                              name="replica-read-plane", daemon=True)
    thread.start()
    logger.info("replica read plane serving on %s:%d", host,
                server.server_address[1])
    return server
