"""Per-workload runtime bundle and the ingest/feed flows.

The equivalent of the reference's ``App.Deduplication`` / ``App.RecordLinkage``
inner classes (App.java:87-189): each workload owns its datasources, blocking
index, processor, listener, link database, and a lock serializing access
(writers block; readers time out after 1 s and surface 503 — App.java:718-725,
827-834, enforced by the HTTP layer).

Flow parity notes:
  * POST batch (App.java:924-1028 / 1065-1179): parse -> records -> partition
    deleted/live -> tombstone + retract links for deleted -> deduplicate live.
  * Deleted-record detection uses the hidden ``dukeDeleted`` property for
    BOTH workloads.  The reference's dedup path checks a nonexistent
    ``_deleted`` property (App.java:974) so its dedup deletes never retract
    links (SURVEY.md quirk Q2) — deliberately fixed here.
  * http-transform disables indexing AND link-db updates for BOTH workloads.
    The reference only does so for record linkage (quirk Q6: a dedup
    "transform" has full side effects) — deliberately fixed here.
  * GET feed rows (App.java:744-770): `_id` = id1+"_"+id2 with ':'->'_',
    `_updated` = link timestamp, `_deleted` = retracted, entity/dataset
    fields resolved by index point-lookups.
"""

from __future__ import annotations

import contextlib
import logging
import threading
import time
import weakref
from typing import Dict, List, Optional, Sequence

from ..core.config import ServiceConfig, WorkloadConfig
from ..core.records import Record
from ..index.base import CandidateIndex
from ..index.inverted import InvertedIndex
from ..links import create_link_database
from ..links.base import LinkDatabase
from ..service.datasource import IncrementalDataSource
from ..store.records import RecordStore
from ..telemetry import memory
from ..utils import faults
from .listeners import ServiceMatchListener
from .processor import Processor


def _snapshot_path(data_folder: str) -> str:
    import os

    return os.path.join(data_folder, "corpus_snapshot.npz")


def _hbm_components(wl_ref) -> Dict[str, int]:
    """Device-buffer bytes for one workload, keyed by component — the
    HBM ledger's registered callable.  Reads single-writer numpy mirrors
    lock-free (torn reads tolerated, the /stats stance); host backends
    (no device corpus) report nothing."""
    wl = wl_ref()
    if wl is None:
        return {}
    corpus = getattr(wl.index, "corpus", None)
    if corpus is None:
        return {}
    from ..ops.encoder import ANN_PROP, ANN_SCALE

    out = {"corpus_tensors": 0, "corpus_embeddings": 0, "int8_scales": 0}
    for prop, arrays in list(corpus.feats.items()):
        for name, arr in list(arrays.items()):
            nbytes = int(getattr(arr, "nbytes", 0) or 0)
            if prop == ANN_PROP:
                if name == ANN_SCALE:
                    out["int8_scales"] += nbytes
                else:
                    out["corpus_embeddings"] += nbytes
            else:
                out["corpus_tensors"] += nbytes
    for mask in ("row_valid", "row_deleted", "row_group"):
        arr = getattr(corpus, mask, None)
        out["corpus_tensors"] += int(getattr(arr, "nbytes", 0) or 0)
    ivf = getattr(wl.index, "ivf", None)
    if ivf is not None:
        out["ivf_membership"] = sum(
            int(getattr(getattr(ivf, field, None), "nbytes", 0) or 0)
            for field in ("centroids", "cell_of", "cell_rows", "counts"))
    return {k: v for k, v in out.items() if v}


def _arena_heat(wl_ref) -> float:
    """Accumulated per-workload device-seconds from the cost ledger's
    phase recorder — the arena's eviction heat (ISSUE 19): among cold
    candidates, the tenant that has burned the least device time spills
    first.  Lock-free torn reads tolerated (ordering hint only)."""
    wl = wl_ref()
    if wl is None:
        return 0.0
    phases = getattr(wl.processor, "phases", None)
    if phases is None:
        return 0.0
    try:
        return float(sum(phases.phase_seconds().values()))
    except Exception:
        return 0.0


class _BatchRequest:
    """One queued ingest request awaiting the merged device batch."""

    __slots__ = ("dataset_id", "entities", "event", "error")

    def __init__(self, dataset_id: str, entities: Sequence[dict]):
        self.dataset_id = dataset_id
        self.entities = entities
        self.event = threading.Event()
        self.error: Optional[Exception] = None


class Workload:
    def __init__(self, config: WorkloadConfig, index: CandidateIndex,
                 processor: Processor, listener: ServiceMatchListener,
                 link_database: LinkDatabase,
                 record_store: Optional[RecordStore] = None):
        self.config = config
        self.name = config.name
        self.kind = config.kind
        self.index = index
        self.processor = processor
        self.listener = listener
        self.link_database = link_database
        self.record_store = record_store
        self.lock = threading.Lock()
        # set under self.lock when a config reload replaces this workload;
        # handlers that resolved a stale reference re-check after locking
        self.closed = False
        # ingest microbatching: concurrent POSTs queue here and whichever
        # thread wins the workload lock processes the whole queue as ONE
        # device batch (self._mb_mutex orders queue access; it is never
        # held while acquiring self.lock)
        self._mb_mutex = threading.Lock()
        self._mb_queue: List[_BatchRequest] = []
        # recent write-side lock-hold EWMA (seconds): busy-503s derive
        # their Retry-After from it, so a reader told to come back gets a
        # hint shaped by how long writers actually hold this workload.
        # Written under self.lock (every observed hold IS a lock hold),
        # read lock-free by the HTTP layer.
        self._hold_ewma: Optional[float] = None
        # Sticky store/index divergence latch: set when a record_store
        # write committed but its index application (tombstone indexing /
        # link retraction / scoring pass) then failed.  While set, the
        # store holds rows the index never applied, so _mark_synced must
        # never stamp again in this process — ANY later stamp would cover
        # the orphaned rows (the store hash includes them) and the restart
        # staleness guard would skip the replay that re-applies them.
        # Cleared only by a restart replay (a fresh Workload).
        self._store_dirty = False
        self.datasources: Dict[str, IncrementalDataSource] = {
            ds.dataset_id: IncrementalDataSource(ds)
            for ds in config.duke.data_sources
        }
        # HBM ledger enrollment (telemetry/memory.py): the components
        # callable holds this workload weakly, so a reload-replaced
        # workload drops out of the books with its last reference and
        # the closed flag hides it meanwhile.  Arena-enabled device
        # corpora register as LOGICAL views (ISSUE 19): the arena owns
        # the physical slab bytes and attributes them once; this
        # registration keeps per-tenant attribution without double
        # counting the budget.
        from ..ops.arena import arena_enabled

        wl_ref = weakref.ref(self)
        corpus = getattr(index, "corpus", None)
        memory.register(self, self.kind, self.name,
                        lambda: _hbm_components(wl_ref),
                        logical=corpus is not None and arena_enabled())
        if corpus is not None:
            # arena identity + eviction heat: device_arrays admits
            # under these (engine.device_matcher.DeviceCorpus)
            corpus.arena_label = f"{self.kind}/{self.name}"
            corpus.arena_heat = lambda: _arena_heat(wl_ref)

    def replace_link_database(self, link_database: LinkDatabase) -> None:
        """Swap the link database wrapper in place — the dispatcher
        installs the HA link-stream publisher this way (ISSUE 8).  Call
        before serving starts or with ``self.lock`` held: the write path
        (listener chain), the read path (feeds), and the delete path all
        resolve through the new wrapper from then on."""
        self.link_database = link_database
        self.listener._wrapped.linkdb = link_database

    # -- lock-hold observations ---------------------------------------------

    def note_lock_hold(self, seconds: float) -> None:
        """Fold one write-side lock-hold duration into the EWMA (call with
        ``self.lock`` held — batch paths and the scheduler dispatcher)."""
        from .scheduler import fold_ewma

        self._hold_ewma = fold_ewma(self._hold_ewma, seconds)

    def busy_retry_after(self) -> int:
        """Whole-second Retry-After hint for lock-timeout busy replies:
        the recent write hold, ceil'd and clamped (ONE policy copy —
        engine.scheduler.retry_after_seconds — for every Retry-After
        source)."""
        from .scheduler import retry_after_seconds

        ewma = self._hold_ewma
        if ewma is None:
            return 1
        return retry_after_seconds(ewma)

    # -- ingest + match -----------------------------------------------------

    def submit_batch(self, dataset_id: str, entities: Sequence[dict],
                     http_transform: bool = False) -> Optional[List[dict]]:
        """Handler entry: lock discipline + ingest microbatching.

        Non-transform POSTs that arrive while another request holds the
        workload lock are queued; whichever thread next wins the lock runs
        the whole queue as ONE merged device batch (per-request conversion
        errors stay per-request), so many small concurrent POSTs cost one
        scoring program instead of N — the request-aggregation half of
        SURVEY.md section 7 hard part 6.  The reference serializes every
        POST on the workload lock (App.java:947) with no aggregation.

        Transforms keep their own lock-held call: their response rows are
        per-request state on the shared listener.  Returns None when the
        workload was replaced by a config reload mid-flight (caller
        re-resolves the registry and resubmits); raises this request's
        error otherwise.
        """
        if http_transform:
            with self.lock:
                if self.closed:
                    return None
                return self.process_batch(dataset_id, entities,
                                          http_transform=True)

        req = _BatchRequest(dataset_id, entities)
        with self._mb_mutex:
            self._mb_queue.append(req)
        with self.lock:
            if not req.event.is_set():
                with self._mb_mutex:
                    if self.closed:
                        # a reload replaced this workload while we waited;
                        # withdraw (if a pre-close leader already took the
                        # request its event is set and we fall through)
                        if req in self._mb_queue:
                            self._mb_queue.remove(req)
                            return None
                    work, self._mb_queue = self._mb_queue, []
                if work:
                    t0 = time.monotonic()
                    try:
                        self._run_merged(work)
                    finally:
                        self.note_lock_hold(time.monotonic() - t0)
        if not req.event.is_set():  # withdrawn post-close without a leader
            return None
        if req.error is not None:
            raise req.error
        return []

    def _retract_links_for(self, deleted: Sequence[Record]) -> None:
        """Retract every link touching the deleted records.

        ONE batched prefetch for the whole set: per-record
        ``get_all_links_for`` calls would pay a write-behind drain
        round-trip per record (each record's buffered retracts sealed and
        flushed by the next record's read).  A link touching two deleted
        records is retracted once; re-asserting it identically is
        idempotent either way.
        """
        if not deleted:
            return
        ids = [r.record_id for r in deleted]
        for link in self.link_database.get_links_for_ids(ids):
            link.retract()
            self.link_database.assert_link(link)

    def _mesh_op_lock(self):
        """Multi-host serving: the dispatcher's global op lock, held across
        every device-program-producing section so processes enqueue mesh
        programs in ONE global order (parallel.dispatch invariant 2).
        Single-process serving gets a no-op context."""
        from ..parallel import dispatch

        d = dispatch.current()
        return d.op_lock if d is not None else contextlib.nullcontext()

    def _mark_synced(self) -> None:
        """Stamp the index as fully caught up with the store (consumed by
        the snapshot staleness guard — engine.device_matcher
        .mark_store_synced).  Called only after a batch applied end to
        end; a failure between the store write and the index commit
        leaves the stamp stale, forcing a replay on the next restart.
        Once any batch left the store ahead of the index
        (``_store_dirty``), no later batch may stamp either — the store
        hash would cover the orphaned rows."""
        if self.record_store is None or self._store_dirty:
            return
        mark = getattr(self.index, "mark_store_synced", None)
        if mark is not None:
            mark(self.record_store.content_hash())

    def _run_merged(self, work: List[_BatchRequest]) -> None:
        """Process queued requests as one batch (call with self.lock held).

        Serializability: merging applies every request's deletes before one
        shared scoring pass, so a merged group whose requests delete and
        upsert the SAME record id with opposite polarity (req A deletes X /
        adds Y merged with req B deletes Y / adds X) would end in a state
        matching no serial order.  Such conflicts split the queue: the
        merged group flushes (deletes + one scoring pass) before the
        conflicting request starts a new group, making the outcome equal to
        executing the groups — and therefore the requests — in queue order.
        Same-polarity overlap needs no split: repeated deletes retract
        idempotently and repeated upserts index in queue order inside one
        scoring pass (later content wins), exactly as serial execution.
        """
        group: List[_BatchRequest] = []
        group_records: List[List[Record]] = []
        deleted_ids: set = set()
        live_ids: set = set()

        def flush():
            nonlocal group, group_records, deleted_ids, live_ids
            all_live: List[Record] = []
            any_deleted = False
            ok: List[_BatchRequest] = []
            for req, records in zip(group, group_records):
                put_done = False
                try:
                    if self.record_store is not None:
                        self.record_store.put_many(records)
                        # kill-differential site (ISSUE 10): store rows
                        # durable, index/scoring/links not yet applied
                        faults.check_crash("post_store_put")
                        put_done = True
                    deleted = [r for r in records if r.is_deleted()]
                    for record in deleted:
                        self.index.index(record)
                    self._retract_links_for(deleted)
                except Exception as e:  # store errors stay per-request
                    if put_done:
                        # the store committed rows the index will never
                        # apply: latch the divergence so no later stamp
                        # (this flush or any future batch) can mask it
                        # (_mark_synced honors the latch)
                        self._store_dirty = True
                    req.error = e
                    req.event.set()
                    continue
                any_deleted = any_deleted or bool(deleted)
                all_live.extend(r for r in records if not r.is_deleted())
                ok.append(req)
            try:
                with self._mesh_op_lock():
                    if any_deleted:
                        self.index.commit()
                        # seal the retraction writes even when no scoring
                        # pass (and thus no listener batch_done/commit)
                        # follows — a delete-only group must not leave
                        # them unsealed in the write-behind buffer
                        self.link_database.commit()
                    if all_live:
                        self.processor.deduplicate(all_live)
                if ok:
                    self._mark_synced()
            except Exception as e:
                if self.record_store is not None and ok:
                    # the group's store writes committed but the shared
                    # scoring/commit pass did not complete
                    self._store_dirty = True
                for req in ok:
                    req.error = e
            finally:
                for req in ok:
                    req.event.set()
            group, group_records = [], []
            deleted_ids, live_ids = set(), set()

        for req in work:
            try:  # conversion errors stay per-request
                datasource = self.datasources[req.dataset_id]
                records = datasource.records_for_batch(req.entities)
            except Exception as e:
                req.error = e
                req.event.set()
                continue
            req_deleted = {r.record_id for r in records if r.is_deleted()}
            req_live = {r.record_id for r in records if not r.is_deleted()}
            if (req_deleted & live_ids) or (req_live & deleted_ids):
                flush()
            group.append(req)
            group_records.append(records)
            deleted_ids |= req_deleted
            live_ids |= req_live
        if group:
            flush()

    def process_batch(self, dataset_id: str, entities: Sequence[dict],
                      http_transform: bool = False) -> List[dict]:
        """Ingest a batch and run matching; returns the transform response
        rows (input entities + duke_links) when ``http_transform``."""
        t_hold = time.monotonic()
        datasource = self.datasources[dataset_id]
        records = datasource.records_for_batch(entities)
        live = [r for r in records if not r.is_deleted()]
        deleted = [r for r in records if r.is_deleted()]

        put_done = False
        try:
            if http_transform:
                self.index.set_indexing_disabled(True)
                self.listener.set_link_database_updates_disabled(True)
            else:
                if self.record_store is not None:
                    # durable source of truth first; the blocking index is a
                    # replayable cache of this store (SURVEY.md section 7)
                    self.record_store.put_many(records)
                    faults.check_crash("post_store_put")
                    put_done = True
                for record in deleted:
                    # tombstone in the index (still resolvable by the GET
                    # feed's point lookups); links retract batched below
                    self.index.index(record)
                self._retract_links_for(deleted)

            with self._mesh_op_lock():
                if deleted and not http_transform:
                    self.index.commit()
                    # seal retraction writes for delete-only batches (see
                    # _run_merged; no-op when a scoring pass follows)
                    self.link_database.commit()
                if live or http_transform:
                    self.processor.deduplicate(live)

            if http_transform:
                return self._transform_response(entities)
            self._mark_synced()
            return []
        except BaseException:
            if put_done:
                # store committed, index application failed: latch so no
                # later batch can stamp over the divergence (_mark_synced
                # honors the latch; a restart replay re-applies the rows)
                self._store_dirty = True
            raise
        finally:
            self.note_lock_hold(time.monotonic() - t_hold)
            self.index.set_indexing_disabled(False)
            self.listener.set_link_database_updates_disabled(False)

    def _transform_response(self, entities: Sequence[dict]) -> List[dict]:
        rows = []
        for entity in entities:
            row = dict(entity)
            entity_id = entity.get("_id")
            entity_id = str(entity_id) if entity_id is not None else None
            row["duke_links"] = self.listener.get_links_for_entity(entity_id)
            rows.append(row)
        return rows

    # -- incremental feed (call with self.lock held) ------------------------

    def _link_row(self, link) -> dict:
        """One feed row (wire format per App.java:744-770) — THE shared
        materialization (``links.replica.feed_row``): the follower read
        plane resolves through the same function, so leader and replica
        feeds cannot drift by construction (ISSUE 8)."""
        from ..links.replica import feed_row

        return feed_row(link, self.index.find_record_by_id)

    def links_since(self, since: int = 0) -> List[dict]:
        """Full materialized feed (the HTTP layer streams via links_page;
        this serves the HTTP/1.0 fallback and tests).  Internally paged so
        lazy record mirrors resolve endpoints through bounded batched
        prefetches instead of one point SELECT per link."""
        rows: List[dict] = []
        cursor = since
        while True:
            page, cursor = self.links_page(cursor, 5000)
            if not page:
                return rows
            rows.extend(page)

    def links_page(self, since: int, limit: int):
        """One bounded feed page: (rows, next_cursor).

        The HTTP layer streams a large ``?since=`` poll as a sequence of
        these pages, re-taking the workload lock per page so a
        multi-million-link backlog never holds the lock for the whole
        response (the reference holds its lock across the entire row loop,
        App.java:827-874).  ``next_cursor`` is the last row's timestamp
        (strictly-greater-than feed semantics); an empty ``rows`` means the
        feed is drained."""
        from ..links.replica import links_feed_page

        return links_feed_page(self.link_database, self.index, since, limit)

    def save_corpus_snapshot(self) -> None:
        """Persist the device-corpus snapshot (no-op for host backends).

        Best-effort: a failed save only logs; the record store remains the
        source of truth and the next start falls back to full replay."""
        if (self.record_store is None
                or not hasattr(self.index, "snapshot_save")):
            return
        try:
            # drain any write-behind link flush first: a snapshot must
            # never be newer than the link rows its batches produced
            self.link_database.drain()
            self.index.snapshot_save(_snapshot_path(self.config.data_folder))
        except Exception:
            logging.getLogger("workload").exception(
                "corpus snapshot save failed (replay will rebuild)"
            )

    def close(self, save_snapshot: bool = True) -> None:
        """Release index/link-db resources (the reference leaks these on hot
        reload — SURVEY.md quirk Q7; fixed by calling this on config swap).

        Device backends additionally persist a corpus snapshot so the next
        start can skip feature re-extraction; hot reload passes
        ``save_snapshot=False`` because it already saved under the quiesce
        locks (the corpus cannot have changed since)."""
        self.closed = True
        if save_snapshot:
            self.save_corpus_snapshot()
        finalizer = getattr(self.processor, "finalizer", None)
        if finalizer is not None:
            finalizer.shutdown()
        self.index.close()
        self.link_database.close()
        if self.record_store is not None:
            self.record_store.close()


def build_workload(wc: WorkloadConfig, sc: ServiceConfig, *,
                   backend: str = "host",
                   persistent: bool = True) -> Workload:
    """Assemble a workload: blocking index + processor + listener + link DB.

    ``backend``: 'host' (inverted index + scalar scoring — the conformance/
    baseline path), 'device' (TPU-resident corpus + batched kernels, exact
    brute-force blocking, see engine.device_matcher), 'ann' (embedding
    cosine blocking + exact rescoring, see engine.ann_matcher — for corpora
    where brute force stops being free), 'sharded' (the ANN backend over a
    jax.sharding.Mesh — record-axis-sharded corpus, all_gather top-K merge;
    the v5e-8 / multi-host serving configuration, engine.sharded_matcher),
    or 'sharded-brute' (exact brute force over the same mesh).
    """
    group_filtering = wc.is_record_linkage
    if backend != "host":
        # device-family backends compile multi-second XLA programs per
        # (capacity, bucket, K) shape; the persistent cache turns every
        # restart's first-contact compiles into disk reads.  Enabled here
        # so EVERY embedder gets it (the service CLI, benches, tests and
        # direct build_workload callers used to enable it individually —
        # the restart bench didn't, and its first probe silently paid
        # ~10-20 s of re-compiles per process)
        from ..utils.jit_cache import enable_persistent_cache

        enable_persistent_cache()
    if backend == "device":
        from .device_matcher import DeviceIndex, DeviceProcessor

        index = DeviceIndex(wc.duke, tunables=sc.tunables)
        processor = DeviceProcessor(
            wc.duke, index, group_filtering=group_filtering,
            profile=sc.profile, threads=sc.threads,
        )
    elif backend == "ann":
        from .ann_matcher import AnnIndex, AnnProcessor

        index = AnnIndex(wc.duke, tunables=sc.tunables)
        processor = AnnProcessor(
            wc.duke, index, group_filtering=group_filtering,
            profile=sc.profile, threads=sc.threads,
        )
    elif backend == "sharded":
        from .sharded_matcher import ShardedAnnIndex, ShardedAnnProcessor

        index = ShardedAnnIndex(wc.duke, tunables=sc.tunables)
        processor = ShardedAnnProcessor(
            wc.duke, index, group_filtering=group_filtering,
            profile=sc.profile, threads=sc.threads,
        )
    elif backend == "sharded-brute":
        from .sharded_matcher import (
            ShardedDeviceIndex,
            ShardedDeviceProcessor,
        )

        index = ShardedDeviceIndex(wc.duke, tunables=sc.tunables)
        processor = ShardedDeviceProcessor(
            wc.duke, index, group_filtering=group_filtering,
            profile=sc.profile, threads=sc.threads,
        )
    else:
        index = InvertedIndex(wc.duke, tunables=sc.tunables)
        processor = Processor(
            wc.duke,
            index,
            group_filtering=group_filtering,
            threads=sc.threads,
            profile=sc.profile,
        )

    link_database = None
    record_store: Optional[RecordStore] = None
    try:
        link_database = create_link_database(
            wc.link_database_type,
            wc.data_folder if persistent else None,
            is_record_linkage=wc.is_record_linkage,
        )
        # per-workload link-mode from the XML; ONE_TO_ONE env overrides
        # globally (None = defer to each workload's attribute)
        one_to_one = (wc.enforce_one_to_one if sc.one_to_one is None
                      else sc.one_to_one and wc.is_record_linkage)
        listener = ServiceMatchListener(
            wc.name, link_database, kind=wc.kind,
            one_to_one=one_to_one,
            record_resolver=index.find_record_by_id,
        )
        processor.add_match_listener(listener)

        if persistent and wc.data_folder:
            import os

            from ..store.records import SqliteRecordStore

            record_store = SqliteRecordStore(
                os.path.join(wc.data_folder, "records.sqlite")
            )
            # resume: rebuild the blocking index from the durable store (the
            # reference resumes by reopening its Lucene dir in APPEND mode —
            # IncrementalLuceneDatabase.java:233-244).  Device backends may
            # shortcut the per-record feature re-extraction through a
            # corpus snapshot — attempted FIRST with a lazy store-backed
            # record mirror, so a successful snapshot restart never decodes
            # the whole store (the 10M-row eager decode took ~24 minutes);
            # the store stays the source of truth and any snapshot mismatch
            # falls back to full replay.
            loaded = False
            snap = _snapshot_path(wc.data_folder)
            if hasattr(index, "snapshot_load") and os.path.exists(snap):
                from ..store.records import LazyRecordMap

                loaded = index.snapshot_load(
                    snap,
                    LazyRecordMap(record_store),
                    content_hash=record_store.content_hash(),
                )
            restored = loaded
            if not loaded:
                records_by_id = {
                    r.record_id: r for r in record_store.all_records()
                }
                if records_by_id:
                    restored = True
                    for record in records_by_id.values():
                        index.index(record)
                    index.commit()
                mark = getattr(index, "mark_store_synced", None)
                if mark is not None:
                    mark(record_store.content_hash())
            # the restored corpus' capacity/value-slot fingerprint differs
            # from the empty-corpus warm the processor ctor kicked; re-warm
            # so the first real batch doesn't stall on scorer compiles
            cache = getattr(index, "scorer_cache", None)
            if restored and cache is not None:
                cache.prewarm_async(group_filtering)
            if restored and not loaded:
                # replay path: stream the rebuilt corpus to HBM now (the
                # snapshot path kicks this inside snapshot_load) so the
                # first query doesn't pay the full upload
                warm = getattr(index, "warm_upload_async", None)
                if warm is not None:
                    warm()
    except BaseException:
        # a half-built workload never reaches the caller; release whatever
        # opened so a failing hot reload cannot leak handles (quirk Q7)
        for resource in (index, link_database, record_store):
            if resource is not None:
                try:
                    resource.close()
                except Exception:
                    pass
        raise
    return Workload(wc, index, processor, listener, link_database, record_store)


def adopt_workload(wc: WorkloadConfig, sc: ServiceConfig, *, backend: str,
                   index: CandidateIndex, link_database: LinkDatabase,
                   record_store: Optional[RecordStore] = None) -> Workload:
    """Build a SERVING workload around an already-populated index + link
    database — the leader-failover promotion path (ISSUE 8).

    A promoted follower's replica corpus (bootstrap snapshot + replayed
    commits) and replica link DB (published op stream at the applied
    watermark) are already bit-identical to the deposed leader's, so only
    the write-plane objects are new: a full processor (host finalization
    ON — the follower replica ran with it off) and a match listener whose
    events land in the replica link DB from now on.
    """
    group_filtering = wc.is_record_linkage
    if backend == "device":
        from .device_matcher import DeviceProcessor as _P
    elif backend == "ann":
        from .ann_matcher import AnnProcessor as _P
    elif backend == "sharded":
        from .sharded_matcher import ShardedAnnProcessor as _P
    elif backend == "sharded-brute":
        from .sharded_matcher import ShardedDeviceProcessor as _P
    else:
        raise RuntimeError(
            f"promotion needs a device-family backend (got {backend!r})"
        )
    processor = _P(wc.duke, index, group_filtering=group_filtering,
                   profile=sc.profile, threads=sc.threads)
    one_to_one = (wc.enforce_one_to_one if sc.one_to_one is None
                  else sc.one_to_one and wc.is_record_linkage)
    listener = ServiceMatchListener(
        wc.name, link_database, kind=wc.kind, one_to_one=one_to_one,
        record_resolver=index.find_record_by_id,
    )
    processor.add_match_listener(listener)
    return Workload(wc, index, processor, listener, link_database,
                    record_store)
