"""Mesh-sharded serving backends: the REST service over a device mesh.

Round 2 left the mesh machinery (``parallel/sharded.py``,
``parallel/ann_sharded.py``) as a library with no consumer in the service:
``build_workload`` could only construct single-device backends, so a v5e-8
deployment could not serve HTTP from a sharded corpus.  This module closes
that gap — the reference wires its matcher straight into the request
handlers (App.java:343-345,1005); here the same wiring scales to a
``jax.sharding.Mesh``:

  * ``ShardedDeviceCorpus`` keeps the exact append/tombstone/incremental-
    update model of ``DeviceCorpus`` (host numpy mirror as rebuildable
    truth) but places every device tensor record-axis sharded over the
    mesh, with capacity aligned to ``mesh.size * chunk`` granules so each
    shard holds whole scan chunks;
  * ``ShardedAnnIndex`` / ``ShardedDeviceIndex`` are the ANN and exact
    brute-force blocking backends over that corpus — snapshots, value-slot
    growth, delete/tombstone and the ``CandidateIndex`` interface are all
    inherited unchanged;
  * the scorer caches swap the single-device programs for the
    constraint-driven mesh ones (``parallel.sharded.PARTITION_RULES``):
    per-shard retrieval/scan with global row offsets, local exact
    rescoring, and a replicated-layout top-K merge the partitioner lowers
    to one all-gather over ICI — communication is O(Q * K * D) while
    compute scales 1/D (SURVEY.md section 5.7);
  * both mesh caches are first-class engine citizens (ISSUE 18): they
    ride the AOT executable store (mesh facets join the store key, the
    prewarm ladder lowers against mesh-annotated avals) and the certified
    dd finalize (survivors gather to replicated layout, then the same
    ``ops.scoring.build_dd_rescorer`` program runs bit-identical to the
    single-device path).

Queries are replicated (uploaded per block, never gathered cross-shard),
escalation loops (K for brute force, C for ANN recall) run unchanged
through ``_PendingBlock``/``resolve_block``, and host finalization is the
same double-precision path — so emitted probabilities are bit-identical to
the single-chip backends (differential-tested in
``tests/test_sharded_service.py`` on the virtual 8-device mesh).

Deployment: single-host this shards over every local device — the
flagship v5e-8 configuration (BASELINE configs[4]) runs one process
driving all 8 chips, full REST surface included.  Multi-host meshes
(``parallel.multihost.initialize()``) work end to end: the HTTP frontend
is a single-controller and follower processes replay every corpus
mutation and scoring pass in lockstep through ``parallel/dispatch.py``
(token-authenticated op broadcast over DCN; see that module for the
ordering/failure invariants).  Exercised by
``tests/test_multihost_serving.py`` — two OS processes, real HTTP, the
same link set as a single-process run — and by the driver dryrun's
two-process smoke.
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

from ..core.config import DukeSchema, MatchTunables
from ..ops import encoder as E
from .ann_matcher import AnnIndex, AnnProcessor, _AnnScorerCache
from .device_matcher import (
    DeviceCorpus,
    DeviceIndex,
    DeviceProcessor,
    _CHUNK,
    _ScorerCache,
)

logger = logging.getLogger("sharded-matcher")

_MESH_LOCK = threading.Lock()
_MESH = None


def serving_mesh():
    """The process-wide 1-D corpus mesh the sharded backends serve from.

    Joins the multi-host job first when one is configured (no-op
    otherwise), then builds the mesh over every global device — one mesh
    for all workloads, so hot config reloads don't re-initialize
    distributed state.
    """
    global _MESH
    with _MESH_LOCK:
        if _MESH is None:
            from ..parallel import multihost

            multihost.initialize()
            _MESH = multihost.global_corpus_mesh()
            from .. import telemetry

            telemetry.MESH_DEVICES.set(_MESH.size)  # dukecheck: ignore[DK502] once per process: mesh construction
            logger.info(
                "serving mesh: %d device(s), axis %r",
                _MESH.size, _MESH.axis_names,
            )
        return _MESH


class ShardedDeviceCorpus(DeviceCorpus):
    """``DeviceCorpus`` whose device mirror is record-axis sharded.

    Capacity grows in ``mesh.size * chunk`` granules (each shard always
    holds whole scan chunks — required by the mesh scorers' per-shard
    ``row_offset`` arithmetic); placement and the incremental tree updater
    carry explicit shardings so the arrays never silently collapse to a
    single device.
    """

    def __init__(self, plan, values_per_record: int, mesh):
        from ..parallel.sharded import LeadingAxisPlacer

        super().__init__(plan, values_per_record)
        self.mesh = mesh
        # ONE copy of the sharding/granule conventions: the same placer
        # machinery parallel/sharded.py and parallel/ring.py use
        self._placer = LeadingAxisPlacer(mesh, mesh.size * _CHUNK)
        self.granule = self._placer.granule
        self._updater_fn = None
        self._mask_updater_fn = None
        self._mask_scatter_fn = None

    def _sharding(self, ndim: int):
        return self._placer._sharding(ndim)

    def _place(self, arr):
        import jax

        return jax.device_put(arr, self._sharding(arr.ndim))

    def _updater(self):
        """Sharding-constrained incremental updater: the global-row update
        slice lands on whichever shard owns those rows, and the outputs are
        pinned back to the record sharding so a commit can never migrate
        the corpus off the mesh."""
        if self._updater_fn is None:
            import jax
            from jax import lax

            def update_tree(dev, upd, start):
                out = jax.tree_util.tree_map(
                    lambda d, u: lax.dynamic_update_slice_in_dim(
                        d, u, start, axis=0
                    ),
                    dev, upd,
                )
                return jax.tree_util.tree_map(
                    lambda a: lax.with_sharding_constraint(
                        a, self._sharding(a.ndim)
                    ),
                    out,
                )

            self._updater_fn = jax.jit(update_tree, donate_argnums=(0,))
        return self._updater_fn

    def _mask_updater(self):
        """Sharding-constrained mask-slice updater (see _updater)."""
        if self._mask_updater_fn is None:
            import jax
            from jax import lax

            def update_masks(masks, upd, start):
                out = tuple(
                    lax.dynamic_update_slice_in_dim(m, u, start, axis=0)
                    for m, u in zip(masks, upd)
                )
                return tuple(
                    lax.with_sharding_constraint(m, self._sharding(1))
                    for m in out
                )

            self._mask_updater_fn = jax.jit(
                update_masks, donate_argnums=(0,)
            )
        return self._mask_updater_fn

    def _mask_scatter(self):
        """Sharding-constrained tombstone scatter (see _updater)."""
        if self._mask_scatter_fn is None:
            import jax
            from jax import lax

            def scatter(masks, idx, vvals, dvals):
                valid, deleted, group = masks
                out = (valid.at[idx].set(vvals),
                       deleted.at[idx].set(dvals))
                out = tuple(
                    lax.with_sharding_constraint(m, self._sharding(1))
                    for m in out
                )
                return out + (group,)

            self._mask_scatter_fn = jax.jit(scatter, donate_argnums=(0,))
        return self._mask_scatter_fn


class _MeshProgramLift:
    """dd + AOT lifts shared by the mesh scorer caches (ISSUE 18).

    Mixed in ahead of the base caches, this makes the sharded backends
    first-class: queries upload replicated (never gathered cross-shard),
    the dd survivor rescore runs on device through a replicated-layout
    gather, and the AOT executable store serves mesh executables whose
    store keys carry the mesh facets and whose lowering avals carry the
    real shardings (``parallel.sharded.PARTITION_RULES``).
    """

    queries_from_rows = False
    supports_aot = True

    # single-writer mesh observability (plain ints — scrape-time
    # snapshots in service/metrics.py, never a registry write here)
    _dd_gathers = 0
    _dd_gather_rows = 0

    @property
    def supports_dd(self) -> bool:
        """dd finalize runs on the FRONTEND only (the follower replay of
        parallel/dispatch.py never enqueues it), so the survivor-gather
        collective in ``_dd_call`` is only safe when every mesh device is
        addressable from this process.  A multi-host mesh keeps the host
        dd path (README: dd/AOT parity matrix)."""
        return self._mesh_fully_addressable()

    def _mesh_fully_addressable(self) -> bool:
        cached = getattr(self, "_mesh_local", None)
        if cached is None:
            import jax

            pid = jax.process_index()
            cached = all(d.process_index == pid
                         for d in self.index.mesh.devices.flat)
            self._mesh_local = cached
        return cached

    def _dd_call(self, fn, qfeats, cfeats, query_row_j, top_index):
        """Certified dd finalize over the mesh: gather the resolved
        block's (Q, K) survivors from the record-axis-sharded corpus
        tensors into a compact replicated block, then run the SAME
        memoized single-device dd program against it with an identity
        index.  Clipping ``top_index`` before the gather reproduces the
        single-device "-1 padding gathers row 0" semantics exactly, so
        the verdicts are bit-identical (tests/test_mesh_parity.py)."""
        import jax.numpy as jnp

        from ..parallel.sharded import build_replicated_gather

        gather = getattr(self, "_dd_gather_fn", None)
        if gather is None:
            gather = build_replicated_gather(self.index.mesh)
            self._dd_gather_fn = gather
        q, k = top_index.shape
        rows = jnp.clip(top_index, 0).reshape(-1)
        gathered = gather(cfeats, rows)
        self._dd_gathers += 1
        self._dd_gather_rows += int(q * k)
        ident = jnp.arange(q * k, dtype=jnp.int32).reshape(q, k)
        return fn(qfeats, gathered, query_row_j, ident)

    def _sds(self, shape, dtype, family: str = "corpus"):
        """Mesh-annotated lowering avals: corpus-family tensors carry the
        record-axis sharding, query-family tensors the replicated spec —
        so an AOT executable compiles against (and at load time only
        accepts) the layouts dispatch actually passes."""
        import jax

        from ..parallel.sharded import rule_sharding

        fam = "corpus" if family == "corpus" else "queries"
        return jax.ShapeDtypeStruct(
            shape, dtype,
            sharding=rule_sharding(self.index.mesh, fam, len(shape)),
        )

    def _ladder(self, cap: int):
        # mesh queries never gather from corpus rows (queries_from_rows
        # is False), so only the replicated-upload variant is ever
        # dispatched — half the single-device ladder
        return [e for e in super()._ladder(cap) if not e[2]]

    def _min_warm_cap(self) -> int:
        # the smallest real corpus capacity is one mesh granule (every
        # shard holds whole scan chunks); lowering below it would bake
        # shapes dispatch can never present
        return self.index.corpus.granule

    def _store_key(self, plan, k: int, group_filtering: bool,
                   from_rows: bool, cap: int, bucket: int) -> dict:
        from ..utils.jit_cache import mesh_fingerprint

        key = super()._store_key(plan, k, group_filtering, from_rows,
                                 cap, bucket)
        # a mesh executable is only valid on the topology it was
        # partitioned for: a 4-way entry must be unreachable from an
        # 8-way mesh even on the same host (tests/test_mesh_aot.py)
        key["mesh"] = mesh_fingerprint(self.index.mesh)
        return key


class _ShardedScorerCache(_MeshProgramLift, _ScorerCache):
    """Brute-force scorer cache over the mesh (parallel.sharded program)."""

    def _build(self, top_k: int, group_filtering: bool, from_rows: bool,
               plan=None):
        from ..parallel.sharded import build_sharded_scorer

        # signature matches the single-device from_rows=False scorer:
        # fn(qfeats, cfeats, valid, deleted, group, qgroup, qrow, min_logit)
        return build_sharded_scorer(
            plan or self.index.plan, self.index.mesh, chunk=_CHUNK, top_k=top_k,
            group_filtering=group_filtering,
        )


class _ShardedAnnScorerCache(_MeshProgramLift, _AnnScorerCache):
    """ANN scorer cache over the mesh (parallel.ann_sharded program)."""

    def _build(self, top_c: int, group_filtering: bool, from_rows: bool,
               plan=None):
        import jax

        from ..parallel.ann_sharded import build_sharded_ann_scorer

        base = build_sharded_ann_scorer(
            plan or self.index.plan, self.index.mesh, chunk=_CHUNK, top_c=top_c,
            group_filtering=group_filtering,
        )

        # a JITTED adapter to the single-device ANN call convention (the
        # embedding tree — and the int8 scale when present — rides
        # separately and is reassembled as the ANN_PROP pseudo-property
        # inside the trace): AOT lowering needs a traceable callable
        # with the engine's flat signature, not a host-side wrapper
        @jax.jit
        def call(q_emb, qfeats, corpus_emb, corpus_feats, cvalid, cdeleted,
                 cgroup, query_group, query_row, min_logit):
            cfeats = dict(corpus_feats)
            cfeats[E.ANN_PROP] = E.as_emb_tree(corpus_emb)
            return base(q_emb, qfeats, cfeats, cvalid, cdeleted, cgroup,
                        query_group, query_row, min_logit)

        return call

    def _build_ivf(self, top_c: int, nprobe: int, group_filtering: bool,
                   from_rows: bool):
        from ..parallel.ann_sharded import build_sharded_ivf_scorer

        base = build_sharded_ivf_scorer(
            self.index.plan, self.index.mesh, top_c=top_c, nprobe=nprobe,
            group_filtering=group_filtering,
        )

        def call(q_emb, qfeats, emb_tree, centroids, cell_rows,
                 corpus_feats, cvalid, cdeleted, cgroup, query_group,
                 query_row, min_logit):
            cfeats = dict(corpus_feats)
            cfeats[E.ANN_PROP] = E.as_emb_tree(emb_tree)
            return base(q_emb, qfeats, cfeats, centroids, cell_rows,
                        cvalid, cdeleted, cgroup, query_group, query_row,
                        min_logit)

        return call

    def _ivf_placers(self):
        """SNIPPETS.md pjit partition-rule pattern, through the shared
        rule table: replicate the small lookup table (centroids), shard
        the big per-row state (the stacked local-row membership matrix)
        on the record axis."""
        import jax

        from ..parallel.sharded import rule_sharding

        mesh = self.index.mesh
        repl = rule_sharding(mesh, "centroids", 2)
        sharded = rule_sharding(mesh, "ivf_membership", 2)
        return (
            lambda arr: jax.device_put(arr, repl),
            lambda arr: jax.device_put(arr, sharded),
        )


class ShardedDeviceIndex(DeviceIndex):
    """Exact brute-force blocking over a record-axis-sharded corpus."""

    def __init__(self, schema: DukeSchema, *,
                 tunables: Optional[MatchTunables] = None,
                 values_per_record: Optional[int] = None,
                 mesh=None):
        # the corpus factory runs inside super().__init__
        self.mesh = mesh if mesh is not None else serving_mesh()
        super().__init__(
            schema, tunables=tunables, values_per_record=values_per_record
        )

    def _make_corpus(self, plan, values_per_record: int):
        return ShardedDeviceCorpus(plan, values_per_record, self.mesh)

    @property
    def scorer_cache(self) -> _ShardedScorerCache:
        if self._scorer_cache is None:
            self._scorer_cache = _ShardedScorerCache(self)
        return self._scorer_cache


class ShardedAnnIndex(AnnIndex):
    """Embedding-ANN blocking over a record-axis-sharded corpus.

    The flagship scale configuration (BASELINE.json configs[4]): corpus
    embeddings and feature tensors shard over the mesh, per-shard cosine
    top-C + local exact rescoring, all_gather merge.  Everything else —
    encoder, snapshots, recall escalation semantics — is ``AnnIndex``.
    """

    def __init__(self, schema: DukeSchema, *,
                 tunables: Optional[MatchTunables] = None,
                 values_per_record: Optional[int] = None,
                 mesh=None, **kwargs):
        self.mesh = mesh if mesh is not None else serving_mesh()
        super().__init__(
            schema, tunables=tunables, values_per_record=values_per_record,
            **kwargs,
        )

    def _make_corpus(self, plan, values_per_record: int):
        return ShardedDeviceCorpus(plan, values_per_record, self.mesh)

    def _ivf_shards(self) -> int:
        # the IVF membership matrix stacks per-shard (K, B) blocks of
        # LOCAL row ids so P(SHARD_AXIS) placement hands each mesh
        # program lane exactly its own block (parallel.ann_sharded)
        return self.mesh.size

    @property
    def scorer_cache(self) -> _ShardedAnnScorerCache:
        if self._scorer_cache is None:
            self._scorer_cache = _ShardedAnnScorerCache(self)
        return self._scorer_cache


class ShardedDeviceProcessor(DeviceProcessor):
    """DeviceProcessor over a ShardedDeviceIndex (exhaustive stats)."""

    exhaustive = True


class ShardedAnnProcessor(AnnProcessor):
    """AnnProcessor over a ShardedAnnIndex (rescored-candidate stats)."""

    exhaustive = False
