"""Parallel host finalization of device-scored survivor pairs.

The device scorer ranks ~tens of millions of exact pairs per second, but
every surviving top-K pair used to funnel through a single-threaded Python
loop (per-survivor ``Processor.compare`` in ``DeviceProcessor
._score_blocks``) — so end-to-end ingest throughput was bounded by host
finalization, not the TPU (the post-device Amdahl bottleneck).  This module
makes that loop parallel, bounded, and mostly skippable:

  * **Parallel**: per-query survivor finalization fans out over a worker
    pool sized by ``DUKE_FINALIZE_THREADS`` (falling back to the
    processor's ``threads`` knob).  Workers only *compute* — the exact f64
    ``compare`` per survivor and the would-be events; results are gathered
    and listener events are emitted by the coordinating thread in strict
    query order, so the match/maybe/no-match stream and the link rows are
    bit-identical to the serial path at any thread count.

  * **Skippable** (decisive-band pruning, ``DUKE_DECISIVE_BAND``): the
    device logit is optimistic — host-only properties contribute their
    maximum and float32 error is credited via the certified margin
    (``ops.scoring.certified_f32_margin``).  A survivor whose upper-bound
    probability still cannot clear ``min(threshold, maybe_threshold)``
    certifiably emits no event, so its host ``compare`` is skipped.  The
    device-side survivor filter keeps a coarser (1e-3) insurance margin,
    so the skipped band is exactly the over-conservative tail the filter
    retains; emitted pairs always get the exact f64 rescore, preserving
    the bit-identical-probability contract.  The skipped/rescored split
    rides ``ProfileStats`` (``duke_finalize_pairs_total`` on /metrics).

  * **Device-certified** (ISSUE 12, ``DUKE_DEVICE_FINALIZE``, default
    on): survivors above the decisive band used to round-trip to host
    Python regardless of how far from a threshold they sat, because the
    f32 margin is too coarse to decide near-band pairs.  The dd rescore
    (``ops.scoring.build_dd_rescorer`` over ``ops.dd``) re-scores the
    surviving pairs on device in two-float emulated-f64 and certifies a
    three-way verdict split per pair:

      - **certified reject** — the dd logit (plus the EXACTLY-computed
        host-side logits of any non-certifiable property, see below)
        sits provably below every decision boundary by more than
        ``certified_dd_margin``: no event is possible, no host
        ``compare`` runs, no candidate ``Record`` is even resolved for
        all-certifiable schemas.  This is where the win lives for
        schemas with host-only properties (the survivor filter widens
        by the optimistic host bound, so most survivors are non-events)
        and for sharp/degenerate ``[low, high]`` ranges whose f32
        certified margin collapsed the decisive band.  For mild
        all-device schemas the 1e-3 survivor filter already sits at the
        emit bound — survivors are essentially emitters — and the
        block-level gate (``ops.scoring.dd_gate_bound``) skips the dd
        program outright rather than paying it for nothing.
      - **certified event** — provably above the lowest boundary: the
        event class is certain, but the emitted confidence must be the
        bit-exact f64 value, so the pair takes one host ``compare`` —
        O(emitted links) host work, not O(survivors).  That compare is
        served through a comparison-signature confidence memo
        (``compare`` is a pure function of the comparison properties'
        value lists, so a cached result is the bit-identical f64 by
        construction — and the tuple keys compare by full string
        equality, no hashing caveat): dedup traffic is dominated by
        repeated identity groups, where every copy pair shares one
        signature pair and the whole group costs ONE compare instead
        of O(group^2).
      - **ambiguous residue** — within the (tiny, ~1e-10) dd band of a
        boundary, or carrying tensors that may have truncated the
        record (``unsafe``): exactly today's host path.

    Properties whose kind is not dd-certifiable (weighted-lev, numeric,
    geo — and host-only comparators) fall back to the host PER PROPERTY
    and PER PAIR: their exact f64 logits are computed with the same
    ``Property.compare_probability`` + ``probability_logit`` fold the
    oracle uses and added to the dd device logit, so one numeric
    property costs per-survivor host arithmetic for that property only
    — it does not collapse the whole schema to the legacy path.  The
    fallback is logged once per workload (not per batch).

    Events still emit from the coordinating thread in strict query
    order through the same path — the dd rescore introduces no new
    lock and no new emission site, so event streams and link rows stay
    bit-identical to ``DUKE_DEVICE_FINALIZE=0`` by construction (the
    only behavioral delta is *skipping* compares that provably emit
    nothing).  ``duke_finalize_pairs_total{outcome=device_certified}``
    and ``duke_dd_residue_total{reason}`` ride ``ProfileStats``.
"""

from __future__ import annotations

import logging
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import List, Optional, Sequence, Tuple

from ..core.bayes import probability_logit
from ..core.records import Record
from ..store.records import record_digest
from ..telemetry.decisions import PairDecision
from ..telemetry.env import env_flag, env_str
from ..utils import numcheck


def fallback_pair_logit(props, r1: Record, r2: Record) -> float:
    """Exact f64 logit contribution of the host-fallback properties.

    The same per-property fold ``Processor.compare`` performs — max over
    value pairs of ``Property.compare_probability``, clamped
    ``probability_logit``, properties missing on either side contribute
    nothing — restricted to ``props`` (core Property objects in schema
    order, from ``ops.scoring.dd_fallback_props``).  Adding this to the
    device dd logit reproduces the oracle's total up to f64 summation
    order, which ``certified_dd_margin`` charges."""
    total = 0.0
    for prop in props:
        vs1 = [v for v in r1.get_values(prop.name) if v]
        vs2 = [v for v in r2.get_values(prop.name) if v]
        if not vs1 or not vs2:
            continue
        best = 0.0
        for v1 in vs1:
            for v2 in vs2:
                p = prop.compare_probability(v1, v2)
                if p > best:
                    best = p
        total += probability_logit(best)
    return total


class QueryOutcome:
    """One query's finalization result, computed off the listener thread.

    ``events`` holds ``(event_name, candidate, probability)`` in survivor
    (descending device logit) order — exactly what the serial loop would
    have emitted; an empty list means ``no_match_for``.

    ``decisions`` carries the per-pair decision inputs
    (telemetry.decisions.PairDecision, survivor order) for the decision
    recorder — empty when recording is disabled; ``prune``/``margin``/
    ``host_bound`` are the block's decisive bound, certified f32 margin
    and optimistic host-property logit (None/0 without a decisive band).
    """

    __slots__ = ("events", "survivors", "rescored", "skipped",
                 "decisions", "prune", "margin", "host_bound",
                 "device_certified", "residue_margin", "residue_kind",
                 "residue_truncation")

    def __init__(self, events: List[Tuple[str, Record, float]],
                 survivors: int, rescored: int, skipped: int,
                 decisions: Optional[list] = None,
                 prune: Optional[float] = None,
                 margin: Optional[float] = None,
                 host_bound: float = 0.0,
                 device_certified: int = 0,
                 residue_margin: int = 0,
                 residue_kind: int = 0,
                 residue_truncation: int = 0):
        self.events = events
        self.survivors = survivors
        self.rescored = rescored
        self.skipped = skipped
        self.decisions = decisions if decisions is not None else []
        self.prune = prune
        self.margin = margin
        self.host_bound = host_bound
        self.device_certified = device_certified
        self.residue_margin = residue_margin
        self.residue_kind = residue_kind
        self.residue_truncation = residue_truncation


def _resolve_threads(threads: int, use_env: bool) -> int:
    if use_env:
        env = env_str("DUKE_FINALIZE_THREADS")
        if env:
            try:
                return max(1, int(env))
            except ValueError:
                # a typo'd manifest must not keep the service from
                # starting (the convention every env knob here follows)
                logging.getLogger("finalize").warning(
                    "ignoring non-integer DUKE_FINALIZE_THREADS=%r", env
                )
    return max(1, threads)


# Confidence-memo capacity: keys are two 20-byte content digests + a
# float (~100 B/entry, ~6 MB full).  Reset wholesale when full — dedup
# traffic is dominated by a small working set of identity-pair digests.
_CONF_CACHE_MAX = 1 << 16


class FinalizeExecutor:
    """Block-scoped survivor-finalization executor for device processors.

    One instance per processor; the pool is created lazily on the first
    multi-threaded block and reused across batches (the host ``Processor``
    precedent of a pool per batch would pay thread spawn per microbatch).
    ``use_env=False`` pins the constructor arguments against the env knobs
    (benchmark baselines).
    """

    def __init__(self, threads: int = 1, *, decisive: Optional[bool] = None,
                 device: Optional[bool] = None, use_env: bool = True):
        self.threads = _resolve_threads(threads, use_env)
        if decisive is None:
            decisive = not use_env or env_flag("DUKE_DECISIVE_BAND", True)
        self.decisive = decisive
        # device-resident certified finalization (ISSUE 12): default on;
        # =0 pins the legacy host path exactly.  use_env=False without an
        # explicit ``device`` pins the legacy path too (bench baselines).
        if device is None:
            device = use_env and env_flag("DUKE_DEVICE_FINALIZE", True)
        self.device = device
        # once-per-workload notice when property kinds force host residue
        self._kind_fallback_logged = False  # single-writer: block coordinator (finalize_block runs under the workload lock)
        # confidence memo (device-finalize path only, so =0 pins the
        # legacy path exactly): (sig1, sig2) -> Processor.compare f64
        # result, where a record's ``sig`` is the tuple of its
        # comparison-property value lists — compare is a pure function
        # of exactly those values, so a hit returns the bit-identical
        # confidence, and key equality is EXACT (tuples of strings, no
        # hash-collision caveat).  ``_sig_cache`` memoizes content
        # digest -> sig so a candidate's signature is built once per
        # distinct record content, not once per pair.  NO lock by design
        # (ISSUE 12): individual dict get/set are atomic under the GIL,
        # and the over-capacity reset rebinds a fresh dict atomically —
        # a racing worker at worst misses a cached entry and recomputes.
        self._conf_cache: dict = {}  # single-writer: none — deliberately lock-free (GIL-atomic get/set, atomic reset rebind; see block comment)
        self._sig_cache: dict = {}  # single-writer: none — deliberately lock-free (same contract as _conf_cache)
        self._pool: Optional[ThreadPoolExecutor] = None  # guarded by: self._pool_lock
        self._pool_lock = threading.Lock()

    def _get_pool(self) -> ThreadPoolExecutor:
        with self._pool_lock:
            if self._pool is None:
                self._pool = ThreadPoolExecutor(
                    max_workers=self.threads,
                    thread_name_prefix="finalize",
                )
            return self._pool

    def shutdown(self) -> None:
        with self._pool_lock:
            pool, self._pool = self._pool, None
        if pool is not None:
            pool.shutdown(wait=False)

    def finalize_block(self, proc, block: Sequence[Record],
                       result) -> List[QueryOutcome]:
        """Compute every query's outcome for one scored block.

        ``proc`` is the owning DeviceProcessor (supplies ``compare``, the
        record mirror, and thresholds); ``result`` is the resolved
        ``_BlockResult``.  Returns outcomes in query order; the caller
        emits the listener events serially from them.
        """
        from ..ops import scoring as S

        database = proc.database
        corpus = database.corpus
        records_map = database.records
        threshold = proc.schema.threshold
        maybe = proc.schema.maybe_threshold
        # recomputed per block: the plan's host/device split can change
        # between batches (long-text demotion) and the bound must track it
        prune = (S.decisive_prune_logit(proc.schema, database.plan)
                 if self.decisive else None)
        # decision-recorder inputs (telemetry.decisions): the certified
        # margin classifies near-threshold band skips, the host bound
        # turns a device logit into the f32 filter verdict.  Collected
        # only when the processor carries an enabled recorder — the
        # per-pair PairDecision alloc stays off the disabled path.
        recorder = getattr(proc, "decisions", None)
        record_decisions = recorder is not None and recorder.enabled
        margin = (S.certified_f32_margin(database.plan)
                  if record_decisions and prune is not None else None)
        host_bound = (S.host_bound_logit(database.plan.host_props)
                      if record_decisions else 0.0)
        # device-certified finalization (ISSUE 12): the caller attaches
        # the block's dd rescore output (hi, lo, unsafe numpy arrays) to
        # the result; None means the block could not ride the device
        # (multi-host mesh, http-transform probes, dd rescore disabled —
        # fully-addressable sharded corpora DO ride it since ISSUE 18's
        # replicated survivor gather)
        dd = getattr(result, "dd", None) if self.device else None
        plan = database.plan
        plan_has_dd = self.device and bool(S.dd_plan_specs(plan))
        dd_reject = dd_event = None
        fallback: List = []
        nc_margin = None  # DUKE_NUMCHECK=1: shadow-oracle margin budget
        if dd is not None and plan_has_dd:
            dd_reject = S.dd_reject_bound(proc.schema, plan)
            dd_event = S.dd_event_bound(proc.schema, plan)
            fallback = S.dd_fallback_props(proc.schema, plan)
            if numcheck.enabled():
                # the bound the certified verdicts charged: dd margin
                # plus the probability-space comparison slack
                t_min = threshold
                if maybe is not None and maybe != 0.0:
                    t_min = min(t_min, maybe)
                nc_margin = (S.certified_dd_margin(plan)
                             + S._dd_threshold_slack(t_min))
        if self.device and not self._kind_fallback_logged:
            # once per workload, not per batch: which properties force
            # the per-pair host-residue path (uncertifiable kinds +
            # host-only comparators), or that the whole schema does
            kinds_forced = (S.dd_fallback_props(proc.schema, plan)
                            if plan_has_dd else
                            list(proc.schema.comparison_properties()))
            self._kind_fallback_logged = True
            if kinds_forced:
                logging.getLogger("finalize").info(
                    "device finalize: %s fall back to per-pair host "
                    "scoring (no certified dd kernel for their "
                    "comparator kinds)%s",
                    sorted(p.name for p in kinds_forced),
                    "" if plan_has_dd else
                    " — no property is dd-certifiable, every survivor "
                    "takes the host path",
                )
        resolver = records_map.get
        if not isinstance(records_map, dict):
            # lazy store-backed mirrors (LazyRecordMap) mutate an LRU on
            # every get — serialize just the resolution, not the compare
            rl = threading.Lock()
            inner = resolver

            def resolver(rid):  # noqa: F811 - deliberate shadowing
                with rl:
                    return inner(rid)

        compare = proc.compare
        row_ids = corpus.row_ids
        comparison_props = list(proc.schema.comparison_properties())

        def sig(rec: Record):
            """Comparison signature: the value tuple ``compare`` is a
            pure function of, memoized per distinct record content."""
            d = record_digest(rec)
            s = self._sig_cache.get(d)
            if s is None:
                s = tuple(tuple(rec.get_values(p.name))
                          for p in comparison_props)
                sc = self._sig_cache
                if len(sc) >= _CONF_CACHE_MAX:
                    sc = self._sig_cache = {}
                sc[d] = s
            return s

        def one(qi: int, record: Record) -> QueryOutcome:
            events: List[Tuple[str, Record, float]] = []
            survivors = result.survivor_triples(qi)
            rescored = skipped = certified = 0
            res_margin = res_kind = res_trunc = 0
            decisions: List[PairDecision] = []
            rec_id = record.record_id
            query_sig = None  # built lazily, once per query

            def memo_compare(cand: Record) -> float:
                """The comparison-signature confidence memo (see the
                constructor comment): a duplicate group's every copy
                pair shares one (sig, sig) key, so the group costs ONE
                compare.  Ordered key — PersonName-style greedy token
                matching is not provably symmetric.  Shared by the
                certified-event confidence fetch AND the numcheck
                shadow oracle, so the sanitizer leg's certified-reject
                checks stay O(distinct content pairs), not O(group^2)."""
                nonlocal query_sig
                if query_sig is None:
                    query_sig = sig(record)
                ckey = (query_sig, sig(cand))
                cache = self._conf_cache
                p = cache.get(ckey)
                if p is None:
                    p = compare(record, cand)
                    if len(cache) >= _CONF_CACHE_MAX:
                        cache = self._conf_cache = {}
                    cache[ckey] = p
                return p
            for pos, row, device_logit in survivors:
                rid = row_ids[row]
                if rid is None or rid == rec_id:
                    continue
                if prune is not None and device_logit <= prune:
                    # upper-bound probability certifiably below the
                    # minimum emit threshold: no event possible
                    skipped += 1
                    if record_decisions:
                        decisions.append(
                            PairDecision(rid, device_logit, True, None))
                    continue
                candidate = None
                reason = None  # why this pair takes the host compare
                dd_total = None  # certified total (numcheck shadow leg)
                certified_event = False
                if dd_reject is not None:
                    if dd[2][qi, pos]:
                        # tensors may have truncated the record: the dd
                        # counts are not certifiably the full-value
                        # counts — host residue
                        reason = "truncation"
                    else:
                        # f32 pair sums exactly in f64
                        total = float(dd[0][qi, pos]) + float(dd[1][qi, pos])
                        if fallback:
                            # per-property host fallback: exact f64
                            # logits of the non-certifiable properties
                            candidate = resolver(rid)
                            if candidate is None:
                                continue
                            total += fallback_pair_logit(
                                fallback, record, candidate)
                        if total <= dd_reject:
                            # certified reject: the host f64 probability
                            # provably cannot clear any threshold — no
                            # compare, no event
                            certified += 1
                            if record_decisions:
                                decisions.append(PairDecision(
                                    rid, device_logit, True, None,
                                    path="device_certified"))
                            if nc_margin is not None \
                                    and numcheck.take_sample():
                                # DUKE_NUMCHECK shadow oracle: the ONE
                                # verdict class that skips the host
                                # compare pays one back, sampled (and
                                # memoized — k identical copy pairs
                                # cost one compare, not k)
                                shadow = (candidate if candidate
                                          is not None else resolver(rid))
                                if shadow is not None:
                                    numcheck.observe(
                                        "reject", rec_id, rid, total,
                                        memo_compare(shadow),
                                        threshold, maybe, nc_margin)
                            continue
                        if total < dd_event:
                            # inside the (tiny) ambiguous band around a
                            # boundary: only the exact host compare can
                            # decide
                            reason = "margin"
                        else:
                            # certified event — the class is certain,
                            # but the emitted confidence must be the
                            # exact f64 value, so the pair still takes
                            # ONE compare (O(links) host work, not
                            # residue)
                            dd_total = total
                            certified_event = True
                elif self.device and not plan_has_dd:
                    reason = "kind"
                if candidate is None:
                    candidate = resolver(rid)
                    if candidate is None:
                        continue
                if self.device:
                    prob = memo_compare(candidate)
                else:
                    prob = compare(record, candidate)
                if certified_event and nc_margin is not None:
                    # free shadow check: the compare already ran for
                    # the bit-exact confidence — the oracle must agree
                    # an event emits, and the margin bound must hold
                    numcheck.observe("event", rec_id, rid, dd_total,
                                     prob, threshold, maybe, nc_margin)
                rescored += 1
                if reason == "margin":
                    res_margin += 1
                elif reason == "kind":
                    res_kind += 1
                elif reason == "truncation":
                    res_trunc += 1
                if record_decisions:
                    decisions.append(
                        PairDecision(rid, device_logit, False, prob))
                if prob > threshold:
                    events.append(("matches", candidate, prob))
                elif maybe is not None and maybe != 0.0 and prob > maybe:
                    events.append(("matches_perhaps", candidate, prob))
            return QueryOutcome(events, len(survivors), rescored, skipped,
                                decisions, prune, margin, host_bound,
                                certified, res_margin, res_kind, res_trunc)

        if self.threads <= 1 or len(block) <= 1:
            return [one(qi, r) for qi, r in enumerate(block)]
        pool = self._get_pool()
        # map() preserves submission order, so outcomes line up with the
        # block and emission stays in strict query order
        return list(pool.map(one, range(len(block)), block))
