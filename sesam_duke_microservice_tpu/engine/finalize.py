"""Parallel host finalization of device-scored survivor pairs.

The device scorer ranks ~tens of millions of exact pairs per second, but
every surviving top-K pair used to funnel through a single-threaded Python
loop (per-survivor ``Processor.compare`` in ``DeviceProcessor
._score_blocks``) — so end-to-end ingest throughput was bounded by host
finalization, not the TPU (the post-device Amdahl bottleneck).  This module
makes that loop parallel, bounded, and mostly skippable:

  * **Parallel**: per-query survivor finalization fans out over a worker
    pool sized by ``DUKE_FINALIZE_THREADS`` (falling back to the
    processor's ``threads`` knob).  Workers only *compute* — the exact f64
    ``compare`` per survivor and the would-be events; results are gathered
    and listener events are emitted by the coordinating thread in strict
    query order, so the match/maybe/no-match stream and the link rows are
    bit-identical to the serial path at any thread count.

  * **Skippable** (decisive-band pruning, ``DUKE_DECISIVE_BAND``): the
    device logit is optimistic — host-only properties contribute their
    maximum and float32 error is credited via the certified margin
    (``ops.scoring.certified_f32_margin``).  A survivor whose upper-bound
    probability still cannot clear ``min(threshold, maybe_threshold)``
    certifiably emits no event, so its host ``compare`` is skipped.  The
    device-side survivor filter keeps a coarser (1e-3) insurance margin,
    so the skipped band is exactly the over-conservative tail the filter
    retains; emitted pairs always get the exact f64 rescore, preserving
    the bit-identical-probability contract.  The skipped/rescored split
    rides ``ProfileStats`` (``duke_finalize_pairs_total`` on /metrics).
"""

from __future__ import annotations

import logging
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import List, Optional, Sequence, Tuple

from ..core.records import Record
from ..telemetry.decisions import PairDecision
from ..telemetry.env import env_flag, env_str


class QueryOutcome:
    """One query's finalization result, computed off the listener thread.

    ``events`` holds ``(event_name, candidate, probability)`` in survivor
    (descending device logit) order — exactly what the serial loop would
    have emitted; an empty list means ``no_match_for``.

    ``decisions`` carries the per-pair decision inputs
    (telemetry.decisions.PairDecision, survivor order) for the decision
    recorder — empty when recording is disabled; ``prune``/``margin``/
    ``host_bound`` are the block's decisive bound, certified f32 margin
    and optimistic host-property logit (None/0 without a decisive band).
    """

    __slots__ = ("events", "survivors", "rescored", "skipped",
                 "decisions", "prune", "margin", "host_bound")

    def __init__(self, events: List[Tuple[str, Record, float]],
                 survivors: int, rescored: int, skipped: int,
                 decisions: Optional[list] = None,
                 prune: Optional[float] = None,
                 margin: Optional[float] = None,
                 host_bound: float = 0.0):
        self.events = events
        self.survivors = survivors
        self.rescored = rescored
        self.skipped = skipped
        self.decisions = decisions if decisions is not None else []
        self.prune = prune
        self.margin = margin
        self.host_bound = host_bound


def _resolve_threads(threads: int, use_env: bool) -> int:
    if use_env:
        env = env_str("DUKE_FINALIZE_THREADS")
        if env:
            try:
                return max(1, int(env))
            except ValueError:
                # a typo'd manifest must not keep the service from
                # starting (the convention every env knob here follows)
                logging.getLogger("finalize").warning(
                    "ignoring non-integer DUKE_FINALIZE_THREADS=%r", env
                )
    return max(1, threads)


class FinalizeExecutor:
    """Block-scoped survivor-finalization executor for device processors.

    One instance per processor; the pool is created lazily on the first
    multi-threaded block and reused across batches (the host ``Processor``
    precedent of a pool per batch would pay thread spawn per microbatch).
    ``use_env=False`` pins the constructor arguments against the env knobs
    (benchmark baselines).
    """

    def __init__(self, threads: int = 1, *, decisive: Optional[bool] = None,
                 use_env: bool = True):
        self.threads = _resolve_threads(threads, use_env)
        if decisive is None:
            decisive = not use_env or env_flag("DUKE_DECISIVE_BAND", True)
        self.decisive = decisive
        self._pool: Optional[ThreadPoolExecutor] = None  # guarded by: self._pool_lock
        self._pool_lock = threading.Lock()

    def _get_pool(self) -> ThreadPoolExecutor:
        with self._pool_lock:
            if self._pool is None:
                self._pool = ThreadPoolExecutor(
                    max_workers=self.threads,
                    thread_name_prefix="finalize",
                )
            return self._pool

    def shutdown(self) -> None:
        with self._pool_lock:
            pool, self._pool = self._pool, None
        if pool is not None:
            pool.shutdown(wait=False)

    def finalize_block(self, proc, block: Sequence[Record],
                       result) -> List[QueryOutcome]:
        """Compute every query's outcome for one scored block.

        ``proc`` is the owning DeviceProcessor (supplies ``compare``, the
        record mirror, and thresholds); ``result`` is the resolved
        ``_BlockResult``.  Returns outcomes in query order; the caller
        emits the listener events serially from them.
        """
        from ..ops import scoring as S

        database = proc.database
        corpus = database.corpus
        records_map = database.records
        threshold = proc.schema.threshold
        maybe = proc.schema.maybe_threshold
        # recomputed per block: the plan's host/device split can change
        # between batches (long-text demotion) and the bound must track it
        prune = (S.decisive_prune_logit(proc.schema, database.plan)
                 if self.decisive else None)
        # decision-recorder inputs (telemetry.decisions): the certified
        # margin classifies near-threshold band skips, the host bound
        # turns a device logit into the f32 filter verdict.  Collected
        # only when the processor carries an enabled recorder — the
        # per-pair PairDecision alloc stays off the disabled path.
        recorder = getattr(proc, "decisions", None)
        record_decisions = recorder is not None and recorder.enabled
        margin = (S.certified_f32_margin(database.plan)
                  if record_decisions and prune is not None else None)
        host_bound = (S.host_bound_logit(database.plan.host_props)
                      if record_decisions else 0.0)
        resolver = records_map.get
        if not isinstance(records_map, dict):
            # lazy store-backed mirrors (LazyRecordMap) mutate an LRU on
            # every get — serialize just the resolution, not the compare
            rl = threading.Lock()
            inner = resolver

            def resolver(rid):  # noqa: F811 - deliberate shadowing
                with rl:
                    return inner(rid)

        compare = proc.compare
        row_ids = corpus.row_ids

        def one(qi: int, record: Record) -> QueryOutcome:
            events: List[Tuple[str, Record, float]] = []
            survivors = result.survivors(qi)
            rescored = skipped = 0
            decisions: List[PairDecision] = []
            rec_id = record.record_id
            for row, device_logit in survivors:
                rid = row_ids[row]
                if rid is None or rid == rec_id:
                    continue
                if prune is not None and device_logit <= prune:
                    # upper-bound probability certifiably below the
                    # minimum emit threshold: no event possible
                    skipped += 1
                    if record_decisions:
                        decisions.append(
                            PairDecision(rid, device_logit, True, None))
                    continue
                candidate = resolver(rid)
                if candidate is None:
                    continue
                prob = compare(record, candidate)
                rescored += 1
                if record_decisions:
                    decisions.append(
                        PairDecision(rid, device_logit, False, prob))
                if prob > threshold:
                    events.append(("matches", candidate, prob))
                elif maybe is not None and maybe != 0.0 and prob > maybe:
                    events.append(("matches_perhaps", candidate, prob))
            return QueryOutcome(events, len(survivors), rescored, skipped,
                                decisions, prune, margin, host_bound)

        if self.threads <= 1 or len(block) <= 1:
            return [one(qi, r) for qi, r in enumerate(block)]
        pool = self._get_pool()
        # map() preserves submission order, so outcomes line up with the
        # block and emission stays in strict query order
        return list(pool.map(one, range(len(block)), block))
