"""Match-decision explainability: per-pair score provenance replay.

ISSUE 5 tentpole, the on-demand half: given two records — by id or raw —
replay the full scoring pipeline in explain mode and return a structured
breakdown answering "why did (or didn't) A link to B":

  * **retrieval provenance** — how the pair would meet: inverted-index
    terms hit with tf/idf contributions (host backend,
    index.inverted.explain_retrieval), embedding cosine + retrieval rank
    + the EFFECTIVE top-C after recall escalation — and, under DUKE_IVF,
    the probed-cell list plus whether the candidate's cell was probed,
    the "why was this pair missed" answer (ANN backends, ISSUE 9) — or
    the exhaustive brute-force bounds (device backend);
  * **host breakdown** — per comparison property: the cleaned values,
    per-value-pair comparator similarities, Duke's probability map, and
    the clamped naive-Bayes logit contribution.  Contributions sum (from
    the 0.5 prior, logit 0) to EXACTLY the pair logit
    ``Processor.compare`` folds — same clamps, same iteration order —
    so ``sigmoid(sum)`` reproduces the emitted probability bit-for-bit;
  * **device verdict** — the per-property float32 logits from
    ``ops.scoring.build_property_logits`` (the explain variant of the
    jitted fast path: same kernels, never the same jit program), the
    certified f32 margin, the survivor-filter and decisive-prune bounds,
    and which band the pair lands in (filtered / pruned / rescored);
  * **link state** — the current link row between the two ids, if any.

Replay is SIDE-EFFECT FREE by construction: nothing here indexes,
emits listener events, or writes links — held by the golden parity test
(tests/test_explain.py).  ``explain_request`` takes the workload lock
(read-style, 1 s timeout -> busy) for the whole assembly; the first
explain against a schema jit-compiles a tiny 1x1 pair program under the
lock (cached per plan after that).
"""

from __future__ import annotations

import math
import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..core.bayes import probability_logit
from ..core.records import Record
from ..telemetry.decisions import classify, explanation_digest

__all__ = [
    "ExplainBusy",
    "ExplainError",
    "host_breakdown",
    "device_breakdown",
    "retrieval_provenance",
    "explain_pair",
    "explain_request",
    "resolve_records",
]

# value-pair rows listed per property in the breakdown; the BEST pair is
# always reported, this only bounds the exhaustive listing for
# pathological multi-valued records (V x V combos)
_MAX_PAIR_ROWS = 16


class ExplainError(Exception):
    """4xx-shaped client error (unknown id / malformed payload)."""

    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status


class ExplainBusy(Exception):
    """Workload lock unavailable within the read timeout."""


# -- host breakdown -----------------------------------------------------------


def host_breakdown(schema, r1: Record, r2: Record) -> Dict[str, Any]:
    """Per-property provenance of ``Processor.compare(r1, r2)``.

    Mirrors the host engine's fold exactly: per property the max over
    value pairs of ``Property.compare_probability`` (strict ``>`` — the
    first maximum wins, as in the engine), per-property logit via the
    same clamped ``core.bayes.probability_logit``, summed from the 0.5
    prior.  A property with values missing on either side contributes
    nothing (and reports ``status: "missing"``).
    """
    props: List[Dict[str, Any]] = []
    total = 0.0
    for prop in schema.comparison_properties():
        vs1 = [v for v in r1.get_values(prop.name) if v]
        vs2 = [v for v in r2.get_values(prop.name) if v]
        entry: Dict[str, Any] = {
            "name": prop.name,
            "comparator": (type(prop.comparator).__name__
                           if prop.comparator is not None else None),
            "low": prop.low,
            "high": prop.high,
            "values1": vs1,
            "values2": vs2,
        }
        if not vs1 or not vs2:
            entry.update(status="missing", probability=None,
                         best_similarity=None, logit=0.0)
            props.append(entry)
            continue
        best = 0.0
        best_sim: Optional[float] = None
        best_pair: Optional[Tuple[str, str]] = None
        pair_rows: List[Dict[str, Any]] = []
        for v1 in vs1:
            for v2 in vs2:
                p = prop.compare_probability(v1, v2)
                sim = (prop.comparator.compare(v1, v2)
                       if prop.comparator is not None else None)
                if len(pair_rows) < _MAX_PAIR_ROWS:
                    pair_rows.append({
                        "value1": v1, "value2": v2,
                        "similarity": sim, "probability": p,
                    })
                if p > best:
                    best, best_sim, best_pair = p, sim, (v1, v2)
        logit = probability_logit(best)
        total += logit
        entry.update(
            status="compared", probability=best, best_similarity=best_sim,
            best_pair=list(best_pair) if best_pair else None,
            logit=logit, pairs=pair_rows,
        )
        props.append(entry)
    probability = 1.0 / (1.0 + math.exp(-total))
    return {
        "properties": props,
        "pair_logit": total,
        "probability": probability,
    }


# -- device breakdown ---------------------------------------------------------

# jitted 1x1 explain programs per plan identity (ops.scoring
# .build_property_logits); tiny, but re-tracing per request would make
# /explain latency compile-bound forever
_SCORER_LOCK = threading.Lock()
_SCORERS: Dict[tuple, Any] = {}
_SCORER_CAP = 32


def _plan_key(plan) -> tuple:
    # id() distinguishes comparator PARAMETER changes (QGram q, numeric
    # min_ratio, ...) that name/kind/low/high/widths would not capture.
    # Sound only because each cache entry holds a strong reference to
    # its plan (and so its comparators): a live entry's comparator can
    # never be garbage-collected, so its id can never be reused by a
    # different-parameter comparator from a config reload.
    return tuple(
        (s.name, s.kind, s.low, s.high, s.v, s.chars, id(s.comparator))
        for s in plan.device_props
    )


def _explain_scorer(plan):
    import jax

    from ..ops import scoring as S

    key = _plan_key(plan)
    with _SCORER_LOCK:
        entry = _SCORERS.get(key)
        if entry is None:
            # (fn, plan): the plan ref pins the comparators — see
            # _plan_key's id()-soundness note
            entry = (jax.jit(S.build_property_logits(plan)), plan)
            if len(_SCORERS) >= _SCORER_CAP:
                _SCORERS.pop(next(iter(_SCORERS)))
            _SCORERS[key] = entry
        return entry[0]


def _frozen_plan(plan):
    """Immutable spec copies: the live plan mutates in place under
    ingest (value-slot growth, demotion) and a trace must never read a
    spec mid-mutation (the _ScorerCache._frozen_plan precedent)."""
    from dataclasses import replace

    from ..ops import features as F

    return F.SchemaFeatures(
        device_props=[replace(s) for s in plan.device_props],
        host_props=list(plan.host_props),
    )


def device_breakdown(index, r1: Record, r2: Record, *,
                     decisive: bool = True,
                     device: bool = True) -> Optional[Dict[str, Any]]:
    """The pair's device-path f32 verdict with per-property provenance.

    Extracts both records under a frozen copy of the CORPUS plan (so
    char truncation / value-slot caps reproduce what device pruning of
    indexed rows actually saw) and runs the un-reduced per-property
    logit program.  Returns None for backends without a feature plan
    (host inverted index).
    """
    from ..ops import scoring as S

    plan = getattr(index, "plan", None)
    if plan is None or not plan.device_props:
        return None
    frozen = _frozen_plan(plan)
    device_names = {s.name for s in frozen.device_props}
    feats = index._extract([r1, r2], plan=frozen)
    # the ANN backend rides its embedding matrix through _extract as a
    # pseudo-property; pair scoring wants only the kernel tensors
    feats = {k: v for k, v in feats.items() if k in device_names}
    qf = {prop: {name: arr[0:1] for name, arr in tensors.items()}
          for prop, tensors in feats.items()}
    cf = {prop: {name: arr[1:2] for name, arr in tensors.items()}
          for prop, tensors in feats.items()}
    per_prop = np.asarray(_explain_scorer(frozen)(qf, cf))[0, 0]
    device_logit = float(np.asarray(per_prop, dtype=np.float64).sum())
    schema = index.schema
    margin = S.certified_f32_margin(frozen)
    survivor_bound = S.emit_bound_logit(schema, frozen, 1e-3)
    prune = S.emit_bound_logit(schema, frozen, margin)
    if device_logit <= survivor_bound:
        verdict = "filtered"
    elif decisive and device_logit <= prune:
        verdict = "pruned"
    else:
        verdict = "rescored"
    out = {
        "per_property": [
            {"name": spec.name, "logit": float(x)}
            for spec, x in zip(frozen.device_props, per_prop)
        ],
        "host_properties": [p.name for p in frozen.host_props],
        "logit": device_logit,
        "certified_margin": margin,
        "host_bound_logit": S.host_bound_logit(frozen.host_props),
        "survivor_bound": survivor_bound,
        "decisive_prune_logit": prune,
        "decisive_band_enabled": bool(decisive),
        "band_verdict": verdict,
    }
    out.update(_dd_breakdown(index, frozen, feats, r1, r2, verdict,
                             device=device))
    return out


def _dd_breakdown(index, frozen, feats, r1: Record, r2: Record,
                  band_verdict: str, *, device: bool) -> Dict[str, Any]:
    """Certified-finalization provenance (ISSUE 12): the pair's dd logit,
    the dd margin/bounds, and ``decided_path`` — which finalization path
    decided this pair (``device_certified`` | ``host_rescore`` |
    ``band_skip``) — so an operator can audit why a pair never touched
    the host.  Replays the same dd rescore program the live path runs
    (1x1 gathered layout, Pallas branches off for the one-off shape).
    """
    from ..ops import scoring as S

    schema = index.schema
    if not device:
        return {"decided_path": ("band_skip"
                                 if band_verdict in ("filtered", "pruned")
                                 else "host_rescore"),
                "device_finalize_enabled": False}
    dd_specs = S.dd_plan_specs(frozen)
    fallback = S.dd_fallback_props(schema, frozen)
    out: Dict[str, Any] = {
        "device_finalize_enabled": True,
        "dd_certifiable": [s.name for s in dd_specs],
        "dd_fallback_properties": [p.name for p in fallback],
    }
    if band_verdict in ("filtered", "pruned"):
        out["decided_path"] = "band_skip"
        return out
    if not getattr(index.scorer_cache, "supports_dd", True):
        # only multi-host meshes land here now (ISSUE 18): their dd
        # survivor gather is a collective the follower replay never
        # enqueues, so the live path rescores on host.  Fully-addressable
        # sharded backends report supports_dd=True and fall through to
        # the same dd replay the single-device path runs — the gathered
        # 1x1 layout below is exactly the replicated block
        # _MeshProgramLift._dd_call feeds the live program.
        out["decided_path"] = "host_rescore"
        out["dd_residue_reason"] = "backend"
        return out
    if not dd_specs:
        out["decided_path"] = "host_rescore"
        out["dd_residue_reason"] = "kind"
        return out
    from ..engine.finalize import fallback_pair_logit
    from .device_matcher import _VALUE_SLOTS_MAX

    # same value-slot cap as the live dd rescore (device_matcher), so
    # the replayed dd_unsafe/decided_path agrees with what the live
    # finalizer did for value-slot-saturated records
    fn = S.dd_rescorer(frozen, queries_from_rows=False, pallas_ok=False,
                       value_slots_cap=_VALUE_SLOTS_MAX)
    dd_names = {s.name for s in dd_specs}
    qf = {prop: {name: arr[0:1] for name, arr in tensors.items()}
          for prop, tensors in feats.items() if prop in dd_names}
    cf = {prop: {name: arr[1:2] for name, arr in tensors.items()}
          for prop, tensors in feats.items() if prop in dd_names}
    hi, lo, unsafe = fn(qf, cf, np.full((1,), -1, np.int32),
                        np.zeros((1, 1), np.int32))
    dd_logit = float(np.float64(np.asarray(hi)[0, 0])
                     + np.float64(np.asarray(lo)[0, 0]))
    total = dd_logit + fallback_pair_logit(fallback, r1, r2)
    dd_margin = S.certified_dd_margin(frozen)
    reject = S.dd_reject_bound(schema, frozen)
    event = S.dd_event_bound(schema, frozen)
    out.update(
        dd_logit=dd_logit,
        certified_dd_margin=dd_margin,
        dd_total_logit=total,
        dd_reject_bound=reject,
        dd_event_bound=event,
        dd_unsafe=bool(np.asarray(unsafe)[0, 0]),
    )
    if out["dd_unsafe"]:
        out["decided_path"] = "host_rescore"
        out["dd_residue_reason"] = "truncation"
    elif total <= reject or total >= event:
        # certified verdict: a reject skips the host entirely; a
        # certified event still fetches its bit-exact confidence from
        # one host compare, but the CLASS was decided on device
        out["decided_path"] = "device_certified"
    else:
        out["decided_path"] = "host_rescore"
        out["dd_residue_reason"] = "margin"
    return out


# -- retrieval provenance -----------------------------------------------------


def retrieval_provenance(workload, r1: Record,
                         r2: Record) -> Optional[Dict[str, Any]]:
    """How retrieval would (or would not) surface ``r2`` as a candidate
    for ``r1`` — dispatched to the blocking backend's
    ``explain_retrieval`` (index.inverted / engine.device_matcher /
    engine.ann_matcher)."""
    explain = getattr(workload.index, "explain_retrieval", None)
    if explain is None:
        return None
    gf = bool(getattr(workload.processor, "group_filtering", False))
    try:
        return explain(r1, r2, group_filtering=gf)
    except ValueError as e:
        # group-filtering precondition (missing dukeGroupNo): report
        # instead of failing the whole explanation
        return {"error": str(e)}


# -- request assembly ---------------------------------------------------------


def _resolve_one(workload, payload: Dict[str, Any], n: int) -> Record:
    rid = payload.get(f"id{n}")
    if rid is not None:
        record = workload.index.find_record_by_id(str(rid))
        if record is None:
            raise ExplainError(
                404, f"Unknown record id '{rid}' for workload "
                     f"'{workload.name}'")
        return record
    raw = payload.get(f"record{n}")
    if isinstance(raw, dict):
        dataset = raw.get("dataset")
        entity = raw.get("entity")
        if not isinstance(entity, dict):
            raise ExplainError(
                400, f"record{n} must be "
                     "{\"dataset\": <datasetId>, \"entity\": {...}}")
        datasource = workload.datasources.get(str(dataset))
        if datasource is None:
            raise ExplainError(
                404, f"Unknown dataset-id '{dataset}' for workload "
                     f"'{workload.name}'")
        try:
            return datasource.record_for_entity(entity)
        except Exception as e:
            raise ExplainError(400, f"record{n} conversion failed: {e}")
    raise ExplainError(
        400, f"Provide id{n} (an indexed record id) or record{n} "
             "({\"dataset\": ..., \"entity\": {...}})")


def resolve_records(workload, payload: Dict[str, Any]) -> Tuple[Record, Record]:
    return _resolve_one(workload, payload, 1), _resolve_one(workload, payload, 2)


def _existing_link(workload, id1: str, id2: str) -> Optional[Dict[str, Any]]:
    try:
        for link in workload.link_database.get_all_links_for(id1):
            if {link.id1, link.id2} == {id1, id2}:
                return {
                    "status": link.status.value,
                    "kind": link.kind.value,
                    "confidence": link.confidence,
                    "timestamp": link.timestamp,
                }
    except Exception:
        return None  # closed/raced link DB: omit rather than fail
    return None


def explain_pair(workload, r1: Record, r2: Record) -> Dict[str, Any]:
    """Assemble the full explanation (call with ``workload.lock`` held)."""
    from ..store.records import record_digest

    schema = workload.processor.schema
    host = host_breakdown(schema, r1, r2)
    probability = host["probability"]
    outcome = classify(probability, schema.threshold,
                       schema.maybe_threshold)
    finalizer = getattr(workload.processor, "finalizer", None)
    device = device_breakdown(
        workload.index, r1, r2,
        decisive=finalizer.decisive if finalizer is not None else True,
        device=finalizer.device if finalizer is not None else True,
    )
    out: Dict[str, Any] = {
        "workload": workload.name,
        "kind": workload.kind,
        "id1": r1.record_id,
        "id2": r2.record_id,
        "thresholds": {
            "threshold": schema.threshold,
            "maybe_threshold": schema.maybe_threshold,
        },
        "retrieval": retrieval_provenance(workload, r1, r2),
        "properties": host["properties"],
        "pair_logit": host["pair_logit"],
        "probability": probability,
        "classification": outcome,
        "link": _existing_link(workload, r1.record_id, r2.record_id),
        "explanation_digest": explanation_digest(
            record_digest(r1), record_digest(r2), probability),
    }
    if device is not None:
        out["device"] = device
    return out


def explain_request(workload, payload: Dict[str, Any], *,
                    lock_timeout: float = 1.0) -> Dict[str, Any]:
    """``POST /explain`` entry: lock (read-timeout semantics, matching
    the feed endpoints), resolve the two records, assemble the
    explanation.  Raises ``ExplainBusy`` on lock timeout and
    ``ExplainError`` for client errors."""
    if not isinstance(payload, dict):
        raise ExplainError(400, "Request body must be a JSON object")
    if not workload.lock.acquire(timeout=lock_timeout):
        raise ExplainBusy()
    try:
        if workload.closed:
            raise ExplainBusy()
        r1, r2 = resolve_records(workload, payload)
        return explain_pair(workload, r1, r2)
    finally:
        workload.lock.release()
