from .listeners import MatchListener, LinkMatchListener, ServiceMatchListener
from .processor import Processor

__all__ = ["MatchListener", "LinkMatchListener", "ServiceMatchListener", "Processor"]
