"""Bulk corpus-vs-corpus re-match over the ring layout.

The consumer ``parallel/ring.py`` was built for (VERDICT r2: "ring scorer
has no consumer"): re-scoring EVERY live record against the whole corpus —
link-database backfills after a lost/retired link store, re-matching after
a threshold change, or initial population when records were bulk-imported
without scoring.  The service batch path replicates its (small) query
block to every device; here the query block IS the corpus, so replication
would put N full feature tensors on every chip.  The ring shards both
axes: each device holds N/D queries and N/D corpus rows, scores resident
queries against its local shard, and ``ppermute``s the blocks around the
mesh — D hops, O(N/D) transfer per hop, no replication (SURVEY.md
section 5.7's ring-structured pass).

Exactness: the ring carry merge is the same running top-K the single and
replicated layouts use, so surviving pairs equal the brute-force scorer's
(pinned by tests/test_rematch.py and the 100k x 100k virtual-mesh bench).
Surviving pairs are host-finalized with the exact double-precision path
and emitted through the workload's normal listener chain — links assert
idempotently (links.base.CONFIDENCE_EPSILON), so re-matching an intact
link database is a no-op for pollers.

Reachable from the REST surface as ``POST /{kind}/{name}/rematch``
(admin extension; the reference has no bulk operations) and from Python
via ``ring_rematch(workload)``.

Multi-host (r4): the frontend broadcasts a ``rematch`` op before running
(parallel/dispatch.py), follower replicas replay the device-program side
(placement, ring passes, escalation re-runs) in lockstep, and every
result fetch goes through ``process_allgather`` — itself a collective all
processes enter — because the ring outputs are query-sharded and a plain
``np.asarray`` cannot materialize non-addressable shards.
"""

from __future__ import annotations

import logging
import time
from typing import Dict, Optional

import numpy as np

logger = logging.getLogger("ring-rematch")

_INITIAL_TOP_K = 64


def _gather(tree):
    """Materialize query-sharded ring outputs on every host.

    Single-process: plain transfers.  Multi-process: each host holds only
    its shards — ``process_allgather`` (a collective every process enters
    in lockstep, including follower replicas) assembles the full arrays
    everywhere; ``tiled=True`` because the inputs are sharded GLOBAL
    arrays (tiled=False would try to stack a per-process leading axis).
    Callers must invoke this in the same order on every process.
    """
    import jax

    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        return multihost_utils.process_allgather(tree, tiled=True)
    return jax.tree_util.tree_map(np.asarray, tree)


def ring_rematch(workload, *, query_block_rows: Optional[int] = None,
                 mesh=None) -> Dict:
    """Re-score every live record against the whole corpus via the ring.

    Call with the workload lock held.  Returns run stats.  Requires a
    device-family backend (the corpus host mirror supplies both the
    corpus and the query features); the host backend has no feature
    tensors to ride the mesh.
    """
    from ..parallel import dispatch

    index = workload.index
    if getattr(index, "corpus", None) is None:
        raise ValueError(
            "ring re-match needs a device-family backend (device/ann/"
            "sharded); the host backend has no corpus tensors"
        )
    d = dispatch.current()
    key = getattr(index, "_dispatch_key", None)
    if d is not None and key is not None:
        if mesh is not None and mesh is not getattr(index, "mesh", None):
            # a custom mesh would compile different collective programs
            # than the followers' (they use the index mesh) — deadlock,
            # not divergence, so refuse up front
            raise ValueError(
                "ring_rematch(mesh=...) cannot override the serving mesh "
                "in a multi-host job"
            )
        # multi-host: followers replay the device-program side of this
        # exact run (same block bounds, same escalation decisions from
        # the gathered counts) so the ring collectives rendezvous
        # Deterministic pre-device failures raise symmetrically on the
        # followers too, but distinguishing them from a mid-run abort is
        # not worth serving a wedged mesh — latch on any failure
        # (dispatch.latch_on_failure, shared with commit/score).
        with d.op_lock:
            d.broadcast(dispatch.with_trace_ctx(
                ("rematch", key, query_block_rows)))
            with dispatch.latch_on_failure(
                d, "frontend rematch aborted mid-run"
            ):
                return _ring_rematch_impl(
                    index, workload.processor, index.schema,
                    query_block_rows=query_block_rows, mesh=mesh,
                    finalize=True,
                )
    return _ring_rematch_impl(
        index, workload.processor, index.schema,
        query_block_rows=query_block_rows, mesh=mesh, finalize=True,
    )


def replay_rematch(index, processor, query_block_rows=None) -> None:
    """Follower-side replay (parallel.dispatch op ``rematch``): the same
    device-program sequence with host finalization off."""
    _ring_rematch_impl(index, processor, index.schema,
                       query_block_rows=query_block_rows,
                       mesh=getattr(index, "mesh", None), finalize=False)


def _ring_rematch_impl(index, processor, schema, *, query_block_rows,
                       mesh, finalize: bool) -> Dict:
    from ..parallel.ring import RingQueryPlacer, build_ring_scorer
    from ..parallel.sharded import ShardedCorpus
    from .device_matcher import _CHUNK

    corpus = index.corpus
    if mesh is None:
        mesh = getattr(index, "mesh", None)
    if mesh is None:
        from .sharded_matcher import serving_mesh

        mesh = serving_mesh()

    group_filtering = processor.group_filtering
    plan = index.plan
    t0 = time.perf_counter()

    # live rows only (valid, not tombstoned, not dukeDeleted)
    size = corpus.size
    live = corpus.row_valid[:size] & ~corpus.row_deleted[:size]
    live_rows = np.nonzero(live)[0]
    n = int(live_rows.size)
    stats = {"queries": n, "corpus_rows": n, "pairs_ranked": 0,
             "survivor_pairs": 0, "events": 0, "seconds": 0.0,
             "devices": int(mesh.size)}
    if n == 0:
        return stats

    # corpus placement: host mirror -> record-axis shards (one-time bulk
    # upload; only the plan's device properties ride — the ANN embedding
    # pseudo-property is irrelevant to the brute-force ring pass)
    prop_names = {spec.name for spec in plan.device_props}
    host_feats = {
        prop: {k: a[:size] for k, a in tensors.items()}
        for prop, tensors in corpus.feats.items() if prop in prop_names
    }
    placer = ShardedCorpus(mesh, chunk=_CHUNK)
    sfeats, svalid, sdeleted, sgroup = placer.place(
        host_feats, corpus.row_valid[:size], corpus.row_deleted[:size],
        corpus.row_group[:size],
    )

    qplacer = RingQueryPlacer(mesh)
    min_logit = index.scorer_cache._min_logit()
    block = query_block_rows or 4096 * mesh.size
    scorers: Dict[int, object] = {}

    def scorer(k):
        if k not in scorers:
            scorers[k] = build_ring_scorer(
                plan, mesh, chunk=_CHUNK, top_k=k,
                group_filtering=group_filtering,
            )
        return scorers[k]

    listeners = processor.listeners if finalize else []
    for listener in listeners:
        listener.batch_ready(n)
    threshold = schema.threshold
    maybe = schema.maybe_threshold
    row_ids = corpus.row_ids
    records = index.records

    try:
        for start in range(0, n, block):
            rows = live_rows[start:start + block]
            qfeats_np = {
                prop: {k: a[rows] for k, a in tensors.items()}
                for prop, tensors in host_feats.items()
            }
            qgroup = corpus.row_group[rows]
            qrow = rows.astype(np.int32)
            rqf, rqg, rqr = qplacer.place(qfeats_np, qgroup, qrow)

            k = min(_INITIAL_TOP_K, max(corpus.capacity, 1))
            while True:
                import jax.numpy as jnp

                tl, ti, cnt = scorer(k)(
                    rqf, sfeats, svalid, sdeleted, sgroup, rqg, rqr,
                    jnp.float32(min_logit),
                )
                # only cnt drives the widening decision — gather it alone
                # per iteration (tl/ti would be megabytes of discarded
                # cross-host transfer per widening step); every process
                # runs this same sequence, so the collective order is
                # identical (parallel/dispatch.py invariant 2)
                cnt_np = _gather(cnt)
                cmax = int(cnt_np[: rows.size].max(initial=0))
                if cmax <= k or k >= placer.padded_capacity(size):
                    break
                k = min(k * 2, placer.padded_capacity(size))
                logger.info("ring escalation: %d at the bound, width=%d",
                            cmax, k)
            top_logit, top_index = _gather((tl, ti))
            top_logit = top_logit[: rows.size]
            top_index = top_index[: rows.size]
            stats["pairs_ranked"] += int(rows.size) * n

            if not finalize:
                continue
            # host finalization: each unordered pair is ranked from both
            # sides; keep the (qrow < crow) orientation so events emit once
            for qi in range(rows.size):
                qrow_global = int(rows[qi])
                record = records.get(row_ids[qrow_global])
                if record is None:
                    continue
                keep = top_logit[qi] > min_logit
                for crow in top_index[qi][keep]:
                    crow = int(crow)
                    if crow < 0 or crow <= qrow_global:
                        continue
                    candidate = records.get(row_ids[crow])
                    if candidate is None:
                        continue
                    stats["survivor_pairs"] += 1
                    prob = processor.compare(record, candidate)
                    if prob > threshold:
                        stats["events"] += 1
                        for listener in listeners:
                            listener.matches(record, candidate, prob)
                    elif maybe is not None and maybe != 0.0 and prob > maybe:
                        stats["events"] += 1
                        for listener in listeners:
                            listener.matches_perhaps(record, candidate, prob)
    finally:
        for listener in listeners:
            listener.batch_done()
    stats["seconds"] = round(time.perf_counter() - t0, 3)
    logger.info("ring re-match: %s", stats)
    return stats
