"""Continuous cross-request microbatching scheduler (ISSUE 6 tentpole).

The inference-serving "continuous batching" pattern (Orca, OSDI '22; vLLM,
SOSP '23) applied to record matching: instead of each HTTP POST walking the
engine alone under the per-workload lock — device launch shapes being
whatever batch size clients happen to send, overload answered by a bare
busy-503 — a per-workload bounded ingest queue sits between HTTP and the
engine and a single dispatcher thread:

  * **coalesces** concurrent POSTs into device-shaped microbatches: each
    pick drains the queue and, when the drained total still sits below its
    padding-bucket boundary (``engine.device_matcher.query_buckets`` — the
    ladder the jitted scorer shapes compile against), waits up to
    ``DUKE_SCHED_WINDOW_MS`` for more arrivals so the launch pads less.
    The window anchors on the HEAD request's enqueue time, so no request
    ever waits more than one window for a fuller launch;
  * **dispatches** each microbatch under the workload lock through
    ``Workload._run_merged`` — the same conflict-splitting merge the
    opportunistic lock-winner path uses — so per-request conversion
    errors stay per-request and event streams / link rows are
    bit-identical to serialized (queue-order) execution;
  * **admits** with an SLO estimate instead of lock-contention 503s:
    past ``DUKE_SCHED_QUEUE_MAX`` pending requests per workload,
    ``submit`` raises :class:`SchedulerReject` carrying a ``Retry-After``
    derived from the queued record count and the observed per-record
    dispatch rate (EWMA) — the HTTP layer maps it to 429;
  * **schedules fairly** across workloads with deficit round-robin
    (``DUKE_SCHED_QUANTUM`` records of quantum per round), so one hot
    tenant's deep queue cannot starve the others — their requests ride
    the next round, not the end of the hot queue;
  * **enforces per-tenant quotas** (ISSUE 19): ``DUKE_TENANT_WEIGHT``
    scales each tenant's per-round quantum (``kind/name=2,name=0.5``
    comma map) and ``DUKE_TENANT_MIN_SHARE`` is the starvation-proof
    floor every tenant earns regardless of weight.  Deficit-starved
    rounds count into ``duke_tenant_throttled_total``, and a
    down-weighted tenant's 429 Retry-After scales by its weight so its
    clients back off at the rate it actually drains.

``DUKE_SCHEDULER=0`` disables the subsystem entirely; the HTTP layer then
falls back to today's lock-winner merge in ``Workload.submit_batch``.

Config-reload interop: queues are keyed by (kind, name), and the
dispatcher re-resolves the workload from the live registry at dispatch
time — a hot reload that replaces the workload just retargets queued
requests at the replacement (drain + requeue for free), and a reload that
REMOVES the workload fails them with :class:`WorkloadGone` (the HTTP
layer's 404).  Shutdown drains: ``shutdown()`` stops admission and the
dispatcher completes every queued request before exiting, so no request
is ever lost or completed twice.
"""

from __future__ import annotations

import contextlib
import logging
import math
import threading
import time
from collections import deque
from typing import Callable, Deque, Dict, List, Optional, Tuple

from ..telemetry import slo, tracing
from ..telemetry.decisions import _MonitorHist
from ..telemetry.env import env_flag, env_float, env_int, env_str

logger = logging.getLogger("ingest-scheduler")

__all__ = [
    "DatasetGone",
    "IngestScheduler",
    "SchedulerClosed",
    "SchedulerReject",
    "WorkloadGone",
    "parse_tenant_weights",
    "scheduler_enabled",
]


def scheduler_enabled() -> bool:
    """``DUKE_SCHEDULER=0`` restores the pre-scheduler ingest path."""
    return env_flag("DUKE_SCHEDULER", True)


# The query-padding ladder default, here (jax-import-free) so BOTH
# consumers — device_matcher's _QUERY_BUCKETS and this module's jax-less
# fallback — parse the same knob with the same default via
# telemetry.env.env_int_tuple and cannot drift.
DEFAULT_QUERY_BUCKETS = "16,128,1024,2048,4096"

# ONE copy of the smoothing/clamp policy shared by every Retry-After
# source (the scheduler's sec/record estimator here and the workload
# lock-hold tracker in engine.workload) — tuning it cannot diverge.
EWMA_ALPHA = 0.3


def fold_ewma(prev: Optional[float], sample: float) -> float:
    """Exponentially-weighted fold; ``prev`` None seeds with the sample."""
    if prev is None:
        return sample
    return (1.0 - EWMA_ALPHA) * prev + EWMA_ALPHA * sample


def retry_after_seconds(estimate: float) -> int:
    """Whole-second Retry-After: ceil'd, clamped to [1, 60]."""
    return int(min(60, max(1, math.ceil(estimate))))


def parse_tenant_weights(spec: Optional[str] = None) -> Dict[str, float]:
    """``DUKE_TENANT_WEIGHT`` parse: a comma map of ``key=weight`` where
    key is ``kind/name`` (most specific) or bare ``name``.  Weights
    scale each tenant's DRR quantum; unlisted tenants weigh 1.0.
    Malformed entries are skipped with a log line — a typo must never
    take admission down."""
    if spec is None:
        spec = env_str("DUKE_TENANT_WEIGHT", "")
    out: Dict[str, float] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        key, sep, value = part.partition("=")
        try:
            if not sep or not key.strip():
                raise ValueError("missing '=' or empty key")
            out[key.strip()] = max(0.0, float(value))
        except ValueError:
            logger.warning("ignoring malformed DUKE_TENANT_WEIGHT "
                           "entry %r", part)
    return out


class SchedulerReject(Exception):
    """Admission refused: the workload's queue is at DUKE_SCHED_QUEUE_MAX.

    ``retry_after`` is the SLO estimate in whole seconds (>= 1) the HTTP
    layer forwards as the 429's Retry-After header."""

    def __init__(self, retry_after: int, depth: int):
        super().__init__(
            f"ingest queue full ({depth} requests pending); "
            f"retry in ~{retry_after}s"
        )
        self.retry_after = retry_after
        self.depth = depth


class SchedulerClosed(Exception):
    """Submitted during shutdown: the scheduler no longer admits work."""


class WorkloadGone(Exception):
    """A config reload removed the workload while requests were queued."""

    def __init__(self, kind: str, name: str):
        super().__init__(f"workload {kind}/{name} removed by config reload")
        self.kind = kind
        self.name = name


class DatasetGone(Exception):
    """A config reload replaced the workload with one that no longer
    defines the request's dataset — the queued request was validated
    against the OLD workload, so dispatch re-checks against the
    replacement (the HTTP layer's unknown-dataset 404)."""

    def __init__(self, kind: str, name: str, dataset_id: str):
        super().__init__(
            f"dataset {dataset_id} gone from workload {kind}/{name} "
            f"after config reload"
        )
        self.kind = kind
        self.name = name
        self.dataset_id = dataset_id


class _SchedRequest:
    """One queued ingest request.

    Duck-types ``engine.workload._BatchRequest`` (dataset_id, entities,
    event, error) so ``Workload._run_merged`` completes it in place."""

    __slots__ = ("dataset_id", "entities", "event", "error", "records",
                 "enqueued", "trace_ctx")

    def __init__(self, dataset_id: str, entities, trace_ctx=None):
        self.dataset_id = dataset_id
        self.entities = entities
        self.event = threading.Event()
        self.error: Optional[Exception] = None
        # one entity converts to one record; the count drives bucket fill
        # and DRR accounting without waiting for conversion
        self.records = max(1, len(entities))
        self.enqueued = time.monotonic()
        self.trace_ctx = trace_ctx


# wait-time buckets: sub-window waits up to reload-stall territory
_WAIT_BOUNDS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                1.0, 2.5, 5.0, 10.0, 30.0)
# microbatch fill in records: the ladder region the coalescer targets
_FILL_BOUNDS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0, 512.0,
                1024.0, 2048.0, 4096.0)


class _TenantQueue:
    """Per-(kind, name) bounded queue + DRR deficit + plain counters.

    Counter writes happen under the scheduler condition (submit) or from
    the single dispatcher thread; /metrics and /stats read them lock-free
    like every other single-writer engine counter."""

    __slots__ = ("kind", "name", "weight", "pending", "queued", "deficit",
                 "admitted", "rejected", "throttled", "microbatches",
                 "merged_requests", "dispatched_records", "wait_hist",
                 "fill_hist")

    def __init__(self, kind: str, name: str, weight: float = 1.0):
        self.kind = kind
        self.name = name
        # per-tenant DRR weight (ISSUE 19): scales the quantum this
        # queue earns per round; immutable after creation (re-resolved
        # when a reload recreates the queue)
        self.weight = weight
        # rounds where this tenant's head request exceeded its
        # accumulated deficit — it waited for later rounds' quantum
        # (delayed, never starved: the min-share floor keeps earning)
        self.throttled = 0  # guarded by: self._cv [writes]
        self.pending: Deque[_SchedRequest] = deque()  # guarded by: self._cv [writes]
        # record count mirror of ``pending``, maintained under the
        # scheduler condition — /metrics and /stats read it (and
        # len(pending)) lock-free, so they must never ITERATE the deque
        # (a concurrent append would raise "deque mutated during
        # iteration" and 500 the scrape)
        self.queued = 0  # guarded by: self._cv [writes]
        self.deficit = 0  # guarded by: self._cv [writes]
        self.admitted = 0  # guarded by: self._cv [writes]
        self.rejected = 0  # guarded by: self._cv [writes]
        self.microbatches = 0  # single-writer: dispatcher thread
        self.merged_requests = 0  # single-writer: dispatcher thread
        self.dispatched_records = 0  # single-writer: dispatcher thread
        self.wait_hist = _MonitorHist(_WAIT_BOUNDS)
        self.fill_hist = _MonitorHist(_FILL_BOUNDS)

    def queued_records(self) -> int:
        return self.queued


def _default_buckets() -> Tuple[int, ...]:
    """The device padding ladder; falls back to the shared env parse if
    the device backend cannot import (the ladder is only a shaping hint —
    host backends coalesce toward the same sizes harmlessly)."""
    try:
        from .device_matcher import query_buckets

        return query_buckets()
    except Exception:  # pragma: no cover - jax-less environment
        from ..telemetry.env import env_int_tuple

        return env_int_tuple("DEVICE_QUERY_BUCKETS", DEFAULT_QUERY_BUCKETS)


class IngestScheduler:
    """The per-app ingest scheduler: bounded queues, one dispatcher.

    ``resolve(kind, name)`` returns the LIVE workload for a queue key (or
    None once a reload removed it) — the scheduler never caches workload
    references across microbatches, which is the whole reload story.
    """

    def __init__(self, resolve: Callable[[str, str], object], *,
                 start: bool = True):
        self._resolve = resolve
        self._cv = threading.Condition()
        self._queues: Dict[Tuple[str, str], _TenantQueue] = {}  # guarded by: self._cv
        self._order: List[Tuple[str, str]] = []  # DRR rotation order; guarded by: self._cv
        self._rr_index = 0  # guarded by: self._cv
        self._closed = False  # guarded by: self._cv
        self._thread: Optional[threading.Thread] = None
        self.window_seconds = max(
            0.0, env_float("DUKE_SCHED_WINDOW_MS", 5.0) / 1000.0)
        self.queue_max = max(1, env_int("DUKE_SCHED_QUEUE_MAX", 256))
        self.quantum = max(1, env_int("DUKE_SCHED_QUANTUM", 4096))
        # per-tenant quota knobs (ISSUE 19): DUKE_TENANT_WEIGHT scales
        # each tenant's per-round quantum; DUKE_TENANT_MIN_SHARE is the
        # starvation-proof floor — even a zero-weighted tenant earns
        # max(1, quantum * min_share) records per round, so weights
        # shape throughput, never availability
        self.min_share = min(1.0, max(
            0.0, env_float("DUKE_TENANT_MIN_SHARE", 0.05)))
        self._weights = parse_tenant_weights()
        self._buckets = _default_buckets()
        # sec/record EWMA over dispatched microbatches (dispatcher-written,
        # admission-read): the Retry-After estimator.  Starts None — the
        # first rejections before any dispatch fall back to 1s.
        self._ewma_sec_per_record: Optional[float] = None  # guarded by: self._cv [writes]
        if start:
            self.start()

    # -- client side --------------------------------------------------------

    def submit(self, kind: str, name: str, dataset_id: str,
               entities) -> None:
        """Enqueue one ingest request and block until its microbatch
        commits.  Raises the request's own error (conversion errors stay
        per-request), :class:`SchedulerReject` when the queue is full,
        :class:`WorkloadGone` when a reload removed the workload, or
        :class:`SchedulerClosed` during shutdown."""
        req = _SchedRequest(dataset_id, entities, tracing.current_context())
        with self._cv:
            if self._closed:
                raise SchedulerClosed("scheduler is shutting down")
            key = (kind, name)
            q = self._queues.get(key)
            if q is None:
                q = self._queues[key] = _TenantQueue(
                    kind, name, self._weight_for(kind, name))
                self._order.append(key)
            if len(q.pending) >= self.queue_max:
                q.rejected += 1
                raise SchedulerReject(self._retry_after_locked(q),
                                      len(q.pending))
            q.admitted += 1
            q.pending.append(req)
            q.queued += req.records
            self._cv.notify_all()
        with tracing.span("sched.queued", {
            "workload": name, "kind": kind, "records": req.records,
        }):
            req.event.wait()
        if req.error is not None:
            raise req.error

    def retry_after_hint(self, kind: str, name: str) -> int:
        """Current backlog-drain estimate in whole seconds (for /stats)."""
        with self._cv:
            q = self._queues.get((kind, name))
            return self._retry_after_locked(q) if q is not None else 1

    def _weight_for(self, kind: str, name: str) -> float:
        """``kind/name`` (most specific) wins over bare ``name``."""
        w = self._weights.get(f"{kind}/{name}")
        if w is None:
            w = self._weights.get(name, 1.0)
        return w

    def _quantum_for(self, q: _TenantQueue) -> int:
        """Per-round deficit grant: the weighted quantum with the
        min-share floor (a weight of 0 still drains, just last)."""
        floor = max(1, int(self.quantum * self.min_share))
        return max(floor, int(round(self.quantum * q.weight)))

    def _retry_after_locked(self, q: _TenantQueue) -> int:
        per_record = self._ewma_sec_per_record
        if per_record is None:
            return 1
        est = q.queued_records() * per_record
        if q.weight != 1.0:
            # a down-weighted tenant drains at weight * the fleet rate:
            # its 429s must say so, or a flooding tenant retries on an
            # estimate computed for capacity it no longer gets
            est /= max(q.weight, self.min_share, 1e-9)
        return retry_after_seconds(est)

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._thread_main, name="ingest-scheduler", daemon=True)
        self._thread.start()

    def _thread_main(self) -> None:
        """Dispatcher entry: a crash must fail queued requests loudly,
        never leave them (and every future submit) hanging while
        admission keeps accepting."""
        try:
            self._dispatch_loop()
        except BaseException:
            logger.exception(
                "ingest dispatcher died; failing pending requests and "
                "closing admission")
            err = SchedulerClosed("ingest dispatcher died (see logs)")
            with self._cv:
                self._closed = True
                for q in self._queues.values():
                    while q.pending:
                        req = q.pending.popleft()
                        q.queued -= req.records
                        req.error = err
                        req.event.set()
                self._cv.notify_all()
            raise

    def shutdown(self, timeout: float = 60.0) -> None:
        """Stop admission, drain every queue, join the dispatcher.

        Queued requests complete normally (no lost requests); requests
        submitted after this point raise :class:`SchedulerClosed`."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            if self._thread.is_alive():  # pragma: no cover - wedged lock
                logger.warning("scheduler drain did not finish in %ss",
                               timeout)
            self._thread = None

    # -- dispatcher ---------------------------------------------------------

    def _dispatch_loop(self) -> None:
        while True:
            with self._cv:
                while not self._closed and not any(
                        q.pending for q in self._queues.values()):
                    self._cv.wait()
                if self._closed and not any(
                        q.pending for q in self._queues.values()):
                    return
            dispatched, next_deadline = self._run_round()
            if dispatched == 0:
                # nothing was dispatchable: every non-empty queue is
                # either inside its coalesce window (wake at the earliest
                # head deadline — or sooner, when an arrival notifies the
                # condition and may complete a bucket) or banking deficit
                # (brief yield; the next round's quantum unblocks it)
                now = time.monotonic()
                wait = (min(0.05, max(0.0, next_deadline - now))
                        if next_deadline is not None else 0.001)
                with self._cv:
                    if not self._closed:
                        self._cv.wait(timeout=wait)

    def _run_round(self):
        """One DRR round: every queue earns a quantum; queues whose
        drained total fills its padding bucket (or whose head-anchored
        coalesce window expired) dispatch a microbatch; under-filled
        queues inside their window are requeued untouched — the single
        dispatcher thread NEVER sleeps on one tenant's fill while another
        tenant has work ready.  A head larger than the accumulated
        deficit waits for later rounds (its deficit keeps growing, so it
        is delayed by rounds, never starved).  Returns ``(dispatched,
        next_deadline)`` — the microbatch count and the earliest coalesce
        deadline among the queues still waiting for fill."""
        with self._cv:
            order = list(self._order)
            start = self._rr_index % max(1, len(order))
            self._rr_index += 1
        dispatched = 0
        next_deadline: Optional[float] = None
        for key in order[start:] + order[:start]:
            with self._cv:
                q = self._queues.get(key)
                if q is None:
                    continue
                if not q.pending:
                    q.deficit = 0  # classic DRR: idle queues bank nothing
                    # age out drained queues whose workload a reload
                    # removed — otherwise dead tenants export zero-depth
                    # series and pad every round forever
                    if self._resolve(q.kind, q.name) is None:
                        del self._queues[key]
                        self._order.remove(key)
                    continue
                q.deficit += self._quantum_for(q)
            batch, deadline = self._collect(q)
            if batch:
                if self._dispatch(q, batch):
                    dispatched += 1
                    with self._cv:
                        if not q.pending:
                            q.deficit = 0
                else:
                    # lock contention requeued the batch: back off like a
                    # coalesce deadline instead of re-polling at the idle
                    # loop's 1 ms tick for the whole hold (a reload can
                    # hold workload locks for minutes)
                    deadline = time.monotonic() + 0.05
            if (deadline is not None
                    and (next_deadline is None or deadline < next_deadline)):
                next_deadline = deadline
        return dispatched, next_deadline

    def _collect(self, q: _TenantQueue):
        """Pop a microbatch from ``q``: up to its DRR deficit, coalescing
        toward the padding-bucket boundary.  Never blocks: an under-filled
        batch whose head-anchored window has not expired is requeued
        intact and ``(None, deadline)`` returned — the dispatch loop
        sleeps until the earliest such deadline (or an arrival), so no
        request waits more than one window for a fuller launch and no
        tenant's window ever stalls another tenant's dispatch."""
        batch: List[_SchedRequest] = []
        total = 0
        ladder_max = self._buckets[-1]
        with self._cv:
            while q.pending:
                head = q.pending[0]
                if batch and (total + head.records > q.deficit
                              or total >= ladder_max):
                    break
                if not batch and head.records > q.deficit:
                    # earns more deficit next round; the counter is the
                    # quota-throttle signal (duke_tenant_throttled_total)
                    q.throttled += 1
                    return None, None
                q.pending.popleft()
                q.queued -= head.records
                batch.append(head)
                total += head.records
            if not batch:
                return None, None
            # coalesce window: when the drained total under-fills its
            # padding bucket, hold the batch for more arrivals.  The
            # target anchors on the FIRST drain's boundary — arrivals
            # that overshoot it dispatch immediately instead of
            # escalating the wait toward the next rung.
            target = self._bucket_for(total)
            deadline = batch[0].enqueued + self.window_seconds
            if (total < target and total < q.deficit
                    and not self._closed  # drain ignores windows
                    and time.monotonic() < deadline):
                q.pending.extendleft(reversed(batch))
                q.queued += total
                return None, deadline
            # DRR: consumed quantum leaves the deficit (idle queues are
            # zeroed by the round loop)
            q.deficit -= total
        return batch, None

    def _bucket_for(self, n: int) -> int:
        for b in self._buckets:
            if n <= b:
                return b
        return self._buckets[-1]

    def _dispatch(self, q: _TenantQueue, batch: List[_SchedRequest]) -> bool:
        """Run one microbatch under the live workload's lock.  Returns
        False when the lock was unavailable and the batch was requeued —
        the ONLY dispatcher thread must not block on one workload's long
        hold (a transform, a reload, a wedged writer) while other
        tenants' locks are free; the round loop retries on later rounds
        (the requests' expired windows make the retry dispatch-ready)."""
        try:
            while True:
                wl = self._resolve(q.kind, q.name)
                if wl is None:
                    err = WorkloadGone(q.kind, q.name)
                    for req in batch:
                        req.error = err
                        req.event.set()
                    with self._cv:  # age the dead tenant's queue out too
                        if not q.pending and (q.kind, q.name) in self._queues:
                            del self._queues[(q.kind, q.name)]
                            self._order.remove((q.kind, q.name))
                    return True
                # re-validate datasets against the (possibly reloaded)
                # workload: admission validated against the OLD one, and
                # _run_merged would surface a missing dataset as a bare
                # KeyError (a 500) instead of the unknown-dataset 404
                live: List[_SchedRequest] = []
                for req in batch:
                    if req.dataset_id not in wl.datasources:
                        req.error = DatasetGone(q.kind, q.name,
                                                req.dataset_id)
                        req.event.set()
                    else:
                        live.append(req)
                batch = live
                if not batch:
                    return True
                total = sum(r.records for r in batch)
                if not wl.lock.acquire(blocking=False):
                    with self._cv:
                        q.pending.extendleft(reversed(batch))
                        q.queued += total
                        q.deficit += total  # restore the consumed quantum
                    return False
                try:
                    if wl.closed:
                        continue  # reload swapped it: re-resolve
                    t0 = time.monotonic()
                    for req in batch:
                        q.wait_hist.observe(t0 - req.enqueued)
                    # engine spans land in the HEAD request's trace; the
                    # merged siblings' trace ids ride as an attribute so
                    # a tail-latched slow microbatch still names every
                    # constituent (their own traces show the queue wait)
                    ctx = batch[0].trace_ctx
                    attach = (tracing.attach(ctx) if ctx is not None
                              else contextlib.nullcontext())
                    merged_ids = [
                        r.trace_ctx[0].trace_id for r in batch[:8]
                        if r.trace_ctx is not None
                    ]
                    with attach, tracing.span("sched.microbatch", {
                        "workload": q.name, "kind": q.kind,
                        "requests": len(batch), "records": total,
                        "bucket": self._bucket_for(total),
                        "merged_trace_ids": ",".join(merged_ids),
                    }):
                        wl._run_merged(list(batch))
                    hold = time.monotonic() - t0
                    note = getattr(wl, "note_lock_hold", None)
                    if note is not None:
                        note(hold)
                finally:
                    wl.lock.release()
                # always-on SLO signal (ISSUE 16): per-request ingest
                # latency from SCHEDULER ARRIVAL to microbatch completion
                # — the queueing delay included — folded under ONE leaf
                # tracker lock per microbatch, taken with no other lock
                # held; the feed-lag meter marks the rows this batch may
                # have minted (plain attribute write)
                done = time.monotonic()
                slo.tracker("ingest", q.kind, q.name).record_batch(
                    [done - req.enqueued for req in batch], done,
                    # exemplar trace ids for violation entries: only
                    # sampled traces link anywhere, so unsampled → None
                    [(req.trace_ctx[0].trace_id
                      if req.trace_ctx is not None
                      and req.trace_ctx[0].sampled else None)
                     for req in batch])
                slo.feed_meter(q.kind, q.name).note_write()
                q.microbatches += 1
                q.merged_requests += len(batch)
                q.dispatched_records += total
                q.fill_hist.observe(float(total))
                # once per microbatch, and admission reads the estimator
                # under _cv — fold under the same lock so a Retry-After
                # computed mid-fold can never mix old/new EWMA state
                with self._cv:
                    self._ewma_sec_per_record = fold_ewma(
                        self._ewma_sec_per_record, hold / max(1, total))
                return True
        except Exception as e:  # never lose a request on dispatcher errors
            logger.exception("microbatch dispatch failed for %s/%s",
                             q.kind, q.name)
            for req in batch:
                if not req.event.is_set():
                    req.error = e
                    req.event.set()
            return True

    # -- observability ------------------------------------------------------

    def queues(self) -> List[_TenantQueue]:
        """Stable snapshot of the tenant queues for scrape-time walkers."""
        with self._cv:
            return list(self._queues.values())

    def stats_snapshot(self) -> dict:
        """The /stats scheduler block."""
        out = {
            "window_ms": round(self.window_seconds * 1000.0, 3),
            "queue_max": self.queue_max,
            "quantum_records": self.quantum,
            "min_share": self.min_share,
            "sec_per_record_ewma": (
                round(self._ewma_sec_per_record, 9)
                if self._ewma_sec_per_record is not None else None
            ),
            "workloads": [],
        }
        for q in self.queues():
            waits = q.wait_hist
            out["workloads"].append({
                "kind": q.kind,
                "name": q.name,
                "weight": q.weight,
                "depth": len(q.pending),
                "queued_records": q.queued_records(),
                "admitted": q.admitted,
                "rejected": q.rejected,
                "throttled": q.throttled,
                "microbatches": q.microbatches,
                "merged_requests": q.merged_requests,
                "records_dispatched": q.dispatched_records,
                "avg_wait_ms": (
                    round(waits.total / waits.count * 1000.0, 3)
                    if waits.count else None
                ),
                "retry_after_hint": self.retry_after_hint(q.kind, q.name),
            })
        return out
