"""Match-event listeners.

Reproduces the reference's listener chain: Duke's ``MatchListener`` event
protocol (startProcessing/batchReady/matches/matchesPerhaps/noMatchFor/
batchDone/endProcessing — BaseLinkDatabaseMatchListener.java:53-109), the
link-database-forwarding listener, and the service listener that additionally
accumulates per-entity matches for http-transform responses
(BaseLinkDatabaseMatchListener.java:44-46,84-88,115-136) and can be switched
off while a transform runs (lines 111-113).
"""

from __future__ import annotations

import logging
import time
from typing import Dict, List, Optional, Tuple

from ..core.records import ORIGINAL_ENTITY_ID_PROPERTY_NAME, DATASET_ID_PROPERTY_NAME, Record
from ..links.base import Link, LinkDatabase, LinkKind, LinkStatus


class MatchListener:
    def start_processing(self) -> None: ...
    def batch_ready(self, size: int) -> None: ...
    def matches(self, r1: Record, r2: Record, confidence: float) -> None: ...
    def matches_perhaps(self, r1: Record, r2: Record, confidence: float) -> None: ...
    def no_match_for(self, record: Record) -> None: ...
    def batch_done(self) -> None: ...
    def end_processing(self) -> None: ...


class LinkMatchListener(MatchListener):
    """Duke's LinkDatabaseMatchListener: persist match events as links."""

    def __init__(self, linkdb: LinkDatabase):
        self.linkdb = linkdb

    def matches(self, r1: Record, r2: Record, confidence: float) -> None:
        self.linkdb.assert_link(
            Link(r1.record_id, r2.record_id, LinkStatus.INFERRED,
                 LinkKind.DUPLICATE, confidence)
        )

    def matches_perhaps(self, r1: Record, r2: Record, confidence: float) -> None:
        self.linkdb.assert_link(
            Link(r1.record_id, r2.record_id, LinkStatus.INFERRED,
                 LinkKind.MAYBE, confidence)
        )

    def batch_done(self) -> None:
        self.linkdb.commit()


class ServiceMatchListener(MatchListener):
    """The workload listener: forwards to the link DB (unless disabled for
    http-transform) and accumulates per-entity matches for the transform
    response (``duke_links``)."""

    def __init__(self, workload_name: str, linkdb: LinkDatabase,
                 kind: str = "deduplication", one_to_one: bool = False):
        self._wrapped = LinkMatchListener(linkdb)
        self.link_database_updates_disabled = False
        self._entity_matches: Dict[str, List[Tuple[Record, float]]] = {}
        # one-to-one enforcement (opt-in): the reference parses
        # link-mode="one-to-one" but never reads the flag (SURVEY.md quirk
        # Q5), so by default every above-threshold pair links.  With
        # ``one_to_one`` definite matches are buffered per batch and
        # resolved greedily by descending confidence so each record links
        # to at most one counterpart; maybe-matches pass through.
        self.one_to_one = one_to_one
        self._pending_matches: List[Tuple[float, Record, Record]] = []
        prefix = (
            "recordLinkageMatchListener" if kind == "recordlinkage"
            else "deduplicationMatchListener"
        )
        self.logger = logging.getLogger(f"{prefix}-{workload_name}")
        self._batch_start: Optional[float] = None

    def set_link_database_updates_disabled(self, disabled: bool) -> None:
        self.link_database_updates_disabled = disabled

    def batch_ready(self, size: int) -> None:
        self._entity_matches = {}
        self._pending_matches = []
        self._batch_start = time.monotonic()
        self.logger.info("batchReady(size=%d)", size)
        if not self.link_database_updates_disabled:
            self._wrapped.batch_ready(size)

    def batch_done(self) -> None:
        if self.one_to_one:
            self._flush_one_to_one()
        if not self.link_database_updates_disabled:
            self._wrapped.batch_done()
        if self._batch_start is not None:
            self.logger.info(
                "batchDone() batchElapsedTime: %s seconds.",
                time.monotonic() - self._batch_start,
            )

    def _flush_one_to_one(self) -> None:
        """Greedy max-confidence assignment: each record in at most one
        definite link — within the batch AND against links asserted by
        earlier batches (a stronger new pair retracts the weaker existing
        link; a weaker one is suppressed).  Ties break on record ids so
        the output is deterministic under threaded scoring."""
        taken: set = set()
        # secondary keys make equal-confidence ordering independent of
        # listener-call interleaving (THREADS > 1)
        for confidence, r1, r2 in sorted(
            self._pending_matches,
            key=lambda t: (-t[0], t[1].record_id, t[2].record_id),
        ):
            if r1.record_id in taken or r2.record_id in taken:
                continue
            if not self.link_database_updates_disabled:
                blocked, to_retract = self._existing_conflicts(
                    r1.record_id, r2.record_id, confidence
                )
                if blocked:
                    continue
                for link in to_retract:
                    link.retract()
                    self._wrapped.linkdb.assert_link(link)
                self._wrapped.matches(r1, r2, confidence)
            taken.add(r1.record_id)
            taken.add(r2.record_id)
            self._record_entity_match(r1, r2, confidence)
        self._pending_matches = []

    def _existing_conflicts(self, id1: str, id2: str, confidence: float):
        """Definite links from earlier batches touching either record.

        Returns (blocked, to_retract): blocked when an existing link with
        >= confidence already claims one of the records; otherwise the
        weaker existing links to retract before asserting the new pair.
        """
        pair = {id1, id2}
        blocked = False
        to_retract = []
        for rid in pair:
            for link in self._wrapped.linkdb.get_all_links_for(rid):
                if link.kind != LinkKind.DUPLICATE:
                    continue
                if link.status == LinkStatus.RETRACTED:
                    continue
                if {link.id1, link.id2} == pair:
                    continue  # same pair: plain re-assert
                if link.confidence >= confidence:
                    blocked = True
                else:
                    to_retract.append(link)
        return blocked, to_retract

    def matches(self, r1: Record, r2: Record, confidence: float) -> None:
        if self.one_to_one:
            self._pending_matches.append((confidence, r1, r2))
            return
        if not self.link_database_updates_disabled:
            self._wrapped.matches(r1, r2, confidence)
        self._record_entity_match(r1, r2, confidence)

    def matches_perhaps(self, r1: Record, r2: Record, confidence: float) -> None:
        if not self.link_database_updates_disabled:
            self._wrapped.matches_perhaps(r1, r2, confidence)
        self._record_entity_match(r1, r2, confidence)

    def no_match_for(self, record: Record) -> None:
        if not self.link_database_updates_disabled:
            self._wrapped.no_match_for(record)

    def start_processing(self) -> None:
        if not self.link_database_updates_disabled:
            self._wrapped.start_processing()

    def end_processing(self) -> None:
        if not self.link_database_updates_disabled:
            self._wrapped.end_processing()

    def _record_entity_match(self, r1: Record, r2: Record, confidence: float) -> None:
        entity_id = r1.get_value(ORIGINAL_ENTITY_ID_PROPERTY_NAME)
        self._entity_matches.setdefault(entity_id, []).append((r2, confidence))

    def get_links_for_entity(self, entity_id: str) -> List[dict]:
        """duke_links rows for one input entity
        (BaseLinkDatabaseMatchListener.java:115-136)."""
        out = []
        for record, confidence in self._entity_matches.get(entity_id, []):
            out.append(
                {
                    "datasetId": record.get_value(DATASET_ID_PROPERTY_NAME),
                    "entityId": record.get_value(ORIGINAL_ENTITY_ID_PROPERTY_NAME),
                    "confidence": confidence,
                }
            )
        return out
