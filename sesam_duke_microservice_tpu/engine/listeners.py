"""Match-event listeners.

Reproduces the reference's listener chain: Duke's ``MatchListener`` event
protocol (startProcessing/batchReady/matches/matchesPerhaps/noMatchFor/
batchDone/endProcessing — BaseLinkDatabaseMatchListener.java:53-109), the
link-database-forwarding listener, and the service listener that additionally
accumulates per-entity matches for http-transform responses
(BaseLinkDatabaseMatchListener.java:44-46,84-88,115-136) and can be switched
off while a transform runs (lines 111-113).
"""

from __future__ import annotations

import logging
import time
from typing import Dict, List, Optional, Tuple

from ..core.records import ORIGINAL_ENTITY_ID_PROPERTY_NAME, DATASET_ID_PROPERTY_NAME, Record
from ..links.base import Link, LinkDatabase, LinkKind, LinkStatus


class MatchListener:
    def start_processing(self) -> None: ...
    def batch_ready(self, size: int) -> None: ...
    def matches(self, r1: Record, r2: Record, confidence: float) -> None: ...
    def matches_perhaps(self, r1: Record, r2: Record, confidence: float) -> None: ...
    def no_match_for(self, record: Record) -> None: ...
    def batch_done(self) -> None: ...
    def end_processing(self) -> None: ...


class LinkMatchListener(MatchListener):
    """Duke's LinkDatabaseMatchListener: persist match events as links.

    With ``batch=True`` (the default) the batch's links are collected and
    handed to the database as ONE ``assert_links`` call at ``batch_done``
    — a single transaction on the durable backend instead of a
    query+commit per link, which dominated the persist phase on
    match-heavy batches.  Timestamps are assigned at event time (Link
    construction), so the deferred write is invisible to ``?since=``
    pollers.  ``batch=False`` preserves the legacy per-event write for
    embedders that read the database mid-batch.

    When ``DUKE_AUDIT_LOG`` is set, every confirmed link decision that
    reaches this listener (post one-to-one resolution — only links that
    are actually asserted) also appends an audit entry carrying the two
    records' content digests and the explanation digest that a later
    ``POST /explain`` replay of the same pair reproduces
    (telemetry.decisions).  The audit file flushes write-behind at
    ``batch_done``; it can never block scoring.
    """

    def __init__(self, linkdb: LinkDatabase, batch: bool = True,
                 audit_context: Optional[Tuple[str, str]] = None):
        self.linkdb = linkdb
        self.batch = batch
        self._pending: List[Link] = []
        # (kind, workload-name) stamped into audit rows
        self._audit_context = audit_context or ("", "")
        self._audit = None

    def batch_ready(self, size: int) -> None:
        # a batch that aborted mid-scoring must not leak its buffered
        # links into the next batch's flush transaction
        self._pending = []
        from ..telemetry.decisions import audit_log

        # re-resolved per batch so env changes (tests, ops toggles) take
        # effect without a workload rebuild
        self._audit = audit_log()

    def _assert(self, link: Link) -> None:
        if self.batch:
            self._pending.append(link)
        else:
            self.linkdb.assert_link(link)

    def _audit_entry(self, r1: Record, r2: Record, confidence: float,
                     kind: str) -> None:
        if self._audit is None:
            return
        from ..store.records import record_digest
        from ..telemetry.decisions import explanation_digest

        d1, d2 = record_digest(r1), record_digest(r2)
        self._audit.append({
            "time_unix": round(time.time(), 3),
            "kind": self._audit_context[0],
            "workload": self._audit_context[1],
            "id1": r1.record_id,
            "id2": r2.record_id,
            "link_kind": kind,
            "confidence": confidence,
            "record_digest1": d1.hex(),
            "record_digest2": d2.hex(),
            "explanation_digest": explanation_digest(d1, d2, confidence),
        })

    def matches(self, r1: Record, r2: Record, confidence: float) -> None:
        self._assert(
            Link(r1.record_id, r2.record_id, LinkStatus.INFERRED,
                 LinkKind.DUPLICATE, confidence)
        )
        self._audit_entry(r1, r2, confidence, "duplicate")

    def matches_perhaps(self, r1: Record, r2: Record, confidence: float) -> None:
        self._assert(
            Link(r1.record_id, r2.record_id, LinkStatus.INFERRED,
                 LinkKind.MAYBE, confidence)
        )
        self._audit_entry(r1, r2, confidence, "maybe")

    def flush_pending(self) -> None:
        """Hand the collected links to the database now (one batched
        call), without ending the batch.  The one-to-one flush calls this
        before its conflict prefetch so this batch's pass-through
        maybe-link upserts are visible to the prefetched link state,
        exactly as the legacy per-event writes were."""
        pending, self._pending = self._pending, []
        if pending:
            self.linkdb.assert_links(pending)

    def batch_done(self) -> None:
        self.flush_pending()
        self.linkdb.commit()
        if self._audit is not None:
            # seal the batch's audit entries for the background flusher
            # (write-behind: the persist phase never waits on the file)
            self._audit.flush()


class ServiceMatchListener(MatchListener):
    """The workload listener: forwards to the link DB (unless disabled for
    http-transform) and accumulates per-entity matches for the transform
    response (``duke_links``)."""

    def __init__(self, workload_name: str, linkdb: LinkDatabase,
                 kind: str = "deduplication", one_to_one: bool = False,
                 record_resolver=None):
        self._wrapped = LinkMatchListener(
            linkdb, audit_context=(kind, workload_name)
        )
        self.link_database_updates_disabled = False
        self._entity_matches: Dict[str, List[Tuple[Record, float]]] = {}
        # one-to-one enforcement (opt-in): the reference parses
        # link-mode="one-to-one" but never reads the flag (SURVEY.md quirk
        # Q5), so by default every above-threshold pair links.  With
        # ``one_to_one`` definite matches are buffered per batch and
        # resolved by descending confidence with displacement repair (see
        # _flush_one_to_one) so each record links to at most one
        # counterpart; maybe-matches pass through.
        self.one_to_one = one_to_one
        self._pending_matches: List[Tuple[float, Record, Record]] = []
        # runner-up pairs kept across recent batches so a record displaced
        # by a stronger later link can fall back to its next-best candidate
        # (deferred-acceptance repair); capped per record and pruned by
        # batch age.  Entries carry the batch number they were seen in;
        # ``record_resolver`` (id -> live Record or None, wired to the
        # index by the workload) re-validates both endpoints at replay so
        # deleted/re-indexed records are never resurrected from stale pairs.
        self._alternatives: Dict[str, List[Tuple[float, Record, Record]]] = {}
        self._alt_batch: Dict[str, int] = {}
        self._batch_no = 0
        self._record_resolver = record_resolver
        self._maybe_seen: set = set()
        prefix = (
            "recordLinkageMatchListener" if kind == "recordlinkage"
            else "deduplicationMatchListener"
        )
        self.logger = logging.getLogger(f"{prefix}-{workload_name}")
        self._batch_start: Optional[float] = None

    def set_link_database_updates_disabled(self, disabled: bool) -> None:
        self.link_database_updates_disabled = disabled

    def batch_ready(self, size: int) -> None:
        self._entity_matches = {}
        self._pending_matches = []
        self._maybe_seen = set()
        self._batch_start = time.monotonic()
        self.logger.info("batchReady(size=%d)", size)
        if not self.link_database_updates_disabled:
            self._wrapped.batch_ready(size)

    def batch_done(self) -> None:
        if self.one_to_one:
            if not self.link_database_updates_disabled:
                # maybe-matches passed straight through during scoring and
                # sit in the wrapped listener's batch buffer; hand them to
                # the DB before the flush's conflict prefetch reads link
                # state, matching the legacy immediate-write visibility
                self._wrapped.flush_pending()
            self._flush_one_to_one()
        if not self.link_database_updates_disabled:
            self._wrapped.batch_done()
        if self._batch_start is not None:
            self.logger.info(
                "batchDone() batchElapsedTime: %s seconds.",
                time.monotonic() - self._batch_start,
            )

    # runner-up pairs remembered per record for displacement repair, and
    # how many batches they stay replayable (bounds both memory and the
    # staleness of a replayed pair's confidence)
    _ALTERNATIVE_CAP = 8
    _ALTERNATIVE_MAX_AGE = 32

    def _flush_one_to_one(self) -> None:
        """Max-confidence one-to-one assignment with displacement repair.

        Pairs are resolved in descending confidence order — within the
        batch AND against links asserted by earlier batches (one batched
        link fetch; a stronger new pair retracts the weaker existing link,
        a weaker one is suppressed).  When an existing link is retracted,
        its displaced endpoint re-enters the queue with its remembered
        runner-up candidates (deferred-acceptance style), so displacement
        chains settle instead of stranding records.  Ties break on record
        ids so the output is deterministic under threaded scoring.

        Event-protocol note: a record whose every buffered definite match
        is suppressed here gets an explicit ``no_match_for`` at the end of
        the flush (unless it produced a maybe-match), keeping the listener
        contract's "every processed record emits some event" property.
        """
        import heapq

        pending = self._pending_matches
        self._pending_matches = []
        batch_queries: Dict[str, Record] = {
            t[1].record_id: t[1] for t in pending
        }

        transform = self.link_database_updates_disabled
        self._batch_no += 1
        if self._batch_no % self._ALTERNATIVE_MAX_AGE == 0:
            self._prune_alternatives()
        links_by_id: Dict[str, List[Link]] = {}
        # ids whose links are COMPLETE in links_by_id (the batched fetch
        # also surfaces links of out-of-batch endpoints — those entries are
        # partial and must not suppress the lazy per-record fetch)
        fetched: set = set()
        if not transform and pending:
            ids = {t[1].record_id for t in pending} | {
                t[2].record_id for t in pending
            }
            # seed every id so unlinked records (the steady-state common
            # case) don't fall through to per-record lazy DB lookups
            links_by_id = {rid: [] for rid in ids}
            fetched = set(ids)
            for link in self._wrapped.linkdb.get_links_for_ids(ids):
                links_by_id.setdefault(link.id1, []).append(link)
                links_by_id.setdefault(link.id2, []).append(link)

        # heap orders by (-confidence, ids, tie-counter); the counter makes
        # every entry totally ordered BEFORE comparison could reach the
        # Record payloads (Record has __eq__ but no __lt__ — a tie on the
        # string keys would otherwise raise TypeError); seen_pairs guards
        # against the same pair re-entering via both endpoints' alternative
        # lists
        tie = iter(range(1 << 62))
        heap: List[tuple] = [
            (-conf, r1.record_id, r2.record_id, next(tie), r1, r2)
            for conf, r1, r2 in pending
        ]
        heapq.heapify(heap)
        seen_pairs: set = set()
        taken: set = set()

        while heap:
            negconf, id1, id2, _, r1, r2 = heapq.heappop(heap)
            confidence = -negconf
            pkey = tuple(sorted((id1, id2)))
            if pkey in seen_pairs:
                continue
            seen_pairs.add(pkey)
            if id1 in taken or id2 in taken:
                self._remember_alternative(confidence, r1, r2)
                continue
            if not transform:
                blocked, to_retract = self._existing_conflicts(
                    links_by_id, fetched, id1, id2, confidence
                )
                if blocked:
                    self._remember_alternative(confidence, r1, r2)
                    continue
                for link in to_retract:
                    link.retract()
                    self._wrapped.linkdb.assert_link(link)
                    for rid in (link.id1, link.id2):
                        peers = links_by_id.get(rid)
                        if peers and link in peers:
                            peers.remove(link)
                    # the displaced endpoint re-competes with its
                    # remembered runner-ups; both endpoints of a replayed
                    # pair must still resolve to live records (a stale
                    # pair must never resurrect a deleted/re-indexed id)
                    displaced = link.id2 if link.id1 in (id1, id2) else link.id1
                    for alt_conf, a1, a2 in self._alternatives.get(
                        displaced, ()
                    ):
                        akey = tuple(sorted((a1.record_id, a2.record_id)))
                        if akey in seen_pairs:
                            continue
                        if not self._replay_live(a1, a2):
                            continue
                        heapq.heappush(
                            heap,
                            (-alt_conf, a1.record_id, a2.record_id,
                             next(tie), a1, a2),
                        )
                self._wrapped.matches(r1, r2, confidence)
                new = Link(id1, id2, LinkStatus.INFERRED,
                           LinkKind.DUPLICATE, confidence)
                links_by_id.setdefault(id1, []).append(new)
                links_by_id.setdefault(id2, []).append(new)
            taken.add(id1)
            taken.add(id2)
            self._record_entity_match(r1, r2, confidence)

        # ADVICE drift fix: suppressed-everywhere batch records still end
        # the batch with an event
        for rid, record in batch_queries.items():
            if rid not in taken and rid not in self._maybe_seen:
                self.no_match_for(record)

    def _remember_alternative(self, confidence: float, r1: Record,
                              r2: Record) -> None:
        # transform-mode pairs are transient probe queries — they must
        # never become assertable link material in a later real batch
        if self.link_database_updates_disabled:
            return
        pair = tuple(sorted((r1.record_id, r2.record_id)))
        for rid in (r1.record_id, r2.record_id):
            alts = self._alternatives.setdefault(rid, [])
            # one slot per pair: a repeatedly-suppressed pair must not
            # fill the cap with copies and evict distinct runner-ups
            alts[:] = [
                t for t in alts
                if tuple(sorted((t[1].record_id, t[2].record_id))) != pair
            ]
            alts.append((confidence, r1, r2))
            self._alt_batch[rid] = self._batch_no
            if len(alts) > self._ALTERNATIVE_CAP:
                alts.sort(key=lambda t: (-t[0], t[1].record_id,
                                         t[2].record_id))
                del alts[self._ALTERNATIVE_CAP:]

    def _replay_live(self, r1: Record, r2: Record) -> bool:
        """Both endpoints of a remembered pair still resolve to live
        records WITH the remembered content.  A re-indexed record
        invalidates its remembered pairs — their confidences were computed
        from the old values.  Fail closed when no resolver is wired: a
        listener constructed without one (any embedder bypassing
        build_workload) must not re-assert links from batch-old remembered
        confidences for records that may have been re-indexed or deleted
        since (displacement repair degrades gracefully; correctness wins)."""
        if self._record_resolver is None:
            return False
        for rec in (r1, r2):
            live = self._record_resolver(rec.record_id)
            if live is None or live.is_deleted() or live != rec:
                return False
        return True

    def _prune_alternatives(self) -> None:
        cutoff = self._batch_no - self._ALTERNATIVE_MAX_AGE
        stale = [rid for rid, b in self._alt_batch.items() if b <= cutoff]
        for rid in stale:
            self._alt_batch.pop(rid, None)
            self._alternatives.pop(rid, None)

    def _existing_conflicts(self, links_by_id: Dict[str, List[Link]],
                            fetched: set, id1: str, id2: str,
                            confidence: float):
        """Definite links from earlier batches touching either record.

        Returns (blocked, to_retract): blocked when an existing link with
        >= confidence already claims one of the records; otherwise the
        weaker existing links to retract before asserting the new pair.
        ``fetched`` is the set of ids whose links are COMPLETE in
        ``links_by_id`` (the batched prefetch also creates partial entries
        for out-of-batch endpoints of fetched links — completeness, not
        mere presence, decides whether the lazy per-record fetch runs).
        """
        pair = {id1, id2}
        blocked = False
        to_retract = []
        for rid in pair:
            if rid not in fetched:
                fetched.add(rid)
                known = links_by_id.setdefault(rid, [])
                keys = {l.key() for l in known}
                for link in self._wrapped.linkdb.get_all_links_for(rid):
                    if link.key() not in keys:
                        known.append(link)
            for link in links_by_id[rid]:
                if link.kind != LinkKind.DUPLICATE:
                    continue
                if link.status == LinkStatus.RETRACTED:
                    continue
                if {link.id1, link.id2} == pair:
                    continue  # same pair: plain re-assert
                if link.confidence >= confidence:
                    blocked = True
                else:
                    to_retract.append(link)
        return blocked, to_retract

    def matches(self, r1: Record, r2: Record, confidence: float) -> None:
        if self.one_to_one:
            self._pending_matches.append((confidence, r1, r2))
            return
        if not self.link_database_updates_disabled:
            self._wrapped.matches(r1, r2, confidence)
        self._record_entity_match(r1, r2, confidence)

    def matches_perhaps(self, r1: Record, r2: Record, confidence: float) -> None:
        if self.one_to_one:
            self._maybe_seen.add(r1.record_id)
        if not self.link_database_updates_disabled:
            self._wrapped.matches_perhaps(r1, r2, confidence)
        self._record_entity_match(r1, r2, confidence)

    def no_match_for(self, record: Record) -> None:
        if not self.link_database_updates_disabled:
            self._wrapped.no_match_for(record)

    def start_processing(self) -> None:
        if not self.link_database_updates_disabled:
            self._wrapped.start_processing()

    def end_processing(self) -> None:
        if not self.link_database_updates_disabled:
            self._wrapped.end_processing()

    def _record_entity_match(self, r1: Record, r2: Record, confidence: float) -> None:
        entity_id = r1.get_value(ORIGINAL_ENTITY_ID_PROPERTY_NAME)
        self._entity_matches.setdefault(entity_id, []).append((r2, confidence))

    def get_links_for_entity(self, entity_id: str) -> List[dict]:
        """duke_links rows for one input entity
        (BaseLinkDatabaseMatchListener.java:115-136)."""
        out = []
        for record, confidence in self._entity_matches.get(entity_id, []):
            out.append(
                {
                    "datasetId": record.get_value(DATASET_ID_PROPERTY_NAME),
                    "entityId": record.get_value(ORIGINAL_ENTITY_ID_PROPERTY_NAME),
                    "confidence": confidence,
                }
            )
        return out
