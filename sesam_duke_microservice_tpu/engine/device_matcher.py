"""The TPU-native matching backend: device-resident corpus + batched scoring.

Replaces the reference hot path (per-record Lucene candidate query + per-pair
scalar comparator dispatch — SURVEY.md section 3.2, hot loops 1-2) with one
XLA program per query block: the whole corpus lives on device as padded
feature tensors (``ops.features``), a jitted blockwise scorer
(``ops.scoring.build_corpus_scorer``) scores every query against every
corpus row in chunks keeping a running top-K, and the host only finalizes
the surviving K pairs per query.

Semantics contract (held to the host engine by differential tests in
``tests/test_device_matcher.py``):

  * exact brute-force blocking — candidates are a superset of anything
    Lucene retrieves, so recall can only improve (SURVEY.md section 7
    "blocking recall parity");
  * the match/maybe/no-match events equal the host ``engine.processor``'s
    for every pair whose probability clears ``min(threshold,
    maybe_threshold)``: device logits are exact for device-kernel
    properties, and host-only comparators are re-scored exactly for the
    surviving pairs (optimistic-bound pruning, ``ops.scoring``);
  * multi-valued properties score all value pairs on device: the value
    axis auto-sizes to the data (``_maybe_grow_value_slots``, capped by
    ``DEVICE_VALUE_SLOTS_MAX``), so a record whose second value is the
    matching one is pruned identically to the host engine;
  * K-escalation keeps this exact: if any query had more potential
    candidates than K, the scorer re-runs with doubled K until all fit.

Mutation model (vs Lucene's delete-then-readd,
IncrementalLuceneDatabase.java:507-517): the corpus is append-only with
tombstone masks.  Re-indexing an ID tombstones the old row and appends a new
one; ``dukeDeleted`` records stay resolvable by id (the GET feed needs them,
App.java:854-855) but carry a deleted mask bit that excludes them from
candidate scoring (IncrementalLuceneDatabase.java:478).  Capacity grows by
doubling in multiples of the scan chunk, so the jitted scorer recompiles
only O(log N) times over a corpus's lifetime.
"""

from __future__ import annotations

import contextlib
import logging
import os
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import telemetry
from ..core.config import DukeSchema, MatchTunables
from ..core.records import GROUP_NO_PROPERTY_NAME, Record, SchemaError
from ..index.base import CandidateIndex
from ..ops import features as F
from ..ops.features import CHARS as _F_CHARS, CHARS_WEIGHTED as _F_CHARS_W
from ..telemetry import costs, tracing
from ..telemetry.env import env_flag, env_int, env_int_tuple, env_str
from .scheduler import DEFAULT_QUERY_BUCKETS
from ..utils.jit_cache import record_cache_hit, record_compile
from .listeners import MatchListener
from .processor import (
    PHASE_ENCODE,
    PHASE_PERSIST,
    PHASE_RETRIEVE,
    PHASE_SCORE,
    PhaseRecorder,
    ProfileStats,
)

logger = logging.getLogger("device-matcher")

# Query blocks are bucketed to these sizes so batch-size jitter does not
# recompile the scorer (static shapes; SURVEY.md section 7 hard part 2).
# Env-tunable so the CPU test backend can use small shapes; TPU defaults
# are sized for the MXU/VPU (DEVICE_CHUNK rows of corpus per scan step).
# Measured on v5e (20k corpus): chunk 8192 + bucket 1024 runs the scorer at
# ~38M exact pairs/s vs ~16M at chunk 512 + bucket 256 — the scan-step
# fixed costs (top-K merge, kernel dispatch) amortize over 16x more rows
# and 4x more queries per step.  r3: the ladder extends to 4096-query
# blocks — an 8192-query batch runs 86.7M pairs/s end-to-end at bucket
# 4096 vs 67.8M at 1024 (per-block dispatch/fetch overhead halves twice);
# intermediate 2048 keeps mid-size batches from over-padding.
_QUERY_BUCKETS = env_int_tuple(
    "DEVICE_QUERY_BUCKETS", DEFAULT_QUERY_BUCKETS
)
_CHUNK = env_int("DEVICE_CHUNK", 8192)
# Incremental device-update slices bucket independently of the scan chunk:
# a steady-state commit of a few hundred rows must not pay a chunk-sized
# (8192-row) transfer.
_UPDATE_SLICE = env_int("DEVICE_UPDATE_SLICE", 512)
# Pre-sized corpus capacity (rows) for deployments that know their corpus
# scale: capacity-doubling growth transiently needs old + new tensors
# resident, so a corpus near half of HBM cannot double its way up (e.g.
# 10M rows would try to allocate a 16.8M-row copy).  Pre-sizing allocates
# once at the target and never grows through the danger zone.
_INITIAL_CAPACITY = env_int("DEVICE_INITIAL_CAPACITY", 0)
_INITIAL_TOP_K = env_int("DEVICE_TOP_K", 64)
# Value-slot auto-growth cap: pair scoring is O(V^2) combos per property, so
# the per-property value axis stops doubling here; records with more values
# score their first MAX slots on device (host finalization still sees every
# value, so only *pruning* can be affected beyond the cap).
_VALUE_SLOTS_MAX = env_int("DEVICE_VALUE_SLOTS_MAX", 8)
# Per-property char-width auto-growth (CHARS-kind properties): when
# DEVICE_MAX_CHARS is NOT pinned, each property's char tensors start at
# the 32-char Myers width and double to fit the data — so ONE long-text
# field (a description, an abstract) widens only its own tensors while
# the other properties stay on the fast single-word path.  Past
# DEVICE_DEMOTE_CHARS (default = MYERS_MAX_CHARS, the Pallas kernel
# ceiling) the property DEMOTES to the host-scored path instead: the
# device keeps pruning on the remaining short properties with the
# demoted property's maximum contribution folded into the optimistic
# bound (ops.scoring.host_bound_logit), and survivors host-finalize
# exactly — one 1000-char field costs host work per SURVIVOR instead of
# dragging every corpus pair onto the ~86K pairs/s scan-DP kernel.
# DEVICE_DEMOTE_CHARS=0 disables demotion; widths then grow to
# DEVICE_MAX_CHARS_CAP and truncate beyond it.
_CHARS_CAP = env_int("DEVICE_MAX_CHARS_CAP", 1024)
_DEMOTE_CHARS = env_int("DEVICE_DEMOTE_CHARS", 256)


def query_buckets() -> tuple:
    """The query-padding ladder (public: the ingest scheduler coalesces
    cross-request microbatches toward these boundaries so device launches
    ride already-compiled shapes with minimal padding)."""
    return _QUERY_BUCKETS


def bucket_for(n: int) -> int:
    """Padded query-block size for an ``n``-record batch."""
    for b in _QUERY_BUCKETS:
        if n <= b:
            return b
    return _QUERY_BUCKETS[-1]


# Pre-resolved registry children (dukecheck DK501/DK502): the padding
# ladder is a closed set, so per-bucket children resolve once at import
# and the scoring path writes plain single-writer child counters with no
# family-lock lookup or key-tuple allocation per block.
_BUCKET_CHILDREN = {
    b: (telemetry.QUERY_BLOCKS.labels(bucket=str(b)),  # dukecheck: ignore[DK501] init-time pre-resolution
        telemetry.QUERY_PAD_ROWS.labels(bucket=str(b)))  # dukecheck: ignore[DK501] init-time pre-resolution
    for b in _QUERY_BUCKETS
}
_STREAM_SLICES_CHILD = telemetry.STREAM_APPEND_SLICES.single()


def _stream_append_slice(n: int) -> Optional[int]:
    """Slice size for the streamed extract→upload append, or None for the
    whole-batch path (small batches have nothing to overlap).

    ``DUKE_STREAM_APPEND=0`` pins the legacy whole-batch behavior.  When
    the full batch qualifies for the shared-memory parallel extractor,
    slices grow to its minimum slab so every slice still rides the
    process pool — the overlap must never cost the fan-out.
    """
    if not env_flag("DUKE_STREAM_APPEND", True):
        return None
    slice_n = _UPDATE_SLICE
    from ..ops import parallel_extract as PX

    if PX.enabled(n):
        slice_n = max(slice_n, PX.min_records())
    return slice_n if n > slice_n else None


class DeviceCorpus:
    """Host mirror + device tensors for one workload's indexed records.

    Numpy arrays are the durable host mirror (rebuildable source of truth is
    the record store); device arrays are refreshed lazily per commit.  Rows
    are append-only; ``row_valid`` clears on tombstone.
    """

    def __init__(self, plan, values_per_record: int):
        self.plan = plan
        self.v = values_per_record
        # capacity growth granule: scan-chunk multiples; the sharded
        # corpus raises this to mesh.size * chunk so every shard always
        # holds whole chunks
        self.granule = _CHUNK
        self.capacity = 0
        self.size = 0
        # incremental live-row count (row_valid & ~row_deleted), maintained
        # by append/tombstone: per-batch O(capacity) mask scans to compute
        # it (plus the boolean fancy-index allocation) were measurable at
        # 10M rows.  External mask mutators must recompute it
        # (snapshot_load does), same contract as _dirty_masks.
        self.live_rows = 0
        self.feats: Dict[str, Dict[str, np.ndarray]] = {}
        self.row_valid = np.zeros((0,), dtype=bool)
        self.row_deleted = np.zeros((0,), dtype=bool)
        self.row_group = np.full((0,), -1, dtype=np.int32)
        self.row_ids: List[Optional[str]] = []
        self._device = None           # cached jnp feature mirrors
        self._dirty_full = True       # capacity changed -> full re-upload
        # masks: _dirty_masks forces a FULL (cap,)-sized refresh (growth,
        # snapshot restore, external mutation); steady-state commits ride
        # the incremental trackers instead — at the 10M flagship scale a
        # wholesale mask refresh is ~60 MB over the device link PER
        # COMMIT (r5 measured it dominating the serve batch), while the
        # appended-slice + tombstone-scatter updates are O(batch)
        self._dirty_masks = True
        self._pending_update: Optional[Tuple[int, int]] = None  # appended rows
        self._mask_slice: Optional[Tuple[int, int]] = None  # appended masks
        self._mask_rows: List[int] = []                     # tombstones
        self._mask_device = None
        # serializes device_arrays between the restart warm-upload thread
        # (DeviceIndex.warm_upload_async) and the scoring path; the
        # generation counter detects host-mirror mutations that land
        # while an upload is in flight (writers don't take the lock —
        # they run under the workload lock, which the warm thread is
        # outside of), forcing a re-run so cleared dirty flags can never
        # hide rows from the device copy
        self._upload_lock = threading.Lock()
        self._mutation_gen = 0
        # arena identity (ISSUE 19): the owning workload stamps its
        # kind/name label and a cost-ledger heat callable after build;
        # device_arrays admits through ops.arena under these before
        # every upload (no-ops under DUKE_ARENA=0)
        self.arena_label = ""
        self.arena_heat: Optional[object] = None

    # -- growth --------------------------------------------------------------

    def _target_capacity(self, needed: int) -> int:
        """Doubling growth in ``self.granule`` multiples (one copy of the
        growth policy for the single-device and sharded corpora)."""
        g = self.granule
        cap = max(self.capacity, g)
        if _INITIAL_CAPACITY > 0:
            cap = max(cap, -(-_INITIAL_CAPACITY // g) * g)
        while cap < needed:
            cap *= 2
        return cap

    def _grow(self, needed: int) -> None:
        cap = self._target_capacity(needed)
        if cap == self.capacity:
            return
        if self.capacity > 0:
            # a doubling of an existing corpus: the next device_arrays
            # call re-uploads everything (observability: capacity events
            # explain latency spikes and justify DEVICE_INITIAL_CAPACITY)
            telemetry.CORPUS_GROWTHS.inc()  # dukecheck: ignore[DK502] rare event: capacity doubling, not per-record
        self.row_valid = _grow_1d(self.row_valid, cap, False)
        self.row_deleted = _grow_1d(self.row_deleted, cap, False)
        self.row_group = _grow_1d(self.row_group, cap, -1)
        for prop, tensors in self.feats.items():
            self.feats[prop] = {
                name: _grow_nd(arr, cap) for name, arr in tensors.items()
            }
        self.capacity = cap
        self._dirty_full = True
        self._dirty_masks = True

    def append(self, feats: Dict[str, Dict[str, np.ndarray]],
               deleted: np.ndarray, group: np.ndarray,
               ids: Sequence[str]) -> np.ndarray:
        """Append N rows; returns their row indices."""
        n = len(ids)
        if n == 0:
            return np.zeros((0,), dtype=np.int64)
        if not self.feats:
            # first append defines per-property tensor shapes
            self.feats = {
                prop: {
                    name: np.zeros((0,) + arr.shape[1:], dtype=arr.dtype)
                    for name, arr in tensors.items()
                }
                for prop, tensors in feats.items()
            }
        self._grow(self.size + n)
        rows = np.arange(self.size, self.size + n)
        # appended rows are contiguous: slice assignment is a straight
        # memcpy, where fancy indexing with the arange pays an index path
        lo, hi = self.size, self.size + n
        for prop, tensors in feats.items():
            for name, arr in tensors.items():
                self.feats[prop][name][lo:hi] = arr
        self.row_valid[lo:hi] = True
        self.row_deleted[lo:hi] = deleted
        self.row_group[lo:hi] = group
        self.row_ids.extend(ids)
        self.live_rows += int(n - np.asarray(deleted, dtype=bool).sum())
        old_size, self.size = self.size, self.size + n
        self._mutation_gen += 1
        if not self._dirty_full:
            # track the appended range for an incremental device update;
            # merge with a prior un-flushed range (always contiguous)
            if self._pending_update is None:
                self._pending_update = (old_size, n)
            else:
                s, c = self._pending_update
                self._pending_update = (s, old_size + n - s)
            if self._mask_slice is None:
                self._mask_slice = (old_size, n)
            else:
                s, c = self._mask_slice
                self._mask_slice = (s, old_size + n - s)
        return rows

    def tombstone(self, row: int) -> None:
        if self.row_valid[row] and not self.row_deleted[row]:
            self.live_rows -= 1
        self.row_valid[row] = False
        self._mask_rows.append(int(row))
        self._mutation_gen += 1

    def reserve(self, total_rows: int) -> None:
        """Pre-grow capacity to fit ``total_rows`` ahead of a sliced
        append: a capacity doubling mid-stream would set ``_dirty_full``
        and turn every remaining slice flush into a no-op (the whole
        corpus re-uploads at scoring time instead).  No-op before the
        first append — tensor shapes are defined by the first batch."""
        if self.feats and total_rows > self.capacity:
            self._grow(total_rows)

    def stream_flush(self) -> bool:
        """Streaming-append overlap: enqueue the incremental device-mirror
        update for the rows appended so far.  JAX dispatch is
        asynchronous, so this returns once the jitted tree-update is
        enqueued — the HBM copy of slice N proceeds while the host
        extracts slice N+1 (engine.DeviceIndex._append_rows_only).

        No-op (returns False) while a full upload is pending (cold
        corpus, capacity growth, restored snapshot): re-running the
        whole-corpus upload per slice would multiply the transfer, and
        the scoring-time ``device_arrays()`` pays it exactly once
        instead.  The racy unlocked flag read is writer-side only — the
        appending thread is the one calling this, and a concurrent
        warm-upload thread is serialized by the upload lock inside
        ``device_arrays``.
        """
        if self._device is None or self._dirty_full:
            return False
        self.device_arrays()
        return True

    # -- device mirror -------------------------------------------------------

    def _device_nbytes(self) -> int:
        """Device-mirror footprint: the host mirrors' nbytes (the device
        copies share shapes and dtypes, so the host sum IS the device
        cost).  Lock-free torn reads tolerated — the arena re-admits at
        the settled size on the next call."""
        total = 0
        for tensors in list(self.feats.values()):
            for arr in list(tensors.values()):
                total += int(arr.nbytes)
        for arr in (self.row_valid, self.row_deleted, self.row_group):
            total += int(arr.nbytes)
        return total

    def spill_device(self) -> int:
        """Drop the device mirrors to the host tier (arena eviction).

        Takes the upload lock — the arena's lock is OUTER to it (lock
        order in ops.arena), so a spill waits out any in-flight upload.
        The numpy host mirrors stay authoritative; the owner's next
        query re-admits and faults the corpus back in through the
        normal dirty-full upload.  Returns the freed byte estimate."""
        with self._upload_lock:
            freed = self._device_nbytes() if self._device is not None else 0
            self._device = None
            self._mask_device = None
            self._dirty_full = True
            self._dirty_masks = True
            self._pending_update = None
            self._mask_slice = None
            self._mask_rows = []
            self._mutation_gen += 1
            return freed

    def _place(self, arr: np.ndarray):
        """Host array -> device array; the sharded corpus overrides with
        record-axis-sharded placement over its mesh."""
        import jax.numpy as jnp

        return jnp.asarray(arr)

    def _updater(self):
        """The jitted whole-tree incremental updater to use; the sharded
        corpus overrides with a sharding-constrained variant."""
        return _tree_updater()

    def device_arrays(self):
        """(feats, valid, deleted, group) as device arrays.

        Steady-state incremental batches update the device copy in place
        (one ``dynamic_update_slice`` per feature tensor, O(batch) transfer)
        instead of re-uploading the whole corpus; a full upload happens only
        on capacity growth.  The three mask arrays are ALSO incremental
        (r5): appended ranges ride a slice update and tombstones a
        bucketed scatter — at the 10M flagship scale a wholesale mask
        refresh is ~60 MB over the device link per commit, which
        dominated the serve batch.  External code that mutates
        ``row_valid``/``row_deleted`` outside ``append``/``tombstone``
        MUST set ``_dirty_masks = True`` (snapshot_load does).

        Residency is leased from the shared arena FIRST (ISSUE 19):
        admission may spill colder tenants' mirrors and raises
        ``ops.arena.ArenaAdmissionError`` — surfaced as a 503, never an
        allocator OOM — when the budget cannot fit this corpus.  The
        admit call stays OUTSIDE the upload lock (arena lock is outer).
        """
        from ..ops.arena import ARENA

        ARENA.admit(self, self._device_nbytes(), spill=self.spill_device,
                    label=self.arena_label, heat=self.arena_heat)
        with self._upload_lock:
            while True:
                gen = self._mutation_gen
                out = self._device_arrays_locked()
                if gen == self._mutation_gen:
                    return out
                # a writer mutated the host mirror mid-upload (possible
                # only vs the background warm thread): the flags it set
                # were consumed against possibly-torn reads — redo; the
                # second pass is incremental and cheap

    def _bucketed_slice(self, start: int, count: int) -> Tuple[int, int]:
        """ONE copy of the update-slice bucketing policy (features and
        masks): pow2 lengths from ``_UPDATE_SLICE`` to limit updater
        recompiles, clamped into the capacity."""
        bucket = _UPDATE_SLICE
        while bucket < count:
            bucket *= 2
        bucket = min(bucket, self.capacity)
        return min(start, self.capacity - bucket), bucket

    def _device_arrays_locked(self):
        # DETACH-then-consume everywhere below: trackers are swapped out
        # before any host array is read, so a writer racing the
        # background warm thread lands its entry in a FRESH tracker (and
        # bumps _mutation_gen) — the retry loop in device_arrays then
        # applies it, instead of a post-read clear() silently eating it.
        if self._device is None or self._dirty_full:
            telemetry.CORPUS_FULL_UPLOADS.inc()  # dukecheck: ignore[DK502] rare event: growth/restore re-upload
            self._device = {
                prop: {name: self._place(arr) for name, arr in tensors.items()}
                for prop, tensors in self.feats.items()
            }
            self._pending_update = None
            self._dirty_full = False
        elif self._pending_update is not None:
            (start, count), self._pending_update = self._pending_update, None
            start, bucket = self._bucketed_slice(start, count)
            # ONE jitted call updates the whole tree (donated buffers):
            # per-tensor dispatch would pay the device-link round-trip
            # once per tensor per commit.  (The mask slice below is a
            # second dispatch covering the same range; folding masks into
            # this tree would save it, at the cost of merging the mask
            # and feature mirrors' storage — noted, not yet taken.)
            upd = {
                prop: {
                    name: arr[start:start + bucket]
                    for name, arr in tensors.items()
                }
                for prop, tensors in self.feats.items()
            }
            self._device = self._updater()(
                self._device, upd, np.int32(start)
            )
        # masks: full refresh only when forced (growth/restore/external
        # mutation) or when the scattered-row set got so large the
        # wholesale upload is cheaper; otherwise O(batch) updates
        if (
            self._mask_device is None
            or self._dirty_masks
            or len(self._mask_rows) > max(4096, self.capacity >> 4)
        ):
            self._mask_slice = None
            self._mask_rows = []
            self._dirty_masks = False
            self._mask_device = (
                self._place(self.row_valid),
                self._place(self.row_deleted),
                self._place(self.row_group),
            )
        else:
            if self._mask_slice is not None:
                (start, count), self._mask_slice = self._mask_slice, None
                start, bucket = self._bucketed_slice(start, count)
                self._mask_device = self._mask_updater()(
                    self._mask_device,
                    (self.row_valid[start:start + bucket],
                     self.row_deleted[start:start + bucket],
                     self.row_group[start:start + bucket]),
                    np.int32(start),
                )
            if self._mask_rows:
                rows, self._mask_rows = self._mask_rows, []
                # bucketed scatter: every update SETS the host mirror's
                # current value, so duplicate/padded indices and any
                # ordering vs the slice update are idempotent
                idx = np.asarray(rows, dtype=np.int32)
                bucket = 256
                while bucket < idx.size:
                    bucket *= 2
                pad = np.full(bucket - idx.size, idx[0], dtype=np.int32)
                idx = np.concatenate([idx, pad])
                self._mask_device = self._mask_scatter()(
                    self._mask_device, idx,
                    self.row_valid[idx], self.row_deleted[idx],
                )
        valid, deleted, group = self._mask_device
        return self._device, valid, deleted, group

    def _mask_updater(self):
        """Jitted mask-slice updater (the sharded corpus overrides with a
        sharding-constrained variant)."""
        return _mask_slice_updater()

    def _mask_scatter(self):
        """Jitted tombstone scatter (sharded corpus overrides)."""
        return _mask_scatter_updater()


_MASK_UPDATER = None
_MASK_SCATTER = None


def _mask_slice_updater():
    """One jitted call updating (valid, deleted, group) for a contiguous
    appended range — O(batch) transfer instead of O(capacity)."""
    global _MASK_UPDATER
    if _MASK_UPDATER is None:
        import jax
        from jax import lax

        _MASK_UPDATER = jax.jit(
            lambda masks, upd, start: tuple(
                lax.dynamic_update_slice_in_dim(m, u, start, axis=0)
                for m, u in zip(masks, upd)
            ),
            donate_argnums=(0,),
        )
    return _MASK_UPDATER


def _mask_scatter_updater():
    """One jitted call applying scattered tombstone/liveness updates at
    ``idx`` (group is immutable after append, so only valid/deleted)."""
    global _MASK_SCATTER
    if _MASK_SCATTER is None:
        import jax

        def scatter(masks, idx, vvals, dvals):
            valid, deleted, group = masks
            return (valid.at[idx].set(vvals),
                    deleted.at[idx].set(dvals), group)

        _MASK_SCATTER = jax.jit(scatter, donate_argnums=(0,))
    return _MASK_SCATTER


_TREE_UPDATER = None


def _tree_updater():
    """Jitted whole-tree row updater: one device dispatch per commit.

    ``start`` stays a traced scalar (one compile per tree-structure/shape
    combination, not per update position); donation lets XLA reuse every
    existing device buffer in place.
    """
    global _TREE_UPDATER
    if _TREE_UPDATER is None:
        import jax
        from jax import lax

        _TREE_UPDATER = jax.jit(
            lambda dev, upd, start: jax.tree_util.tree_map(
                lambda d, u: lax.dynamic_update_slice_in_dim(
                    d, u, start, axis=0
                ),
                dev, upd,
            ),
            donate_argnums=(0,),
        )
    return _TREE_UPDATER


def _records_content_hash(records_by_id: Dict[str, Record]) -> str:
    """Order-independent digest of record ids AND values (snapshot guard).

    XOR fold of the canonical per-record digests — the same formula the
    record store (store.records) and the index maintain INCREMENTALLY, so
    this full rehash is only the fallback for callers without a running
    hash (direct snapshot_load calls in tests)."""
    from ..store.records import EMPTY_CONTENT_HASH, record_digest, xor_fold

    acc = EMPTY_CONTENT_HASH
    for record in records_by_id.values():
        acc = xor_fold(acc, record_digest(record))
    return acc.hex()


def _grow_1d(arr: np.ndarray, cap: int, fill) -> np.ndarray:
    out = np.full((cap,), fill, dtype=arr.dtype)
    out[: arr.shape[0]] = arr
    return out


def _grow_nd(arr: np.ndarray, cap: int) -> np.ndarray:
    # grown rows are zero-filled, which is safe ONLY because they stay
    # row_valid=False until append() overwrites them — never read them
    # unmasked (sorted-set tensors would need SET_PAD fill otherwise)
    out = np.zeros((cap,) + arr.shape[1:], dtype=arr.dtype)
    out[: arr.shape[0]] = arr
    return out


class DeviceIndex(CandidateIndex):
    """``CandidateIndex`` backed by the device-resident corpus.

    Candidate retrieval through this interface is exact brute force (every
    live record whose optimistic device score clears ``min_relevance`` is a
    candidate) — but the fast path is ``DeviceProcessor.deduplicate``, which
    never materializes candidate Records and goes straight from the scorer's
    top-K to listener events.
    """

    def __init__(self, schema: DukeSchema, *,
                 tunables: Optional[MatchTunables] = None,
                 values_per_record: Optional[int] = None):
        from ..ops import features as F

        self.schema = schema
        self.tunables = tunables or MatchTunables()
        # Value slots auto-size from the data (Duke records are multi-valued;
        # a record whose *second* value is the matching one must still be
        # visible to device pruning).  An explicit ctor arg or
        # DEVICE_VALUE_SLOTS env pins the width instead.
        env_v = env_str("DEVICE_VALUE_SLOTS")
        self._auto_value_slots = values_per_record is None and env_v is None
        # char widths auto-grow per property unless the operator pinned a
        # global width (tests pin small shapes; long-text deployments let
        # the data size each property's tensors)
        self._auto_chars = env_str("DEVICE_MAX_CHARS") is None
        v = values_per_record or int(env_v or "1")
        self.plan = F.SchemaFeatures.plan(schema, values_per_record=v)
        if not self.plan.device_props:
            raise SchemaError(
                "the device backend needs at least one comparison property "
                "with a device kernel (all configured comparators are "
                "host-only); use the host backend for this schema"
            )
        self.corpus = self._make_corpus(self.plan, v)
        self.records: Dict[str, Record] = {}     # id -> live record
        # incremental content digest of ``records`` (same per-record
        # formula as the store's running hash): snapshot_save stamps THIS
        # side and snapshot_load compares the STORE side, so index/store
        # divergence (a store commit whose scoring pass failed) still
        # forces a replay — at O(1) instead of rehashing the corpus.
        # With a LAZY record mirror the incremental fold is impossible
        # (old contents are unobtainable once the store is updated), so
        # the workload instead stamps the store's hash after each fully
        # successful batch (mark_store_synced); a batch that failed
        # between the store write and the index commit leaves the stamp at
        # the pre-batch value, which no longer matches the store — replay.
        from ..store.records import EMPTY_CONTENT_HASH

        self._content_hash = EMPTY_CONTENT_HASH
        self._store_synced_hash: Optional[str] = None
        # multi-host mirror-consistency digest: a sha256 CHAIN over every
        # committed batch (record content + assigned row), maintained by
        # the shared commit() path so frontend and follower replicas fold
        # identically when — and only when — they applied the same
        # mutations in the same order with the same row layout.  Chained
        # (not XOR-folded) on purpose: a missed or doubled batch must
        # change the digest, not cancel out.  Compared frontend-vs-
        # follower after every multi-host commit (parallel.dispatch
        # digest handshake); orthogonal to _content_hash, which guards
        # snapshot/store staleness across restarts.
        self._mirror_digest = EMPTY_CONTENT_HASH
        # O(1) live count (non-dukeDeleted records) for /stats — counting
        # by iterating ``records`` would need the workload lock for the
        # whole scan (seconds at 10M rows)
        self.live_records = 0
        self.id_to_row: Dict[str, int] = {}
        self.indexing_disabled = False
        self._pending: List[Record] = []
        self._lock = threading.Lock()
        self._scorer_cache: Optional["_ScorerCache"] = None
        self._cap_warned: set = set()

    def _make_corpus(self, plan, values_per_record: int) -> DeviceCorpus:
        """Corpus factory (used at construction AND value-slot rebuild);
        the sharded index overrides with its mesh-placed corpus."""
        return DeviceCorpus(plan, values_per_record)

    @property
    def scorer_cache(self) -> "_ScorerCache":
        if self._scorer_cache is None:
            self._scorer_cache = _ScorerCache(self)
        return self._scorer_cache

    # -- CandidateIndex ------------------------------------------------------

    def index(self, record: Record) -> None:
        if self.indexing_disabled:
            return
        with self._lock:
            self._pending.append(record)

    def _extract(self, records: Sequence[Record], plan=None):
        """Feature extraction for a record batch; subclasses may add pseudo-
        properties (the ANN backend rides its embedding matrix in here).
        ``plan`` overrides the corpus plan for query-side extraction."""
        from ..ops import features as F

        return F.extract_batch(plan or self.plan, records)

    def _sized_slots(self, spec, records: Sequence[Record]) -> int:
        """Power-of-two value width fitting ``records`` for one property,
        clamped to DEVICE_VALUE_SLOTS_MAX (warns once per property when the
        clamp makes 9th+ values invisible to device pruning)."""
        need = max(
            (sum(1 for val in r.get_values(spec.name) if val)
             for r in records),
            default=0,
        )
        if need > _VALUE_SLOTS_MAX and spec.name not in self._cap_warned:
            self._cap_warned.add(spec.name)
            logger.warning(
                "property %r has records with %d values; device pruning "
                "sees the first %d (DEVICE_VALUE_SLOTS_MAX)",
                spec.name, need, _VALUE_SLOTS_MAX,
            )
        v = 1
        while v < need:
            v *= 2
        return max(1, min(v, _VALUE_SLOTS_MAX))

    def _query_plan(self, records: Sequence[Record]):
        """Plan for non-indexed query records (http-transform): the value
        axis is sized to the probe batch (power of two, capped) so a query's
        2nd+ values stay visible to pruning WITHOUT widening the corpus —
        scoring handles asymmetric Vq x Vc value combos."""
        from dataclasses import replace

        from ..ops import features as F

        specs = []
        for spec in self.plan.device_props:
            v = self._sized_slots(spec, records)
            specs.append(
                replace(spec, values_per_record=v) if v != spec.v else spec
            )
        return F.SchemaFeatures(
            device_props=specs, host_props=self.plan.host_props
        )

    def commit(self) -> None:
        with self._lock:
            pending, self._pending = self._pending, []
        if not pending:
            return
        # multi-host serving: the drained batch is exactly the corpus
        # mutation about to apply — broadcast it so follower replicas make
        # the identical mutation (parallel.dispatch invariant 1).  The key
        # is tagged by the dispatcher on the frontend only; followers and
        # single-process runs skip.
        from ..parallel import dispatch

        key = getattr(self, "_dispatch_key", None)
        d = dispatch.current() if key is not None else None
        if d is not None:
            # the trailing trace context makes the follower replay a
            # remote child span of this request's trace (ISSUE 2)
            d.broadcast(dispatch.with_trace_ctx(("commit", key, pending)))
        # once broadcast, a local failure leaves followers one commit
        # AHEAD (permanent mirror divergence) — latch before propagating
        with dispatch.latch_on_failure(
            d, "frontend commit failed after broadcast"
        ):
            # last write per ID wins within a batch (Duke re-index semantics)
            by_id: Dict[str, Record] = {}
            for r in pending:
                by_id[r.record_id] = r
            records = list(by_id.values())
            # capture pre-batch liveness BEFORE any value-slot rebuild: a
            # lazy rebuild streams record state from the STORE, which the
            # workload already updated with this batch — rows rebuilt from
            # it reflect the new state, so liveness read after the rebuild
            # would be wrong
            old_live = self._old_liveness(records)
            self._maybe_grow_value_slots(records)
            for r in records:
                old = self.id_to_row.get(r.record_id)
                if old is not None:
                    self.corpus.tombstone(old)
            self._append_records(records, old_live=old_live)
            self._fold_mirror_digest(records)
        # loud mirror verification (multi-host only): every follower just
        # replayed this exact batch through this exact code — compare the
        # resulting chained digests so an asymmetric failure (a swallowed
        # replay exception, OOM, nondeterminism) halts the job here
        # instead of hanging a later collective or finalizing wrong links
        if d is not None:
            d.verify_mirror_digest(key, self._mirror_digest)

    def _fold_mirror_digest(self, records: Sequence[Record]) -> None:
        """Chain one committed batch into the mirror-consistency digest:
        per record, its canonical content digest plus the corpus row it
        landed on (row layout is what the collective programs actually
        consume, so layout divergence must change the digest too)."""
        import hashlib
        import struct as _struct

        from ..store.records import record_digest

        h = hashlib.sha256(self._mirror_digest)
        for r in records:
            h.update(record_digest(r))
            h.update(_struct.pack(
                "<q", self.id_to_row.get(r.record_id, -1)
            ))
        self._mirror_digest = h.digest()

    def _append_chunk(self, records: Sequence[Record]) -> np.ndarray:
        """Extract + corpus append + row mapping for one contiguous chunk."""
        feats = self._extract(records)
        deleted = np.array([r.is_deleted() for r in records], dtype=bool)
        group = np.array(
            [int(r.get_value(GROUP_NO_PROPERTY_NAME) or -1) for r in records],
            dtype=np.int32,
        )
        ids = [r.record_id for r in records]
        rows = self.corpus.append(feats, deleted, group, ids)
        for r, row in zip(records, rows):
            self.id_to_row[r.record_id] = int(row)
        return rows

    def _append_rows_only(self, records: Sequence[Record]) -> np.ndarray:
        """Extract + corpus append + row mapping — no record-mirror, hash,
        or live-count updates (also the streaming rebuild path, where the
        record SET is unchanged).

        Batches past one update slice stream: the batch is appended in
        ``_UPDATE_SLICE``-bucketed slices (grown to the parallel-extract
        minimum when the slab qualifies for the process-pool fan-out, so
        slicing never forfeits it) and each slice's jitted device update
        is enqueued asynchronously while the NEXT slice extracts on host
        — the HBM copy hides under Python extraction instead of
        serializing after it at scoring time.  Host mirrors, dirty-range
        accounting, and row mapping advance per slice, so crash/snapshot
        consistency and the resulting host state are identical to the
        whole-batch path (held by tests/test_feature_cache.py).
        """
        n = len(records)
        slice_n = _stream_append_slice(n)
        if slice_n is None:
            return self._append_chunk(records)
        corpus = self.corpus
        # pre-grow once so no slice crosses a capacity doubling (growth
        # forces a full re-upload, which must not run per slice)
        corpus.reserve(corpus.size + n)
        if corpus._device is None or corpus._dirty_full:
            # nothing to overlap: a full upload is pending (cold corpus,
            # rebuild, growth, restored snapshot), so every slice flush
            # would no-op — keep the whole-batch slab (and its full-size
            # parallel-extract fan-out); scoring pays the one full upload
            # exactly as before this subsystem
            return self._append_chunk(records)
        out = np.empty((n,), dtype=np.int64)
        done = 0
        with tracing.span(
            "encode.stream_append",
            {"records": n, "slice": slice_n},
            annotate=True,
        ):
            while done < n:
                chunk = records[done:done + slice_n]
                rows = self._append_chunk(chunk)
                out[done:done + len(chunk)] = rows
                done += len(chunk)
                if corpus.stream_flush():
                    _STREAM_SLICES_CHILD.inc()
        return out

    def _old_liveness(self, records: Sequence[Record]) -> List[bool]:
        """Pre-batch liveness per record, from INDEX state (id_to_row +
        the old row's deleted mask) — never from a mirror read: a lazy
        mirror reads through to the store, which the workload already
        updated with the NEW values, and counting (or hash-folding) those
        as "old" silently corrupts the live count and the content digest."""
        corpus = self.corpus
        out = []
        for r in records:
            old_row = self.id_to_row.get(r.record_id)
            out.append(
                old_row is not None and not corpus.row_deleted[old_row]
            )
        return out

    def _append_records(self, records: Sequence[Record],
                        old_live: Optional[List[bool]] = None) -> None:
        from ..store.records import LazyRecordMap, record_digest, xor_fold

        if old_live is None:
            old_live = self._old_liveness(records)
        self._append_rows_only(records)
        lazy = isinstance(self.records, LazyRecordMap)
        delta = 0
        acc = self._content_hash
        for r, was_live in zip(records, old_live):
            delta += (0 if r.is_deleted() else 1) - (1 if was_live else 0)
            if not lazy:
                old = self.records.get(r.record_id)
                if old is not None:
                    acc = xor_fold(acc, record_digest(old))
                acc = xor_fold(acc, record_digest(r))
            self.records[r.record_id] = r
        # in lazy mode the incremental fold is impossible (the true old
        # content is gone — the store was updated first); snapshot
        # integrity rides the store-synced stamp instead (mark_store_synced)
        if not lazy:
            self._content_hash = acc
        # one publication per batch: lock-free /stats readers must never
        # observe a mid-append partial count
        self.live_records += delta

    # -- value-slot auto-sizing ----------------------------------------------

    def _chars_needed(self, spec, records: Sequence[Record]) -> int:
        from ..ops.features import char_units

        need = 0
        for r in records:
            for val in r.get_values(spec.name):
                # width in UTF-16 code units — the char-axis unit
                # (ops.features.CHAR_DTYPE); len() undercounts non-BMP
                if len(val) * 2 < need:
                    continue  # cannot beat the running max even if all
                              # chars were surrogate pairs
                n = char_units(val)
                if n > need:
                    need = n
        return need

    def _sized_chars(self, spec, need: int) -> int:
        """Power-of-two char width fitting ``need`` codepoints, at least
        the current width, clamped to DEVICE_MAX_CHARS_CAP (warns once
        per property on clamp)."""
        if need > _CHARS_CAP:
            key = f"chars:{spec.name}"
            if key not in self._cap_warned:
                self._cap_warned.add(key)
                logger.warning(
                    "property %r has a %d-char value; device pruning sees "
                    "the first %d chars (DEVICE_MAX_CHARS_CAP; host "
                    "finalization stays exact)", spec.name, need, _CHARS_CAP,
                )
        width = spec.chars
        while width < need and width < _CHARS_CAP:
            width *= 2
        return min(width, _CHARS_CAP)

    def _maybe_grow_value_slots(self, records: Sequence[Record]) -> None:
        """Grow per-property value slots AND char widths to fit the batch.

        Duke scores the max over *all* value pairs per property
        (IncrementalDataSource.java:69-73 feeds multi-values), and its
        comparators accept arbitrary-length strings
        (testdukeconfig.xml:25-42 puts no bound on property values); the
        device tensors bound both axes for static shapes, so when a batch
        arrives with more values — or longer values — than the current
        widths, the plan widens (power-of-two, capped) and the corpus
        tensors rebuild from the host-resident records.  Growth happens
        at most O(log max) times per axis per property, and widths are
        PER PROPERTY: one long-text field rides the wide (or scan-DP)
        kernels alone while short fields keep the one-word Myers path.
        """
        grew = False
        demote = []
        for spec in self.plan.device_props:
            if self._auto_value_slots:
                v = self._sized_slots(spec, records)
                if v > spec.values_per_record:
                    spec.values_per_record = v
                    grew = True
            if self._auto_chars and spec.kind in (_F_CHARS, _F_CHARS_W):
                need = self._chars_needed(spec, records)
                if _DEMOTE_CHARS and need > _DEMOTE_CHARS:
                    demote.append(spec)
                    continue
                width = self._sized_chars(spec, need)
                if width > spec.chars:
                    spec.max_chars = width
                    grew = True
        if demote and self._demote_to_host(demote):
            grew = True
        if grew:
            self._rebuild_corpus()

    def _demote_to_host(self, specs) -> bool:
        """Move long-text CHARS properties to the host-scored side (see
        the _DEMOTE_CHARS comment).  Never demotes the LAST device
        property — the scorer needs at least one (that one stays at the
        cap width, truncating).  Returns True when the plan changed."""
        changed = False
        keep_one = len(self.plan.device_props) - len(specs) < 1
        if keep_one:
            kept, specs = specs[0], specs[1:]  # first candidate stays
            width = self._sized_chars(kept, _CHARS_CAP)
            key = f"keep:{kept.name}"
            if key not in self._cap_warned:
                self._cap_warned.add(key)
                logger.warning(
                    "property %r is the only device-kernel property, so it "
                    "stays on device at width %d; longer values truncate "
                    "for pruning (host finalization stays exact)",
                    kept.name, width,
                )
            if width > kept.chars:
                kept.max_chars = width
                changed = True  # caller must rebuild the corpus tensors
        if not specs:
            return changed
        names = {s.name for s in specs}
        self.plan.device_props[:] = [
            s for s in self.plan.device_props if s.name not in names
        ]
        for prop in self.schema.comparison_properties():
            if prop.name in names:
                self.plan.host_props.append(prop)
        logger.warning(
            "long-text properties %s demoted to host scoring (values past "
            "DEVICE_DEMOTE_CHARS=%d; device pruning keeps the remaining "
            "properties with the demoted ones' max contribution in the "
            "optimistic bound)", sorted(names), _DEMOTE_CHARS,
        )
        # cached scorer builders snapshotted the old device_props list;
        # drop them (and the warm fingerprint) so the next dispatch
        # rebuilds from the updated plan
        cache = self._scorer_cache
        if cache is not None:
            cache._scorers.clear()
            cache._warmed = None
        return True

    def _rebuild_corpus(self) -> None:
        """Re-extract every stored record under the current feature plan.

        Holds the index lock for the whole swap so a concurrent ``delete``
        cannot land between the old-state capture and the replacement (its
        tombstone would otherwise be resurrected by the re-append).
        """
        from ..store.records import LazyRecordMap

        with self._lock:
            old_records = self.records
            lazy = isinstance(old_records, LazyRecordMap)
            self.corpus = self._make_corpus(
                self.plan, max((s.v for s in self.plan.device_props), default=1)
            )
            self.id_to_row = {}
            if old_records:
                logger.info(
                    "value-slot growth: rebuilding corpus tensors for %d "
                    "records (slots now %s)%s", len(old_records),
                    {s.name: s.v for s in self.plan.device_props},
                    " — streaming from the store" if lazy else "",
                )
            if lazy:
                # stream the store in bounded batches (values() decodes
                # through the capped LRU): a 10M-row lazy corpus must not
                # materialize ~60 GB of Records for a rebuild.  The record
                # set, live count, and content stamp are all unchanged —
                # only the feature tensors re-extract.
                batch: List[Record] = []
                for record in old_records.values():
                    batch.append(record)
                    if len(batch) >= 50_000:
                        self._append_rows_only(batch)
                        batch = []
                if batch:
                    self._append_rows_only(batch)
            else:
                self.records = {}
                # live_records is deliberately NOT zeroed before the
                # re-append: lock-free /stats readers must never observe a
                # transient near-zero count for a populated corpus.  The
                # re-append of the same record set double-counts (every
                # record looks new against the cleared map), so the
                # pre-rebuild count is subtracted once at the end — readers
                # transiently see between 1x and 2x, never a collapse.
                prev_live = self.live_records
                # the record SET is unchanged by a rebuild; re-appending
                # would fold every digest a second time (XOR: fold twice =
                # remove), so the running hash is preserved
                prev_hash = self._content_hash
                if old_records:
                    self._append_records(list(old_records.values()))
                self.live_records -= prev_live
                self._content_hash = prev_hash

    def find_record_by_id(self, record_id: str) -> Optional[Record]:
        return self.records.get(record_id)

    def find_candidate_matches(self, record: Record,
                               group_filtering: bool = False) -> List[Record]:
        """Interface-parity path: scores one record against the corpus and
        returns every live record whose *device* probability clears
        ``min_relevance``-equivalent pruning.  The DeviceProcessor fast path
        bypasses this."""
        result = self.scorer_cache.score_block(
            [record], group_filtering=group_filtering
        )
        out: List[Record] = []
        for row, _logit in result.survivors(0):
            rid = self.corpus.row_ids[row]
            rec = self.records.get(rid)
            if rec is not None:
                out.append(rec)
        return out

    def explain_retrieval(self, record: Record, candidate: Record,
                          group_filtering: bool = False) -> Dict:
        """Retrieval provenance (ISSUE 5): brute force scores every live
        corpus row, so the only ways a pair can fail to meet are corpus
        state (not indexed / tombstoned) and the candidate-mask policy
        (self-pair, same group) — the pair's actual f32 verdict and
        bounds ride the ``device`` section of the explanation
        (engine.explain.device_breakdown)."""
        out: Dict = {
            "mode": "device-brute",
            "exhaustive": True,
            "survivor_bound": self.scorer_cache._min_logit(),
        }
        row = self.id_to_row.get(candidate.record_id)
        out["candidate_indexed"] = row is not None
        if row is not None:
            corpus = self.corpus
            out["candidate_live"] = bool(
                corpus.row_valid[row] and not corpus.row_deleted[row]
            )
        if group_filtering:
            g1 = record.get_value(GROUP_NO_PROPERTY_NAME)
            g2 = candidate.get_value(GROUP_NO_PROPERTY_NAME)
            out["group_excluded"] = bool(g1 and g1 == g2)
        out["self_pair"] = record.record_id == candidate.record_id
        return out

    def delete(self, record: Record) -> None:
        from ..store.records import LazyRecordMap, record_digest, xor_fold

        with self._lock:
            lazy = isinstance(self.records, LazyRecordMap)
            row = self.id_to_row.pop(record.record_id, None)
            if row is not None:
                # liveness from index state (see _old_liveness)
                if not self.corpus.row_deleted[row]:
                    self.live_records -= 1
                self.corpus.tombstone(row)
            if lazy:
                # no decode: the removed value is unused in lazy mode
                # (the content fold rides the store-synced stamp)
                self.records.discard(record.record_id)
            else:
                old = self.records.pop(record.record_id, None)
                if old is not None:
                    self._content_hash = xor_fold(
                        self._content_hash, record_digest(old)
                    )

    def set_indexing_disabled(self, disabled: bool) -> None:
        self.indexing_disabled = disabled

    # -- extraction snapshot (restart acceleration) --------------------------
    #
    # The durable record store is the source of truth (SURVEY.md section 7
    # "State"); the corpus tensors are a rebuildable cache.  Rebuilding
    # means re-running per-record feature extraction — the dominant restart
    # cost at 10^5+ rows — so the host mirror can be snapshotted to one
    # .npz and reloaded in one mmap'd read, the orbax-style device-state
    # snapshot SURVEY.md section 5.4 calls an optimization, never truth:
    # any mismatch (schema change, env-sized tensor shapes, store drift)
    # silently falls back to full replay.

    @staticmethod
    def _snapshot_checksum(entries: Dict[str, np.ndarray]) -> str:
        """Content checksum over the snapshot's payload arrays (ISSUE 10):
        CRC32 chained over (key, dtype, shape, bytes) in sorted key
        order.  Stamped as ``__checksum`` at save and re-derived from
        the as-stored arrays at load, so a flipped byte, a swapped
        member, or a partially-written archive is rejected into a store
        replay instead of silently scoring corrupt features (the zip
        layer's per-member CRC catches most of this; the stamp also
        catches member-level substitution and pre-decompression
        truncation modes it cannot)."""
        import zlib as _zlib

        crc = 0
        for key in sorted(entries):
            arr = np.ascontiguousarray(entries[key])
            meta = f"{key}\x1f{arr.dtype.str}\x1f{arr.shape}".encode()
            crc = _zlib.crc32(arr.tobytes(), _zlib.crc32(meta, crc))
        return format(crc & 0xFFFFFFFF, "08x")

    def _snapshot_reject(self, reason: str, detail: str) -> bool:
        """A snapshot check failed: warn + count, fall back to replay.
        Never raises — the store remains the source of truth and a bad
        snapshot must cost a rebuild, not availability."""
        telemetry.SNAPSHOT_FALLBACKS.labels(reason=reason).inc()  # dukecheck: ignore[DK501] startup/reload-only rejection path, never per-batch
        logger.warning(
            "corpus snapshot rejected (%s: %s); replaying from the "
            "record store", reason, detail,
        )
        return False

    def _snapshot_fingerprint(self) -> str:
        import hashlib

        # plan semantics + every env knob that sizes the feature tensors
        # (must be computable before any data is loaded; value-slot widths
        # are data-derived, so they ride in the snapshot payload instead —
        # __value_slots — and are applied at load)
        spec = repr((
            [(s.name, s.kind, s.low, s.high)
             for s in self.plan.device_props],
            env_str("DEVICE_MAX_CHARS", ""),
            env_str("DEVICE_MAX_CHARS_CAP", ""),
            env_str("DEVICE_DEMOTE_CHARS", ""),
            env_str("DEVICE_MAX_GRAMS", ""),
            env_str("DEVICE_MAX_TOKENS", ""),
            getattr(self, "dim", None),          # ANN embedding width
            getattr(self, "emb_storage", None),  # ANN embedding dtype
            # char-tensor storage dtype (r5: uint16 UTF-16 code units) —
            # a pre-r5 int32-codepoint snapshot must be rejected into a
            # replay, not silently adopted with the wrong text model
            str(np.dtype(F.CHAR_DTYPE)),
        ))
        return hashlib.sha256(spec.encode()).hexdigest()

    def snapshot_save(self, path: str) -> None:
        import ml_dtypes

        corpus = self.corpus
        if corpus.size == 0:
            return
        # stamp the last store-synced digest when the workload maintains
        # one (the lazy-mirror mode), else the index's own running fold —
        # either way a store commit whose scoring/index pass failed leaves
        # the stamp different from the store's current hash, and the
        # restart's compare must then reject the snapshot (stale features
        # must never score)
        content_hash = (self._store_synced_hash
                        if self._store_synced_hash is not None
                        else self._content_hash.hex())
        # np.savez cannot round-trip ml_dtypes (bf16 loads back as raw
        # void); such tensors are saved as uint16 bit views and listed in
        # __bf16_keys so load can view them back
        flat = {}
        bf16_keys = []
        for prop, tensors in corpus.feats.items():
            for name, arr in tensors.items():
                key = f"feat\x1f{prop}\x1f{name}"
                a = arr[: corpus.size]
                if a.dtype == ml_dtypes.bfloat16:
                    bf16_keys.append(key)
                    a = a.view(np.uint16)
                flat[key] = a
        # payload arrays also feed the stamped content checksum (same
        # set the load-side verification re-derives)
        payload = dict(flat)
        payload["__row_valid"] = corpus.row_valid[: corpus.size]
        payload["__row_deleted"] = corpus.row_deleted[: corpus.size]
        payload["__row_group"] = corpus.row_group[: corpus.size]
        # fixed-width unicode, NOT object dtype: object arrays
        # pickle, and a pickle-bearing snapshot would force
        # allow_pickle=True at load — an arbitrary-code-execution
        # vector for anyone who can write the data volume
        payload["__row_ids"] = np.array(
            [rid or "" for rid in corpus.row_ids], dtype=str
        )
        # write-then-rename: a SIGKILL mid-save must never leave a truncated
        # snapshot (np.load would fail and silently force a full replay)
        tmp = f"{path}.tmp.{os.getpid()}"
        # compression trades restart time for disk: zlib over a multi-GB
        # corpus (10M rows ≈ 9 GB with embeddings) takes minutes, so large
        # deployments set SNAPSHOT_COMPRESS=0 and pay disk instead
        savez = (np.savez_compressed
                 if env_flag("SNAPSHOT_COMPRESS", True)
                 else np.savez)
        try:
            savez(
                tmp,
                __fingerprint=np.array(self._snapshot_fingerprint()),
                __content=np.array(content_hash),
                __checksum=np.array(self._snapshot_checksum(payload)),
                __bf16_keys=np.array(bf16_keys, dtype=str),
                __value_slots=np.array(
                    [s.v for s in self.plan.device_props], dtype=np.int64
                ),
                __char_widths=np.array(
                    [s.chars for s in self.plan.device_props],
                    dtype=np.int64,
                ),
                # surviving device properties (r4): a plan that demoted a
                # long-text property to host scoring persists that choice,
                # so a restart re-demotes instead of rejecting the
                # snapshot for a prop-count mismatch and replaying
                __device_props=np.array(
                    [s.name for s in self.plan.device_props], dtype=str
                ),
                **payload,
            )
            # kill-differential site (ISSUE 10): die in the tmp-written/
            # not-yet-renamed window — the restart must find the PREVIOUS
            # snapshot (or none) intact and never the torn tmp
            from ..utils import faults as _faults

            _faults.check_crash("mid_snapshot_save")
            # np.savez appends .npz to names without it
            os.replace(tmp if tmp.endswith(".npz") else f"{tmp}.npz", path)
        except BaseException:
            for cand in (tmp, f"{tmp}.npz"):
                try:
                    os.unlink(cand)
                except OSError:
                    pass
            raise

    def snapshot_load(self, path: str,
                      records_by_id: Dict[str, Record],
                      content_hash: Optional[str] = None) -> bool:
        """Restore the corpus tensors from a snapshot; False -> replay.

        ``records_by_id`` is the durable store's live view; the snapshot is
        rejected unless its live rows are exactly the store's record set.
        ``content_hash`` is the store's incremental content digest
        (store.records.RecordStore.content_hash) — when provided the
        staleness check is an O(1) compare instead of rehashing every
        record's every value.
        """
        import ml_dtypes

        if self.corpus.size != 0 or not os.path.exists(path):
            return False
        try:
            with np.load(path) as data:  # no pickle: plain arrays only
                if str(data["__fingerprint"]) != self._snapshot_fingerprint():
                    return self._snapshot_reject(
                        "fingerprint", "plan/env fingerprint changed")
                if "__value_slots" not in data.files:
                    return self._snapshot_reject(
                        "schema", "missing __value_slots")
                # re-apply persisted long-text demotions BEFORE the
                # per-prop list compares (see snapshot_save __device_props)
                if "__device_props" in data.files and self._auto_chars:
                    saved = [str(x) for x in data["__device_props"]]
                    current = [s.name for s in self.plan.device_props]
                    missing = [
                        s for s in self.plan.device_props
                        if s.name not in saved
                    ]
                    if missing and set(saved) < set(current):
                        # applied even if a later check rejects the
                        # snapshot: the demotion was data-driven, so the
                        # replay that follows a rejection re-ingests the
                        # same long values and would re-demote anyway —
                        # starting demoted is conservative and exact
                        self._demote_to_host(missing)
                    if [s.name for s in self.plan.device_props] != saved:
                        return self._snapshot_reject(
                            "schema", "device property set changed")
                slots = [int(x) for x in data["__value_slots"]]
                if len(slots) != len(self.plan.device_props):
                    return self._snapshot_reject(
                        "schema", "value-slot count mismatch")
                if self._auto_value_slots:
                    # snapshot written under a larger cap: replaying re-grows
                    # under the current one instead of adopting oversize axes
                    if any(v > _VALUE_SLOTS_MAX for v in slots):
                        return self._snapshot_reject(
                            "schema", "value slots exceed the current cap")
                elif slots != [s.v for s in self.plan.device_props]:
                    return self._snapshot_reject(
                        "schema", "value-slot widths changed")
                # per-property char widths (r4): absent key = pre-r4
                # snapshot, valid only at the plan's default widths
                if "__char_widths" in data.files:
                    widths = [int(x) for x in data["__char_widths"]]
                    if len(widths) != len(self.plan.device_props):
                        return self._snapshot_reject(
                            "schema", "char-width count mismatch")
                    if self._auto_chars:
                        if any(w > _CHARS_CAP for w in widths):
                            return self._snapshot_reject(
                                "schema",
                                "char widths exceed the current cap")
                    elif widths != [s.chars for s in self.plan.device_props]:
                        return self._snapshot_reject(
                            "schema", "char widths changed")
                else:
                    widths = [s.chars for s in self.plan.device_props]
                # record CONTENT hash, not just the id set: an id-set check
                # would accept a snapshot predating an in-place record
                # update that only the store persisted (crash before the
                # next snapshot save) and score stale features
                expected = (content_hash if content_hash is not None
                            else _records_content_hash(records_by_id))
                if str(data["__content"]) != expected:
                    return self._snapshot_reject(
                        "content", "record store drifted past the snapshot")
                accepted_hash = bytes.fromhex(expected)
                row_ids = list(data["__row_ids"])
                row_valid = data["__row_valid"]
                row_deleted = data["__row_deleted"]
                row_group = data["__row_group"]
                live = {
                    rid for rid, ok in zip(row_ids, row_valid) if ok
                }
                if live != set(records_by_id):
                    return self._snapshot_reject(
                        "content", "live row set differs from the store")
                bf16_keys = (
                    {str(k) for k in data["__bf16_keys"]}
                    if "__bf16_keys" in data.files else set()
                )
                feats: Dict[str, Dict[str, np.ndarray]] = {}
                # as-stored arrays, pre-bf16-view: the checksum stamp was
                # computed over exactly these at save time
                raw_payload: Dict[str, np.ndarray] = {
                    "__row_ids": data["__row_ids"],
                    "__row_valid": row_valid,
                    "__row_deleted": row_deleted,
                    "__row_group": row_group,
                }
                for key in data.files:
                    if not key.startswith("feat\x1f"):
                        continue
                    _, prop, name = key.split("\x1f", 2)
                    arr = data[key]
                    raw_payload[key] = arr
                    if key in bf16_keys:
                        arr = arr.view(ml_dtypes.bfloat16)
                    feats.setdefault(prop, {})[name] = arr
                # stamped content checksum (ISSUE 10); absent = pre-stamp
                # snapshot, accepted for upgrade compatibility (the zip
                # member CRCs still guard it)
                if "__checksum" in data.files and (
                        str(data["__checksum"])
                        != self._snapshot_checksum(raw_payload)):
                    return self._snapshot_reject(
                        "checksum", "stamped content checksum mismatch")
        except Exception as e:
            logger.exception("snapshot load failed; replaying from store")
            return self._snapshot_reject("corrupt", repr(e))

        # every check passed — only now adopt the snapshot's value-slot
        # and char widths (a rejected snapshot must leave the plan
        # untouched)
        if self._auto_value_slots:
            for spec, v in zip(self.plan.device_props, slots):
                spec.values_per_record = v
        if self._auto_chars:
            for spec, w in zip(self.plan.device_props, widths):
                spec.max_chars = w
        corpus = self.corpus
        n = len(row_ids)
        rows = corpus.append(
            feats, np.asarray(row_deleted), np.asarray(row_group),
            [str(r) for r in row_ids],
        )
        corpus.row_valid[: n] = row_valid
        corpus._dirty_masks = True
        # the direct mask overwrite above bypassed append/tombstone — the
        # incremental live counter must be recomputed with it
        live_count = int(
            (np.asarray(row_valid) & ~np.asarray(row_deleted)).sum()
        )
        corpus.live_rows = live_count
        # corpus tensors are assembled: stream them to HBM while the rest
        # of the restore (row-map wiring below, store/link bring-up in
        # build_workload, service startup) runs on the host
        self.warm_upload_async()
        from ..store.records import LazyRecordMap

        lazy = isinstance(records_by_id, LazyRecordMap)
        for rid, row, ok in zip(row_ids, rows, row_valid):
            if ok:
                self.id_to_row[str(rid)] = int(row)
                if not lazy:
                    self.records[str(rid)] = records_by_id[str(rid)]
        if lazy:
            # store-backed on-demand mirror: restart skips materializing
            # every record (the 10M-row eager decode took ~24 min / 60 GB)
            self.records = records_by_id
        # live = valid rows that are not dukeDeleted (identical to counting
        # non-deleted records, without touching the record payloads)
        self.live_records = live_count
        self._prewarm_feature_cache(feats, records_by_id)
        # adopt the verified digest as the index's running hash AND the
        # store-synced stamp (the restore bypassed the incremental fold)
        self._content_hash = accepted_hash
        self._store_synced_hash = accepted_hash.hex()
        logger.info("corpus snapshot restored: %d rows from %s%s", n, path,
                    " (lazy record mirror)" if lazy else "")
        return True

    def _prewarm_feature_cache(self, feats, records_by_id) -> None:
        """Seed the digest-keyed feature cache from restored snapshot
        tensors so the FIRST resync after a restart already hits.

        Digests come from the durable store's raw rows (no record decode
        — ``RecordStore.row_digests`` folds the stored serialization,
        byte-identical to ``record_digest`` of the live record); plain
        dict mirrors (tests) fall back to hashing the records.  Budget-
        bounded by the cache itself; best-effort — a failure leaves the
        cache cold, never the restore broken.
        """
        from ..ops import feature_cache as FC

        cache = FC.active()
        if cache is None:
            return
        try:
            store = getattr(records_by_id, "_store", None)
            if store is not None and hasattr(store, "row_digests"):
                digest_iter = store.row_digests()
            elif hasattr(records_by_id, "items"):
                from ..store.records import record_digest

                digest_iter = (
                    (rid, record_digest(r))
                    for rid, r in records_by_id.items()
                )
            else:
                return
            warmed = FC.prewarm(
                self.plan, getattr(self, "encoder", None), feats,
                self.id_to_row, digest_iter, cache,
            )
            if warmed:
                logger.info(
                    "feature cache pre-warmed with %d rows from the "
                    "snapshot", warmed,
                )
        except Exception:  # pragma: no cover - degraded, not broken
            logger.exception(
                "feature-cache pre-warm failed (cache stays cold)"
            )

    def warm_upload_async(self) -> None:
        """Dispatch the host-mirror -> HBM corpus upload in the background.

        A restored 10M-row corpus is ~9 GB of device transfer; paying it
        on the first query made restart-to-first-answer ~10 minutes
        (VERDICT r3 #6).  Kicked from snapshot_load as soon as the corpus
        tensors are assembled, so the transfers stream while the rest of
        startup (row-map wiring, link DB, HTTP bring-up) runs; the first
        query's device_arrays() then finds the mirrors already resident
        (or waits on the upload lock for the in-flight remainder).
        """
        # Small corpora upload in milliseconds on first query — not worth
        # a background thread (and its writer-race surface) at all
        if self.corpus.size < 65536:
            return
        # Default ON: in same-day 10M measurements on the tunnel-attached
        # bench host the background upload cut restart+first-probe 1592s
        # -> 1186s (the transfer streams during the load's host work);
        # background PREWARM during the load, by contrast, measured
        # clearly harmful there (remote compiles contend with everything)
        # and stays opt-in via RESTART_PREWARM in the bench.  Numbers and
        # the (large) host variance: BASELINE.md "Restart".
        if not env_flag("DEVICE_WARM_UPLOAD", True):
            return

        def _upload():
            try:
                # MUST go through the retrying entry point: writers run
                # under the workload lock, which this thread is outside of,
                # so the generation check in device_arrays() is the only
                # guard against a commit/tombstone landing mid-upload and
                # having its dirty flags consumed against torn reads (a
                # direct _device_arrays_locked() call here could clear
                # _pending_update/_dirty_* for rows it never uploaded,
                # silently hiding committed rows from scoring)
                feats, valid, deleted, group = self.corpus.device_arrays()
                # block on completion INSIDE the thread so the upload is
                # actually done (not merely enqueued) before we log
                import jax

                jax.block_until_ready((valid, deleted, group))
                jax.block_until_ready(feats)
                logger.info("warm corpus upload complete (%d rows)",
                            self.corpus.size)
            except Exception:  # pragma: no cover - degraded, not broken
                logger.exception(
                    "warm corpus upload failed (first query will retry)"
                )

        t = threading.Thread(target=_upload, daemon=True,
                             name="corpus-upload")
        t.start()

    def mark_store_synced(self, store_hash: Optional[str]) -> None:
        """Record that the index has fully applied every store write up to
        ``store_hash`` (the workload calls this after each successful
        batch).  snapshot_save stamps this value; a store write without a
        subsequent successful index commit leaves it stale and the next
        restart replays."""
        if store_hash is not None:
            self._store_synced_hash = store_hash

    def close(self) -> None:
        # drop the arena lease and the shared-ladder ref NOW instead of
        # waiting for GC: a hot reload's replacement workload must see
        # this tenant's HBM residency and AOT refcount released
        from ..ops.arena import ARENA

        ARENA.forget(self.corpus)
        cache = self._scorer_cache
        if cache is not None:
            cache.release_shared()


class _BlockResult:
    """Scored query block: per-query candidate rows above the pruning bound."""

    def __init__(self, top_logit: np.ndarray, top_index: np.ndarray,
                 min_logit: float):
        self.top_logit = top_logit
        self.top_index = top_index
        self.min_logit = min_logit
        # device-finalize attachments (ISSUE 12): the query-side device
        # context the dd rescore re-uses (set by dispatch_block via
        # resolve_block) and the resolved dd rescore output
        # (hi, lo, unsafe numpy arrays aligned with top_index) consumed
        # by engine.finalize
        self.dd_ctx = None
        self.dd = None

    def survivor_triples(self, q: int) -> List[Tuple[int, int, float]]:
        """(k_position, corpus_row, device_logit) survivors of query q —
        the position indexes the dd rescore arrays (engine.finalize)."""
        logits = self.top_logit[q]
        rows = self.top_index[q]
        keep = np.nonzero(logits > self.min_logit)[0]
        return [(int(k), int(rows[k]), float(logits[k])) for k in keep]

    def survivors(self, q: int) -> List[Tuple[int, float]]:
        """(corpus_row, device_logit) pairs that may clear the threshold."""
        return [(row, logit) for _, row, logit in self.survivor_triples(q)]


def _fp_value(v, depth: int = 0):
    """JSON-able fingerprint image of a comparator/spec attribute: the
    HLO bakes these values in, so the AOT store key must cover them.
    Objects recurse one level through ``vars()`` (a nested comparator's
    parameters matter); anything deeper or unrecognized reduces to its
    type name — a lossy reduction can only cause a spurious key match
    between configs that differ solely inside such a value, and the
    scoring-source hash in the store key bounds that exposure."""
    if isinstance(v, (bool, int, float, str)) or v is None:
        return v
    if isinstance(v, (list, tuple)):
        return [_fp_value(x, depth) for x in v]
    if isinstance(v, dict):
        return sorted((str(k), _fp_value(x, depth)) for k, x in v.items())
    if depth < 2 and hasattr(v, "__dict__"):
        return [type(v).__name__,
                sorted((k, _fp_value(x, depth + 1))
                       for k, x in vars(v).items())]
    return type(v).__name__


def _plan_fingerprint(plan) -> list:
    """Deterministic image of everything in a feature plan the scorer
    HLO depends on: per-property widths, bounds, and comparator
    parameters (the probability map constants are baked into the
    program; thresholds ride as the runtime ``min_logit`` argument and
    deliberately do NOT key the cache)."""
    return [
        [s.name, s.kind, s.v, s.chars, s.low, s.high,
         _fp_value(s.comparator)]
        for s in plan.device_props
    ]


# Daemon threads killed mid-XLA-compile abort the process at interpreter
# teardown; atexit instead signals the warm loop to stop at the next ladder
# step and waits briefly for the in-flight compile to finish.
_WARM_SHUTDOWN = threading.Event()
_WARM_THREADS: List[threading.Thread] = []
_WARM_ATEXIT = False


def _register_warm_thread(t: threading.Thread) -> None:
    global _WARM_ATEXIT
    _WARM_THREADS.append(t)
    if not _WARM_ATEXIT:
        import atexit

        def _drain():
            _WARM_SHUTDOWN.set()
            for th in _WARM_THREADS:
                th.join(timeout=60.0)

        atexit.register(_drain)
        _WARM_ATEXIT = True


class _ScorerCache:
    """Builds/caches jitted scorers per (top_k, group_filtering) and runs the
    exact K-escalation loop."""

    # Indexed-query batches normally gather their features on device from
    # the corpus rows (only the row-index array crosses the host->device
    # link).  The sharded caches disable this: queries ride replicated over
    # the mesh, so they upload bucket-shaped feature tensors instead.
    queries_from_rows = True

    # AOT executable-store participation (ISSUE 15/18): on by default;
    # the sharded caches keep it on with mesh-annotated lowering shapes
    # and mesh facets in the store key (engine.sharded_matcher).
    supports_aot = True
    # store-key namespace: the ANN cache's programs share the ladder
    # geometry but different HLO, so the builders must never collide
    aot_builder = "corpus"

    def __init__(self, index: DeviceIndex):
        self.index = index
        self._scorers: Dict[Tuple[int, bool], object] = {}
        self._warmed = None
        self._warm_thread: Optional[threading.Thread] = None
        self._warm_compiled = 0  # successful AOT compiles (observability)
        self._aot_loaded = 0     # executables deserialized from the store
        self._warm_seconds = 0.0  # last AOT-load/ladder pass duration
        # last warm-thread failure (repr), surfaced in /healthz detail so
        # a silently-cold replica is diagnosable (ISSUE 15 satellite)
        self._warm_error: Optional[str] = None
        # shape-registered executables: (k, group_filtering, from_rows,
        # capacity, bucket) -> compiled/deserialized executable.
        # Lock-free by design: values are immutable once stored, writes
        # (the synchronous load pass, the warm thread) and reads (the
        # dispatch fast path) are GIL-atomic dict ops, and a stale read
        # only costs one jit-path fallback.
        # With DUKE_SHARED_AOT (default), this dict IS a shared ladder's
        # map (utils.jit_cache.SHARED_LADDERS): every cache with the
        # same (plan fingerprint, geometry) key points at ONE dict, so
        # N same-schema tenants share one warm pass and one set of
        # executables.  The holder indirection lets weakref.finalize
        # release the ref when this cache dies without resurrecting it.
        self._aot: Dict[tuple, object] = {}
        self._shared_holder: List[Optional[object]] = [None]
        self._shared_finalizer = None
        # serializes lease churn (rebind/release): two concurrent plan
        # moves on one cache must not double-release a lease or strand
        # an acquired one in an overwritten holder slot
        self._shared_rebind_lock = threading.Lock()

    # -- compile-ladder pre-warm / AOT load ---------------------------------

    def _ladder(self, cap: int) -> List[tuple]:
        """The (capacity, bucket, from_rows) executable ladder for the
        current shape fingerprint — the current capacity plus
        (speculatively) the next doubling step, every padding bucket,
        and both query variants (indexed gather / http-transform
        upload).  ONE enumeration shared by the AOT loader and the warm
        compiler, so a loaded ladder and a compiled ladder can never
        cover different shapes."""
        out = []
        for cap_i in (cap, cap * 2):
            for bucket in _QUERY_BUCKETS:
                for from_rows in (True, False):
                    out.append((cap_i, bucket, from_rows))
        return out

    def _ladder_k(self, cap: int) -> int:
        """Initial candidate width for a ``cap``-row corpus (the ANN
        cache overrides with its top-C)."""
        return min(_INITIAL_TOP_K, cap)

    def _min_warm_cap(self) -> int:
        """Smallest capacity the ladder lowers at — one scan chunk for
        the single-device programs; the sharded caches override with the
        mesh granule (every shard needs whole chunks)."""
        return _CHUNK

    def _store_key(self, plan, k: int, group_filtering: bool,
                   from_rows: bool, cap: int, bucket: int) -> dict:
        """The AOT store key for one ladder entry: everything the
        compiled HLO depends on that the store does not already cover
        (utils.jit_cache adds backend, device kind, jax/jaxlib versions,
        XLA flags, and the scoring-source hash)."""
        return {
            "builder": self.aot_builder,
            "plan": _plan_fingerprint(plan),
            "chunk": _CHUNK,
            "value_slots_max": _VALUE_SLOTS_MAX,
            "k": k,
            "group_filtering": bool(group_filtering),
            "from_rows": bool(from_rows),
            "cap": cap,
            "bucket": bucket,
        }

    def _shared_ladder_key(self, group_filtering: bool) -> tuple:
        """The cross-workload ladder identity: the AOT store key minus
        the per-entry facets (k, variant, capacity, bucket all live
        inside the map's akeys).  Derived through ``_store_key`` so the
        sharded caches' mesh facets ride along automatically — two
        tenants share a ladder iff their entries would share store
        files."""
        import json

        doc = self._store_key(self.index.plan, 0, group_filtering,
                              True, 0, 0)
        for facet in ("k", "from_rows", "cap", "bucket"):
            doc.pop(facet, None)
        return (json.dumps(doc, sort_keys=True, separators=(",", ":"),
                           default=str),)

    def _rebind_shared_ladder(self, group_filtering: bool) -> None:
        """Point ``self._aot`` at the shared ladder for the current
        (fingerprint, geometry) key, releasing any previous lease — the
        refcounted form of the plan-mutation eviction seam: THIS
        tenant's plan moved, so it steps off the old ladder (which
        other tenants may still be on) and onto the new key's; the old
        ladder's executables die with its last leaseholder."""
        import weakref

        from ..utils.jit_cache import (
            SHARED_LADDERS,
            release_shared_lease,
        )

        key = self._shared_ladder_key(group_filtering)
        with self._shared_rebind_lock:
            lease = self._shared_holder[0]
            if lease is not None and lease.key == key:
                return
            SHARED_LADDERS.release(lease)
            lease = SHARED_LADDERS.acquire(key)
            self._shared_holder[0] = lease
            self._aot = lease.map
            if self._shared_finalizer is None:
                self._shared_finalizer = weakref.finalize(
                    self, release_shared_lease, self._shared_holder)

    def release_shared(self) -> None:
        """Drop this cache's shared-ladder ref eagerly (index close)."""
        from ..utils.jit_cache import release_shared_lease

        with self._shared_rebind_lock:
            release_shared_lease(self._shared_holder)
            if self._aot:
                self._aot = {}

    def _warm_serial(self):
        """Context serializing warm compiles over the shared ladder so
        N same-schema tenants pay ONE compile per entry (the losers
        find it registered and skip); per-workload ladders need no
        serialization (one warm thread per cache)."""
        lease = self._shared_holder[0]
        return (lease.warm_lock if lease is not None
                else contextlib.nullcontext())

    def prewarm_async(self, group_filtering: bool) -> None:
        """Make the (query-bucket x capacity x K x variant) scorer ladder
        hot for the current corpus shapes — and speculatively the next
        capacity-doubling step — so a cold run's early batches don't
        stall on sequential jit compiles.  Safe to call often: no-ops
        while the shape fingerprint is unchanged.

        With the AOT store on (``DUKE_AOT``, default), the ladder is
        first *deserialized* synchronously — the whole point is that the
        FIRST batch after a restart scores through a stored executable,
        so the load must not race it — and the background warm thread
        becomes the miss-filler: it compiles only the entries the store
        lacked and serializes each one back (plus seeding the persistent
        XLA compile cache as before)."""
        from ..utils.jit_cache import aot_enabled, enable_persistent_cache

        aot = aot_enabled() and self.supports_aot
        prewarm = env_flag("DEVICE_PREWARM", True)
        if not aot and not prewarm:
            return
        # the warm compiles land in the persistent XLA cache (private jit
        # instances; the live scorer reads the cache on first contact) —
        # make sure it is actually on, whatever the embedding context.
        # With the AOT store on, warming helps even without it (fresh
        # executables register for the dispatch fast path directly).
        if enable_persistent_cache() is None and not aot:
            return  # no cache -> warming could never help the live scorer
        cap = max(self.index.corpus.capacity, self._min_warm_cap())
        key = (
            cap,
            tuple((s.v, s.chars) for s in self.index.plan.device_props),
            bool(group_filtering),
        )
        prev = self._warmed
        if prev == key:
            return
        self._warmed = key
        from ..utils.jit_cache import shared_aot_enabled

        if shared_aot_enabled() and self.supports_aot:
            # shared-ladder form of the eviction seam (ISSUE 19): the
            # ladder key embeds the full plan fingerprint, so a plan
            # move rebinds this cache to a DIFFERENT shared map — other
            # tenants still on the old plan keep theirs, and the old
            # ladder's executables die with its last leaseholder
            # (refcounted evict).  Capacity-only changes keep the lease
            # (the key has no capacity facet).
            self._rebind_shared_ladder(group_filtering)
        elif prev is not None and prev[1:] != key[1:]:
            # the PLAN moved (value-slot/char growth, demotion, filtering
            # flip): every registered executable was built for the old
            # tensor shapes, and its (k, gf, from_rows, cap, bucket) akey
            # would otherwise BLOCK the load pass from refilling that
            # slot — the stale entry would only die at dispatch as a
            # call-time reject with no refill path.  Rebind (not mutate):
            # an in-flight reader of the old dict at worst takes one
            # rejected call.  Capacity-only changes keep the map — old-cap
            # entries are unreachable but the current-cap ones stay hot.
            self._aot = {}
        missing = None
        if aot:
            missing = self._aot_load_ladder(group_filtering, key)
            if not missing:
                return  # full ladder deserialized: nothing to compile
        if not prewarm:
            return  # background compiles off: misses stay on the jit path
        t = threading.Thread(
            target=self._prewarm, args=(group_filtering, key, missing),
            daemon=True, name="scorer-prewarm",
        )
        self._warm_thread = t
        _register_warm_thread(t)
        t.start()

    def _aot_load_ladder(self, group_filtering: bool, key):
        """Deserialize every ladder entry the AOT store holds for the
        current shape fingerprint, registering each for the dispatch
        fast path; returns the (cap, bucket, from_rows) entries still
        missing (the warm thread's compile list), or the full ladder
        when the load pass itself failed."""
        from ..utils.jit_cache import AotStore

        t0 = time.monotonic()
        loaded = 0
        missing: Optional[List[tuple]] = []
        try:
            store = AotStore()
            plan = self._frozen_plan()
            for cap_i, bucket, from_rows in self._ladder(key[0]):
                k = self._ladder_k(cap_i)
                akey = (k, bool(group_filtering), bool(from_rows),
                        cap_i, bucket)
                if akey in self._aot:
                    continue
                exe = store.load(self._store_key(
                    plan, k, group_filtering, from_rows, cap_i, bucket))
                if exe is None:
                    missing.append((cap_i, bucket, from_rows))
                else:
                    self._aot[akey] = exe
                    loaded += 1
        except Exception:  # pragma: no cover - store/backend specific
            logger.exception(
                "AOT ladder load failed (falling back to compiles)")
            missing = None
        self._aot_loaded += loaded
        self._warm_seconds = time.monotonic() - t0
        if loaded:
            logger.info(
                "AOT executable cache: %d scorer(s) deserialized in "
                "%.3f s (%d missing)", loaded, self._warm_seconds,
                len(missing) if missing is not None else -1,
            )
        return missing if missing is not None else self._ladder(key[0])

    def aot_call(self, k: int, group_filtering: bool, from_rows: bool,
                 bucket: int, args: tuple):
        """Run the scoring program through a registered AOT/pre-built
        executable when one matches this exact (K, filtering, variant,
        capacity, bucket) shape; None = caller takes the jit path.  A
        shape drift (the plan mutated after the executable was built)
        raises inside the call — the entry is dropped (counted as a
        reject) and the jit path serves."""
        if not self._aot:
            return None
        akey = (k, bool(group_filtering), bool(from_rows),
                self.index.corpus.capacity, bucket)
        fn = self._aot.get(akey)
        if fn is None:
            return None
        try:
            out = fn(*args)
        except Exception:
            from ..utils.jit_cache import record_aot_reject

            record_aot_reject()
            self._aot.pop(akey, None)
            logger.warning(
                "registered AOT executable rejected at call time (plan "
                "drift since it was built?); jit path serves this shape",
                exc_info=True,
            )
            return None
        record_cache_hit()
        return out

    def _row_shapes(self):
        """Per-row feature tensor shapes under the current plan, derived by
        extracting one empty record (no corpus data needed)."""
        from ..core.records import ID_PROPERTY_NAME

        dummy = Record()
        dummy.add_value(ID_PROPERTY_NAME, "__prewarm__")
        return self.index._extract([dummy])

    def _sds(self, shape, dtype, family: str = "corpus"):
        """Lowering-shape factory: the abstract aval one ladder entry
        lowers against.  ``family`` names the partition-rule family the
        tensor belongs to ("corpus" record-axis state vs "queries"
        replicated query-side inputs) — meaningless on one device, but
        the sharded caches override this to annotate each aval with its
        mesh sharding so AOT executables compile against the real
        layouts (parallel.sharded.PARTITION_RULES)."""
        import jax

        return jax.ShapeDtypeStruct(shape, dtype)

    def _lower_args(self, row_feats, cap: int, bucket: int):
        def sds(a):
            return self._sds((cap,) + a.shape[1:], a.dtype)

        cfeats = {
            prop: {name: sds(arr) for name, arr in tensors.items()}
            for prop, tensors in row_feats.items()
        }
        mb = self._sds((cap,), np.bool_)
        mi = self._sds((cap,), np.int32)
        qr = self._sds((bucket,), np.int32, "queries")
        qg = self._sds((bucket,), np.int32, "queries")
        ml = self._sds((), np.float32, "queries")
        return cfeats, (mb, mb, mi, qg, qr, ml)

    def _probe_shapes(self):
        """Per-row feature shapes of a typical http-transform probe (not in
        the corpus, so extracted under the query plan — value width sized to
        the probe, which for the common single-valued case is 1)."""
        from ..core.records import ID_PROPERTY_NAME

        dummy = Record()
        dummy.add_value(ID_PROPERTY_NAME, "__prewarm__")
        return self.index._extract(
            [dummy], plan=self.index._query_plan([dummy])
        )

    def _lower_one(self, row_feats, cap: int, bucket: int,
                   group_filtering: bool, *, from_rows: bool = True,
                   probe_feats=None, plan=None):
        cfeats, (mb, mb2, mi, qg, qr, ml) = self._lower_args(
            row_feats, cap, bucket
        )
        k = self._ladder_k(cap)
        # a PRIVATE jit instance: tracing the live scorer object from this
        # thread while the main thread traces it too corrupts shared pjit
        # state; _build is the single builder both paths share, so the HLO
        # is identical and the XLA compile lands in the persistent cache
        # the live scorer reads
        scorer = self._build(k, group_filtering, from_rows, plan=plan)
        if from_rows:
            qfeats = {}
        else:
            qfeats = {
                prop: {
                    name: self._sds(
                        (bucket,) + arr.shape[1:], arr.dtype, "queries"
                    )
                    for name, arr in tensors.items()
                }
                for prop, tensors in probe_feats.items()
            }
        return scorer.lower(qfeats, cfeats, mb, mb2, mi, qg, qr, ml).compile()

    def _frozen_plan(self):
        """Immutable copy of the index plan for the warm thread.

        The live plan's specs mutate in place (value-slot / char-width
        growth, long-text demotion) while the main thread ingests; a
        trace in this thread reading a spec mid-mutation produced
        intermittent tracing corruption (KeyError on a jaxpr Var).  The
        copy freezes the state the warm started from; if the live plan
        moves on, these compiles are stale-but-harmless and the shape
        guard kicks a fresh warm."""
        from dataclasses import replace

        from ..ops import features as F

        return F.SchemaFeatures(
            device_props=[replace(s) for s in self.index.plan.device_props],
            host_props=list(self.index.plan.host_props),
        )

    def _prewarm(self, group_filtering: bool, key, missing=None) -> None:
        """Compile the ladder entries ``missing`` (None = the full
        ladder — the AOT store was off or its load pass failed), and
        with the store on serialize each fresh executable back so the
        NEXT process deserializes instead of compiling.  Both query
        variants ride the ladder: http-transform probes score through
        from_rows=False (bucket-shaped qfeats) and would otherwise
        stall on first-contact compiles despite the warm having run."""
        from ..utils.jit_cache import AotStore, aot_enabled

        try:
            store = (AotStore()
                     if aot_enabled() and self.supports_aot else None)
            plan = self._frozen_plan()
            row_feats = self._row_shapes()
            probe_feats = self._probe_shapes()
            entries = self._ladder(key[0]) if missing is None else missing
            self._prewarm_entries(entries, key, group_filtering, store,
                                  plan, row_feats, probe_feats)
        except Exception as e:  # pragma: no cover - warm failures are rare
            # counted + latched (ISSUE 15 satellite): a silently-cold
            # replica — scoring works but every first-contact shape pays
            # a live compile — must be diagnosable from /healthz
            telemetry.PREWARM_FAILURES.inc()  # dukecheck: ignore[DK502] rare event: warm-thread failure, never per-block
            self._warm_error = repr(e)
            logger.exception(
                "scorer pre-warm failed (scoring unaffected, but this "
                "replica stays cold)")

    @staticmethod
    def _cache_bypass():
        """Thread-local context disabling jax's persistent compilation
        cache for one warm compile.  Compiles destined for the AOT store
        must be FRESH: an XLA compile served from that cache yields an
        executable that serializes thin (missing jit symbols — see
        AotStore.save).  The live path keeps its cache (thread-local
        config); direct registration supersedes the old cache-seeding
        role."""
        try:
            from jax._src.config import enable_compilation_cache

            return enable_compilation_cache(False)
        except Exception:  # pragma: no cover - private jax API drift
            return contextlib.nullcontext()  # save()'s validation guards

    def _prewarm_entries(self, entries, key, group_filtering, store,
                         plan, row_feats, probe_feats) -> None:
        for cap_i, bucket, from_rows in entries:
            if self._warmed != key or _WARM_SHUTDOWN.is_set():
                return  # superseded / interpreter exiting
            k = self._ladder_k(cap_i)
            akey = (k, bool(group_filtering), bool(from_rows),
                    cap_i, bucket)
            if akey in self._aot:
                # already registered — on a shared ladder this is the
                # fingerprint-batched prewarm: another tenant's warm (or
                # load pass) filled the slot, so this tenant pays zero
                continue
            with self._warm_serial():
                if akey in self._aot:
                    continue  # lost the race: the winner compiled it
                record_compile()
                ctx = (self._cache_bypass() if store is not None
                       else contextlib.nullcontext())
                t_compile = time.monotonic()
                with ctx:
                    compiled = self._lower_one(
                        row_feats, cap_i, bucket, group_filtering,
                        from_rows=from_rows,
                        probe_feats=None if from_rows else probe_feats,
                        plan=plan,
                    )
                costs.note_compile(time.monotonic() - t_compile)
                self._warm_compiled += 1
                # serve the fresh executable directly — first contact in
                # THIS process skips the live jit trace too; setdefault
                # so a deserialized entry (or a newer warm) is never
                # replaced mid-use
                self._aot.setdefault(akey, compiled)
            if store is not None and not store.save(
                    self._store_key(plan, k, group_filtering,
                                    from_rows, cap_i, bucket),
                    compiled):
                # this backend cannot serialize executables (or the
                # store is unwritable): stop bypassing the persistent
                # XLA compile cache — without the fallback, NOTHING
                # would seed it (the live path serves from the _aot
                # registrations) and every restart would re-pay the
                # full ladder compile, a regression vs the pre-AOT
                # behavior.  Remaining entries compile cache-enabled,
                # converging on the legacy restart story.
                store = None
                logger.warning(
                    "AOT executable save unsupported here; remaining "
                    "warm compiles seed the persistent XLA cache "
                    "instead")

    def _build(self, top_k: int, group_filtering: bool, from_rows: bool,
               plan=None):
        """The ONE scorer builder — both the live cached path (_scorer) and
        the prewarm's private instances (_lower_one) go through it, so the
        two can never drift onto different HLO (which would silently turn
        pre-warming into cache-missing busywork).  ``plan`` overrides for
        the warm thread's frozen copy (_frozen_plan)."""
        from ..ops import scoring as S

        return S.build_corpus_scorer(
            plan or self.index.plan, chunk=_CHUNK, top_k=top_k,
            group_filtering=group_filtering, queries_from_rows=from_rows,
        )

    def _scorer(self, top_k: int, group_filtering: bool,
                from_rows: bool = False):
        key = (top_k, group_filtering, from_rows)
        if key not in self._scorers:
            # a build here is a first-contact shape: XLA compiles at the
            # first call (or reads the persistent cache).  The counter
            # pair makes recompile storms visible on /metrics.
            record_compile()
            t_compile = time.monotonic()
            self._scorers[key] = self._build(top_k, group_filtering,
                                             from_rows)
            costs.note_compile(time.monotonic() - t_compile)
        else:
            record_cache_hit()
        return self._scorers[key]

    def _min_logit(self) -> float:
        from ..ops import scoring as S

        index = self.index
        # the long-validated 1e-3 insurance margin covering float32
        # kernel error at the bound (differential-tested; surviving pairs
        # are re-scored host-exact, so it only costs extra
        # finalizations).  Deliberately NOT widened to the certified
        # per-plan margin: for degenerate configs (low=0.0 / high=1.0)
        # the certified bound explodes and would disable the filter
        # entirely; such schemas instead get an empty decisive band
        # (prune bound below this filter bound -> nothing skipped), which
        # degrades to rescore-everything, never to unsoundness.  Both
        # bounds derive from the ONE emit_bound_logit formula so the
        # threshold/host-bound handling can never drift apart.
        return S.emit_bound_logit(index.schema, index.plan, 1e-3)

    def _prepare_queries(self, records: Sequence[Record],
                         group_filtering: bool):
        """Query-side arrays for a block: (qfeats device tree or {} when the
        scorer gathers on device, from_rows flag, query_row, query_group)."""
        import jax.numpy as jnp

        index = self.index
        bucket = bucket_for(len(records))
        # padding-bucket visibility: which static shapes blocks land on
        # and how many padded rows they carry (unlocked counters — this
        # is the scoring path; see telemetry.QUERY_BLOCKS)
        blocks_child, pad_child = _BUCKET_CHILDREN[bucket]
        blocks_child.inc()
        if bucket > len(records):
            pad_child.inc(bucket - len(records))
        # (a block larger than the biggest bucket is split by the caller)
        rows = [index.id_to_row.get(r.record_id, -1) for r in records]
        from_rows = self.queries_from_rows and all(row >= 0 for row in rows)
        if from_rows:
            # normal dedup/linkage path: the batch was just indexed, so its
            # features already sit on device in the corpus tensors — the
            # scorer gathers them there from query_row, and the only
            # query-side upload is the row-index array (host->device
            # traffic is the dominant steady-state cost over a
            # high-latency device link)
            qfeats = {}
        else:
            # http-transform: queries are not in the corpus; extract under a
            # query-sized value axis (a probe may carry more values than any
            # indexed record — the corpus plan must not widen for it)
            qfeats_np = index._extract(
                records, plan=index._query_plan(records)
            )
            qfeats = {
                prop: {
                    name: jnp.asarray(_pad_rows(arr, bucket))
                    for name, arr in tensors.items()
                }
                for prop, tensors in qfeats_np.items()
            }
        query_row = np.full((bucket,), -1, dtype=np.int32)
        query_group = np.full((bucket,), -2, dtype=np.int32)
        for i, r in enumerate(records):
            query_row[i] = rows[i]
            group_no = r.get_value(GROUP_NO_PROPERTY_NAME)
            if group_filtering and not group_no:
                # host-engine parity (index.inverted.find_candidate_matches)
                raise ValueError(
                    f"The '{GROUP_NO_PROPERTY_NAME}' property was missing "
                    "or empty!"
                )
            query_group[i] = int(group_no) if group_no else -2
        return (qfeats, from_rows, jnp.asarray(query_row),
                jnp.asarray(query_group))

    def dispatch_block(self, records: Sequence[Record], *,
                       group_filtering: bool):
        """Enqueue the device scoring program for a query block and return
        a pending handle — JAX dispatch is asynchronous, so the host can
        finalize the *previous* block (or extract the next) while the
        device crunches this one.  ``resolve`` blocks on the result and
        runs the (rare) K-escalation loop synchronously.
        """
        from ..ops import scoring as S
        import jax.numpy as jnp

        index = self.index
        corpus = index.corpus
        n = len(records)
        min_logit = self._min_logit()

        if corpus.size == 0:
            return _BlockResult(
                np.full((n, 1), S.NEG_INF, np.float32),
                np.full((n, 1), -1, np.int32), min_logit,
            )

        qfeats, from_rows, query_row_j, query_group_j = self._prepare_queries(
            records, group_filtering
        )
        bucket = int(query_row_j.shape[0])
        cfeats, cvalid, cdeleted, cgroup = corpus.device_arrays()
        args = (cfeats, cvalid, cdeleted, cgroup, query_group_j,
                query_row_j, jnp.float32(min_logit))

        def call(k):
            # AOT fast path (ISSUE 15): a deserialized/pre-built
            # executable registered for this exact shape skips the jit
            # trace entirely — a restarted process's first batch scores
            # with ZERO compiles (tests/test_aot_cache.py)
            out = self.aot_call(k, group_filtering, from_rows, bucket,
                                (qfeats,) + args)
            if out is not None:
                return out
            return self._scorer(k, group_filtering, from_rows)(qfeats, *args)

        k = min(_INITIAL_TOP_K, corpus.capacity)
        # brute force is exact for any K that fits every candidate above
        # the bound: escalate while some query overflowed K
        pending = _PendingBlock(
            corpus.capacity, n, min_logit, k, call,
            lambda cmax, kk: cmax > kk, *call(k)
        )
        # query-side context for the post-resolve dd rescore (ISSUE 12):
        # the same uploaded/gathered query features the scorer used
        pending.dd_ctx = (qfeats, from_rows, query_row_j)
        return pending

    def score_block(self, records: Sequence[Record], *,
                    group_filtering: bool) -> _BlockResult:
        pending = self.dispatch_block(records, group_filtering=group_filtering)
        return resolve_block(pending)

    # device-resident certified finalization (ISSUE 12/18): on for every
    # single-process backend — the sharded caches route the survivor
    # gather through a replicated-layout mesh program first (_dd_call
    # override in engine.sharded_matcher, gated off multi-host meshes)
    # and then run the same dd rescorer
    supports_dd = True

    def dd_rescore(self, result: _BlockResult):
        """Run the dd survivor rescore for a resolved block.

        Returns (hi, lo, unsafe) numpy arrays aligned with
        ``result.top_index`` — the two-float emulated-f64 logit over the
        dd-certifiable device properties plus the truncation-safety mask
        (ops.scoring.build_dd_rescorer) — or None when the block cannot
        ride the device (no certifiable property, no survivors at all,
        multi-host mesh).  Collective-free on multi-host: under a
        dispatcher this extra device program runs on the frontend only,
        so the sharded caches expose ``supports_dd`` only when the whole
        mesh is addressable from this process (their ``_dd_call`` gather
        IS a collective — safe single-process, a deadlock cross-host).
        """
        if not self.supports_dd:
            return None
        ctx = result.dd_ctx
        if ctx is None:
            return None
        from ..ops import scoring as S
        import jax.numpy as jnp

        plan = self.index.plan
        # block-level dispatch gate: only survivors whose f32 logit sits
        # low enough to possibly be a certified reject justify the
        # program (dd_gate_bound — certified events and residue take the
        # host compare either way).  Also skips empty blocks, and small
        # tests never pay the first-contact compile.
        gate = S.dd_gate_bound(self.index.schema, plan)
        candidates = ((result.top_logit > result.min_logit)
                      & (result.top_logit <= gate))
        if not bool(candidates.any()):
            return None
        qfeats, from_rows, query_row_j = ctx
        fn = S.dd_rescorer(
            plan, queries_from_rows=from_rows,
            value_slots_cap=_VALUE_SLOTS_MAX,
        )
        if fn is None:
            return None
        cfeats_all = self.index.corpus.device_arrays()[0]
        cfeats = {s.name: cfeats_all[s.name] for s in S.dd_plan_specs(plan)}
        hi, lo, unsafe = self._dd_call(fn, qfeats, cfeats, query_row_j,
                                       jnp.asarray(result.top_index))
        return (np.asarray(hi), np.asarray(lo), np.asarray(unsafe))

    def _dd_call(self, fn, qfeats, cfeats, query_row_j, top_index):
        """Run the dd program against the corpus tensors.  One device:
        the gather happens inside ``fn``.  The sharded caches override
        this to pre-gather the survivors to replicated layout and feed
        ``fn`` an identity index — same program, same arithmetic, so the
        verdicts stay bit-identical across backends."""
        return fn(qfeats, cfeats, query_row_j, top_index)


class _PendingBlock:
    """In-flight device scoring call (see ``_ScorerCache.dispatch_block``).

    ``call(k)`` re-invokes the jitted scorer at width ``k``;
    ``needs_escalation(count_max, k)`` is the backend's saturation
    predicate (brute force: some query overflowed K; ANN: retrieval
    saturated at C).
    """

    def __init__(self, capacity, n, min_logit, k, call, needs_escalation,
                 top_logit, top_index, count, stage: str = "top_k"):
        self.capacity = capacity
        self.n = n
        self.min_logit = min_logit
        self.k = k
        self.call = call
        self.needs_escalation = needs_escalation
        self.top_logit = top_logit
        self.top_index = top_index
        self.count = count
        # retrieval stage the escalation metric attributes re-runs to:
        # "top_k" (brute force), "top_c" (flat ANN), "ivf" (cell probe,
        # incl. its terminal flat-scan fallback)
        self.stage = stage


# process-wide escalation count (observability: the F1-at-scale harness
# reports how often K/C-escalation actually fired at a given corpus size).
# Guarded: resolve_block runs on multiple workload threads in service mode.
ESCALATIONS = 0
_ESCALATIONS_LOCK = threading.Lock()


def _count_escalation(stage: str = "top_k") -> None:
    global ESCALATIONS
    with _ESCALATIONS_LOCK:
        ESCALATIONS += 1
    # mirrored on /metrics; escalations are rare by construction (each
    # doubles K), so the registry update is off the steady-state path
    telemetry.SCORER_ESCALATIONS.inc()  # dukecheck: ignore[DK502] rare by construction (each escalation doubles K)
    # stage-attributed series (ISSUE 9): brute-force K, flat-ANN C, or
    # IVF probe escalations tell different capacity stories
    telemetry.RETRIEVAL_ESCALATIONS.labels(stage=stage).inc()  # dukecheck: ignore[DK501,DK502] rare by construction (each escalation doubles the width)


def resolve_block(pending) -> _BlockResult:
    """Wait for a dispatched block; re-run with doubled width if the
    backend's saturation predicate fires (exactness / recall contract)."""
    if isinstance(pending, _BlockResult):  # empty-corpus short-circuit
        return pending
    import jax

    k = pending.k
    top_logit, top_index, count = (
        pending.top_logit, pending.top_index, pending.count
    )
    while True:
        # ONE device fetch for all three outputs: fetching the count
        # first and the logits after costs a second device-link round
        # trip per block (~0.1 s over the axon tunnel) in the common
        # no-escalation case; the logits are ~256 KB, so speculatively
        # fetching them with the count is free next to the latency
        count_np, logit_np, index_np = jax.device_get(
            (count, top_logit, top_index)
        )
        cmax = int(count_np[: pending.n].max(initial=0))
        if k >= pending.capacity or not pending.needs_escalation(cmax, k):
            res = _BlockResult(logit_np, index_np, pending.min_logit)
            res.dd_ctx = getattr(pending, "dd_ctx", None)
            return res
        k = min(k * 2, pending.capacity)
        _count_escalation(getattr(pending, "stage", "top_k"))
        logger.info(
            "escalation: %d candidates at the bound, retrying with "
            "width=%d", cmax, k,
        )
        top_logit, top_index, count = pending.call(k)


def _pad_rows(arr: np.ndarray, bucket: int) -> np.ndarray:
    n = arr.shape[0]
    if n == bucket:
        return arr
    out = np.zeros((bucket,) + arr.shape[1:], dtype=arr.dtype)
    out[:n] = arr
    return out


class DeviceProcessor:
    """Drop-in for ``engine.processor.Processor`` running the TPU path.

    Same listener event protocol (SURVEY.md section 1 L1); the per-record
    candidate loop becomes: block queries -> one device scoring program ->
    host finalization of the surviving top-K pairs.
    """

    # brute force scores every live corpus row with the exact comparator
    # kernels; the ANN subclass retrieves then rescores only top-C, so its
    # pairs_compared stat must count the rescored candidates instead
    exhaustive = True
    # multi-host follower replicas replay only the device-program side of
    # a batch (parallel.dispatch): host finalization of survivors — and
    # everything downstream of it (listeners, link DBs) — runs on the
    # frontend alone.  The device-program ORDER must stay identical either
    # way, so the flag guards only the per-query host loop.
    finalize_survivors = True

    def __init__(self, schema: DukeSchema, database: DeviceIndex, *,
                 group_filtering: bool = False, profile: bool = False,
                 threads: int = 1):
        from ..telemetry.decisions import DecisionRecorder
        from .explain import host_breakdown
        from .finalize import FinalizeExecutor

        self.schema = schema
        self.database = database
        self.group_filtering = group_filtering
        self.profile = profile
        self.listeners: List[MatchListener] = []
        self.stats = ProfileStats()
        # single-writer per-batch phase durations (workload lock holds
        # the writer exclusivity; readers are lock-free scrapes)
        self.phases = PhaseRecorder()
        # decision flight recorder + quality-drift monitors (ISSUE 5):
        # written ONLY by the coordinating thread that emits listener
        # events (single-writer), scraped lock-free by /metrics and
        # served by /debug/decisions
        self.decisions = DecisionRecorder(
            schema.threshold, schema.maybe_threshold,
            breakdown=lambda q, c: host_breakdown(schema, q, c),
            resolver=database.find_record_by_id,
        )
        self._scorers = database.scorer_cache
        # host finalization of the surviving top-K pairs fans out over
        # this executor (DUKE_FINALIZE_THREADS overrides ``threads``);
        # events still emit in strict query order (engine.finalize)
        self.finalizer = FinalizeExecutor(threads)
        # compile the scorer shape ladder in the background while the
        # service finishes startup / the first batches are parsed
        self._scorers.prewarm_async(group_filtering)

    def add_match_listener(self, listener: MatchListener) -> None:
        self.listeners.append(listener)

    # host-exact pair probability: surviving pairs are finalized with the
    # same double-precision math as the host engine, so threshold decisions
    # and reported confidences are bit-identical to ``engine.processor``
    # (SURVEY.md section 7 hard part 4) — the device program is a pruning
    # filter, never the source of emitted probabilities.
    def compare(self, r1: Record, r2: Record) -> float:
        from .processor import Processor

        return Processor.compare(self, r1, r2)

    def deduplicate(self, records: Sequence[Record]) -> None:
        t0 = time.monotonic()
        for listener in self.listeners:
            listener.batch_ready(len(records))

        # annotate=True bridges the span into jax.profiler.TraceAnnotation
        # while an on-demand capture is live (utils/profiling), so the
        # device timeline carries the same phase names as the trace tree
        with tracing.span(PHASE_ENCODE, {"records": len(records)},
                          annotate=True):
            for record in records:
                self.database.index(record)
            self.database.commit()
        encode_dt = time.monotonic() - t0
        self.phases.observe(PHASE_ENCODE, encode_dt)
        retrieval0 = self.stats.retrieval_seconds
        compare0 = self.stats.compare_seconds
        # corpus growth / value-slot widening changes the scorer shapes;
        # kick the (no-op-when-unchanged) background warm for the new
        # fingerprint plus the next doubling step
        self._scorers.prewarm_async(self.group_filtering)

        # multi-host serving: followers replay the scoring pass with the
        # same query records (the corpus mutation already broadcast from
        # commit()); must precede _score_blocks so every process enqueues
        # the block programs in the same global order
        from ..parallel import dispatch

        key = getattr(self.database, "_dispatch_key", None)
        d = dispatch.current() if key is not None else None
        if d is not None:
            d.broadcast(dispatch.with_trace_ctx(("score", key, list(records))))
        # a frontend that aborts mid-pass (listener exception, OOM) has
        # entered fewer collective programs than the followers it just
        # instructed — latch before propagating (advisor r4 medium)
        match_ns = time.monotonic_ns()
        with dispatch.latch_on_failure(
            d, "frontend scoring pass aborted after broadcast"
        ):
            self._score_blocks(records)

        self.stats.batches += 1
        retrieve_dt = self.stats.retrieval_seconds - retrieval0
        score_dt = self.stats.compare_seconds - compare0
        self.phases.observe(PHASE_RETRIEVE, retrieve_dt)
        self.phases.observe(PHASE_SCORE, score_dt)
        # device-program resolve and host finalization interleave across
        # the double-buffered blocks: the shared aggregate-span layout
        tracing.add_phase_spans(match_ns, retrieve_dt, score_dt)
        t_persist = time.monotonic()
        with tracing.span(PHASE_PERSIST, annotate=True):
            for listener in self.listeners:
                listener.batch_done()
        persist_dt = time.monotonic() - t_persist
        self.phases.observe(PHASE_PERSIST, persist_dt)
        # the same four durations feed the process-wide busy ledger, so
        # per-workload phase counters reconcile against it by definition
        costs.note_busy(encode_dt + retrieve_dt + score_dt + persist_dt)
        if self.profile:
            logger.info(
                "batch=%d records, corpus=%d, %.3fs",
                len(records), self.database.corpus.size,
                time.monotonic() - t0,
            )

    def _score_blocks(self, records: Sequence[Record]) -> None:
        """The device-program side of a batch: double-buffered block
        dispatch + escalation, then (frontend only) host finalization.

        Multi-host follower replicas call this directly with
        ``finalize_survivors=False``: the dispatch structure — block
        order, pre-dispatch of block N+1 before block N resolves,
        escalation re-runs — must match the frontend program-for-program
        or the cross-host collectives deadlock, so the loop is shared
        rather than reimplemented (parallel.dispatch invariant 2).
        """
        corpus = self.database.corpus
        # incremental counter (append/tombstone-maintained): the per-batch
        # O(capacity) mask scans + boolean fancy-index allocation this
        # replaces were real work at 10M rows
        live_rows = corpus.live_rows

        from ..utils.profiling import trace_batch

        # double-buffered dispatch: block N+1's device program is enqueued
        # before block N's results are fetched, so host finalization of N
        # overlaps device scoring of N+1 (SURVEY.md section 7 hard part 6)
        blocks = [
            records[start:start + _QUERY_BUCKETS[-1]]
            for start in range(0, len(records), _QUERY_BUCKETS[-1])
        ]
        pending = None
        if blocks:
            pending = self._scorers.dispatch_block(
                blocks[0], group_filtering=self.group_filtering
            )
        for bi, block in enumerate(blocks):
            t1 = time.monotonic()
            nxt = None
            if bi + 1 < len(blocks):
                nxt = self._scorers.dispatch_block(
                    blocks[bi + 1], group_filtering=self.group_filtering
                )
            with trace_batch(f"score_block[{len(block)}]"):
                result = resolve_block(pending)
            pending = nxt
            t2 = time.monotonic()
            self.stats.retrieval_seconds += t2 - t1

            if not self.finalize_survivors:
                continue
            if self.finalizer.device:
                # dd survivor rescore (ISSUE 12): one more collective-
                # free device program over the resolved (Q, K) pair
                # list; engine.finalize certifies verdicts against it
                # and skips the host compare for certified rejects
                result.dd = self._scorers.dd_rescore(result)
            # parallel host finalization: workers compute the exact f64
            # rescores (and the decisive-band skips) per query; events
            # then emit HERE, serially and in query order, so listener
            # streams and link rows are identical to the serial path at
            # any DUKE_FINALIZE_THREADS (engine.finalize)
            outcomes = self.finalizer.finalize_block(self, block, result)
            for qi, (record, out) in enumerate(zip(block, outcomes)):
                for event, candidate, prob in out.events:
                    self._emit(event, record, candidate, prob)
                if not out.events:
                    for listener in self.listeners:
                        listener.no_match_for(record)
                if out.decisions:
                    # drift monitors + sampled/latched ring records, on
                    # the serial event-coordinator thread (single-writer)
                    self.decisions.observe(
                        record, out.decisions, prune=out.prune,
                        margin=out.margin, host_bound=out.host_bound,
                    )
                self.stats.records_processed += 1
                self.stats.candidates_retrieved += out.survivors
                self.stats.pairs_rescored += out.rescored
                self.stats.pairs_skipped += out.skipped
                self.stats.pairs_device_certified += out.device_certified
                self.stats.dd_residue_margin += out.residue_margin
                self.stats.dd_residue_kind += out.residue_kind
                self.stats.dd_residue_truncation += out.residue_truncation
                if self.exhaustive:
                    # the device ran the exact comparator kernels against
                    # every live corpus row for this query
                    self.stats.pairs_compared += live_rows
                else:
                    # ANN: exact kernels ran only on the retrieved top-C
                    # (the retrieval matmul touches every row, but that is
                    # blocking work, not pair comparison)
                    self.stats.pairs_compared += int(
                        (result.top_index[qi] >= 0).sum()
                    )
            self.stats.compare_seconds += time.monotonic() - t2

    def _emit(self, event: str, r1: Record, r2: Record, prob: float) -> None:
        for listener in self.listeners:
            getattr(listener, event)(r1, r2, prob)
