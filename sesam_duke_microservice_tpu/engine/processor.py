"""The matching engine — host reference implementation.

Reproduces the Duke 1.2 ``Processor.deduplicate(List<Record>)`` contract the
reference drives for both workloads (App.java:1005, App.java:1159; SURVEY.md
section 3.2 call stack):

    batch_ready(n)
    index every record; commit the blocking database
    for each record: candidates = database.find_candidate_matches(record)
        for each candidate (skipping self): prob = compare(record, candidate)
            prob > threshold        -> matches()
            prob > maybe_threshold  -> matches_perhaps()
        no qualifying candidate     -> no_match_for()
    batch_done()

Pair probability: per comparison property, the max over value pairs of
``Property.compare_probability``, folded with naive Bayes from a 0.5 prior;
properties with no values on either side contribute nothing.

This host engine is the semantic oracle and CPU baseline.  The TPU path
(``engine.device_matcher``) replaces the inner loops with batched device
programs but must produce the same events; differential tests hold the two
together.  ``threads`` mirrors the reference's ``Processor.setThreads``
(App.java:344) by fanning the per-record loop over a thread pool.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from typing import List, Sequence

from ..core.bayes import combine_probabilities
from ..core.config import DukeSchema
from ..core.records import Record
from ..index.base import CandidateIndex
from ..telemetry import PhaseRecorder, costs, tracing
from .listeners import MatchListener

# Per-batch engine phases recorded into each processor's PhaseRecorder
# (surfaced as the duke_engine_phase_seconds histogram and the /stats
# phase_seconds map):
#   encode   — record indexing + index commit (feature extraction and
#              device upload live inside commit on device backends)
#   retrieve — candidate retrieval (host index walk / device scoring
#              program resolve)
#   score    — pair scoring + host finalization of survivors
#   persist  — listener batch_done work (link-database flush)
PHASE_ENCODE = "encode"
PHASE_RETRIEVE = "retrieve"
PHASE_SCORE = "score"
PHASE_PERSIST = "persist"


@dataclass
class ProfileStats:
    batches: int = 0
    records_processed: int = 0
    candidates_retrieved: int = 0
    pairs_compared: int = 0
    # host-finalization split (device backends): survivors rescored with
    # the exact f64 compare vs survivors skipped by decisive-band pruning
    # vs certified-rejected on device by the dd rescore (engine.finalize)
    # — always zero on the host engine, whose candidate loop has no
    # device pre-score to prune against
    pairs_rescored: int = 0
    pairs_skipped: int = 0
    pairs_device_certified: int = 0
    # dd residue attribution (ISSUE 12): why a rescored pair could not be
    # device-certified — ambiguous band (margin), tensor truncation, or a
    # schema with no dd-certifiable property at all (kind)
    dd_residue_margin: int = 0
    dd_residue_kind: int = 0
    dd_residue_truncation: int = 0
    retrieval_seconds: float = 0.0
    compare_seconds: float = 0.0

    def merge(self, other: "ProfileStats") -> None:
        self.batches += other.batches
        self.records_processed += other.records_processed
        self.candidates_retrieved += other.candidates_retrieved
        self.pairs_compared += other.pairs_compared
        self.pairs_rescored += other.pairs_rescored
        self.pairs_skipped += other.pairs_skipped
        self.pairs_device_certified += other.pairs_device_certified
        self.dd_residue_margin += other.dd_residue_margin
        self.dd_residue_kind += other.dd_residue_kind
        self.dd_residue_truncation += other.dd_residue_truncation
        self.retrieval_seconds += other.retrieval_seconds
        self.compare_seconds += other.compare_seconds


class Processor:
    def __init__(self, schema: DukeSchema, database: CandidateIndex,
                 *, group_filtering: bool = False, threads: int = 1,
                 profile: bool = False):
        from ..telemetry.decisions import DecisionRecorder
        from .explain import host_breakdown

        self.schema = schema
        self.database = database
        self.group_filtering = group_filtering
        self.threads = max(1, threads)
        self.profile = profile
        self.listeners: List[MatchListener] = []
        self.stats = ProfileStats()
        # single-writer (the workload lock serializes batches): plain
        # attribute math, no locks on the scoring path; /metrics and
        # /stats read it lock-free like the ProfileStats counters
        self.phases = PhaseRecorder()
        # decision monitors/ring (ISSUE 5): host pairs carry no device
        # pre-score, so only outcome counters, the pair-logit histogram
        # and sampled ring records apply; writes serialize on the
        # listener lock (the threaded per-record loop's existing
        # emission barrier)
        self.decisions = DecisionRecorder(
            schema.threshold, schema.maybe_threshold,
            breakdown=lambda q, c: host_breakdown(schema, q, c),
            # bare-compare embedders (the bench CPU baseline) pass no
            # database; sampled records then skip the breakdown
            resolver=(database.find_record_by_id
                      if database is not None else None),
        )
        self._listener_lock = threading.Lock()

    def add_match_listener(self, listener: MatchListener) -> None:
        self.listeners.append(listener)

    # -- pair scoring -------------------------------------------------------

    def compare(self, r1: Record, r2: Record) -> float:
        """Naive-Bayes pair probability over comparison properties."""
        probs = []
        for prop in self.schema.comparison_properties():
            vs1 = [v for v in r1.get_values(prop.name) if v]
            vs2 = [v for v in r2.get_values(prop.name) if v]
            if not vs1 or not vs2:
                continue
            best = 0.0
            for v1 in vs1:
                for v2 in vs2:
                    p = prop.compare_probability(v1, v2)
                    if p > best:
                        best = p
            probs.append(best)
        return combine_probabilities(probs)

    # -- batch processing ---------------------------------------------------

    def deduplicate(self, records: Sequence[Record]) -> None:
        for listener in self.listeners:
            listener.batch_ready(len(records))

        t0 = time.monotonic()
        with tracing.span(PHASE_ENCODE, {"records": len(records)}):
            for record in records:
                self.database.index(record)
            self.database.commit()
        t1 = time.monotonic()
        retrieval0 = self.stats.retrieval_seconds
        compare0 = self.stats.compare_seconds

        match_ns = time.monotonic_ns()
        if self.threads == 1:
            for record in records:
                self._match_record(record)
        else:
            # worker threads adopt the request's trace context so any
            # spans they open land in the same tree (tracing.attach)
            ctx = tracing.current_context()
            with ThreadPoolExecutor(max_workers=self.threads) as pool:
                list(pool.map(
                    lambda r: self._match_record_in_ctx(ctx, r), records))

        self.stats.batches += 1
        t2 = time.monotonic()
        with tracing.span(PHASE_PERSIST):
            for listener in self.listeners:
                listener.batch_done()
        # per-batch phase observations (per-record splits accumulated in
        # ProfileStats above; the histogram granule is the batch)
        retrieve_dt = self.stats.retrieval_seconds - retrieval0
        score_dt = self.stats.compare_seconds - compare0
        persist_dt = time.monotonic() - t2
        self.phases.observe(PHASE_ENCODE, t1 - t0)
        self.phases.observe(PHASE_RETRIEVE, retrieve_dt)
        self.phases.observe(PHASE_SCORE, score_dt)
        self.phases.observe(PHASE_PERSIST, persist_dt)
        # the same four durations feed the process-wide busy ledger, so
        # per-workload phase counters reconcile against it by definition
        costs.note_busy((t1 - t0) + retrieve_dt + score_dt + persist_dt)
        # retrieval and scoring interleave per record (and across the
        # thread pool): the shared aggregate-span layout
        tracing.add_phase_spans(match_ns, retrieve_dt, score_dt)

    def _match_record_in_ctx(self, ctx, record: Record) -> None:
        """Pool-thread entry: re-enter the submitting request's trace."""
        if ctx is None:
            self._match_record(record)
            return
        with tracing.attach(ctx):
            self._match_record(record)

    def _match_record(self, record: Record) -> None:
        t0 = time.monotonic()
        candidates = self.database.find_candidate_matches(
            record, group_filtering=self.group_filtering
        )
        t1 = time.monotonic()

        found = False
        threshold = self.schema.threshold
        maybe = self.schema.maybe_threshold
        pairs = 0
        scored = [] if self.decisions.enabled else None
        for candidate in candidates:
            if candidate.record_id == record.record_id:
                continue
            prob = self.compare(record, candidate)
            pairs += 1
            if scored is not None:
                scored.append((candidate.record_id, prob))
            if prob > threshold:
                found = True
                self._emit("matches", record, candidate, prob)
            elif maybe is not None and maybe != 0.0 and prob > maybe:
                found = True
                self._emit("matches_perhaps", record, candidate, prob)
        if not found:
            with self._listener_lock:
                for listener in self.listeners:
                    listener.no_match_for(record)
        if scored:
            # the recorder is single-writer; the listener lock is the
            # serialization point the threaded loop already has
            with self._listener_lock:
                self.decisions.observe_pairs(record, scored)

        t2 = time.monotonic()
        self.stats.records_processed += 1
        self.stats.candidates_retrieved += len(candidates)
        self.stats.pairs_compared += pairs
        self.stats.retrieval_seconds += t1 - t0
        self.stats.compare_seconds += t2 - t1

    def _emit(self, event: str, r1: Record, r2: Record, prob: float) -> None:
        with self._listener_lock:
            for listener in self.listeners:
                getattr(listener, event)(r1, r2, prob)
