"""Embedding-ANN matching backend: cosine blocking + exact rescoring.

The third blocking backend (after the host inverted index and the device
brute-force corpus): candidate retrieval is a cosine top-C search over
hashed-n-gram record embeddings (``ops.encoder``), and only the retrieved
candidates are scored with the exact per-property kernels
(``ops.scoring.build_ann_scorer``).  Per query the device work drops from
O(N * L^2) comparator FLOPs to O(N * D) matmul FLOPs + O(C * L^2)
rescoring — the configuration for corpora where brute force stops being
free (BASELINE.json configs[3-4]).

Semantics vs the brute-force backend: emitted probabilities for retrieved
pairs are identical (same exact rescoring + host finalization path through
``DeviceProcessor``); the candidate *set* is approximate, bounded below by
recall escalation — when every retrieved candidate clears the pruning
threshold the search re-runs with doubled C, so a saturated result can
never silently truncate.  Recall against brute force is measured in
``tests/test_ann.py`` and the bench harness, mirroring how the reference's
Lucene blocking bounds work per record via ``max_search_hits`` without a
recall guarantee (IncrementalLuceneDatabase.java:349-423).

The embedding matrix rides inside the ``DeviceCorpus`` feature tree as a
pseudo-property (``ops.encoder.ANN_PROP``), so append/growth/tombstone and
the incremental device-mirror update apply to it unchanged.
"""

from __future__ import annotations

import logging
from typing import Optional, Sequence

import numpy as np

from ..core.config import DukeSchema, MatchTunables
from ..core.records import Record
from ..ops import encoder as E
from ..telemetry.env import env_int
from .device_matcher import (
    DeviceIndex,
    DeviceProcessor,
    _BlockResult,
    _ScorerCache,
    _CHUNK,
)

logger = logging.getLogger("ann-matcher")

_ANN_DIM = env_int("DEVICE_ANN_DIM", 256)
_ANN_TOP_C = env_int("DEVICE_ANN_CANDIDATES", 64)


class AnnIndex(DeviceIndex):
    """``CandidateIndex`` with embedding-ANN candidate retrieval.

    Everything corpus-side is inherited from ``DeviceIndex``; this class
    adds the per-record embedding (computed at ingest, appended as a
    pseudo-property tensor) and swaps the scorer for the two-stage ANN
    program.
    """

    def __init__(self, schema: DukeSchema, *,
                 tunables: Optional[MatchTunables] = None,
                 values_per_record: Optional[int] = None,
                 dim: int = _ANN_DIM,
                 initial_top_c: int = _ANN_TOP_C):
        super().__init__(
            schema, tunables=tunables, values_per_record=values_per_record
        )
        self.dim = dim
        self.initial_top_c = initial_top_c
        self.encoder = E.RecordEncoder(schema, dim)
        # rides in the snapshot fingerprint: a pre-bf16 (f32) snapshot must
        # be rejected at load, or the first append would silently pin the
        # corpus to the old dtype and forfeit the HBM/bandwidth win
        self.emb_storage = str(np.dtype(E.STORAGE_DTYPE))

    def _extract(self, records: Sequence[Record], plan=None):
        # the embedding (E.STORAGE_DTYPE bf16 — see ops.encoder) rides
        # through extract_batch so feature + embedding extraction share
        # one entry point
        from ..ops import features as F

        return F.extract_batch(plan or self.plan, records,
                               encoder=self.encoder)

    @property
    def scorer_cache(self) -> "_AnnScorerCache":
        if self._scorer_cache is None:
            self._scorer_cache = _AnnScorerCache(self)
        return self._scorer_cache

    def explain_retrieval(self, record: Record, candidate: Record,
                          group_filtering: bool = False) -> dict:
        """ANN retrieval provenance (ISSUE 5): embedding cosine between
        the pair plus — when safe — the candidate's actual rank in the
        query's top-C retrieval.  The rank re-runs the two-stage scorer
        for this one query; in multi-host serving that would enqueue a
        device program followers never see (collective desync), so rank
        is skipped there and cosine alone is reported."""
        out = super().explain_retrieval(record, candidate, group_filtering)
        out["mode"] = "ann"
        out["exhaustive"] = False
        out["top_c"] = self.initial_top_c
        e1 = self.encoder.encode(record)
        e2 = self.encoder.encode(candidate)
        out["cosine"] = float(np.dot(e1, e2))  # encode() normalizes
        row = self.id_to_row.get(candidate.record_id)
        from ..parallel import dispatch

        if row is not None and dispatch.current() is None:
            result = self.scorer_cache.score_block(
                [record], group_filtering=group_filtering
            )
            positions = np.nonzero(result.top_index[0] == row)[0]
            if positions.size:
                out["rank"] = int(positions[0])
                out["retrieved"] = True
            else:
                out["rank"] = None
                out["retrieved"] = False
        return out


class _AnnScorerCache(_ScorerCache):
    """Caches jitted ANN scorers per (top_c, group_filtering) and runs the
    recall-escalation loop."""

    def _build(self, top_c: int, group_filtering: bool, from_rows: bool,
               plan=None):
        from ..ops import scoring as S

        return S.build_ann_scorer(
            plan or self.index.plan, chunk=_CHUNK, top_c=top_c,
            group_filtering=group_filtering, queries_from_rows=from_rows,
        )

    def _lower_one(self, row_feats, cap: int, bucket: int,
                   group_filtering: bool, *, from_rows: bool = True,
                   probe_feats=None, plan=None):
        """ANN pre-warm: the scorer signature carries the embedding matrix
        separately from the feature tree (see dispatch_block).  Covers both
        variants — from_rows=True (indexed batches gather on device) and
        from_rows=False (http-transform probes upload bucket-shaped
        qfeats + a (bucket, dim) query embedding)."""
        import jax

        row_feats = dict(row_feats)
        emb = row_feats.pop(E.ANN_PROP)[E.ANN_TENSOR]
        cfeats, (mb, mb2, mi, qg, qr, ml) = self._lower_args(
            row_feats, cap, bucket
        )
        corpus_emb = jax.ShapeDtypeStruct((cap,) + emb.shape[1:], emb.dtype)
        c = min(self.index.initial_top_c, cap)
        # private jit instance via the shared builder — see
        # _ScorerCache._lower_one
        scorer = self._build(c, group_filtering, from_rows, plan=plan)
        if from_rows:
            q_emb = jax.ShapeDtypeStruct((), np.float32)
            qfeats = {}
        else:
            pf = dict(probe_feats)
            pemb = pf.pop(E.ANN_PROP)[E.ANN_TENSOR]
            q_emb = jax.ShapeDtypeStruct(
                (bucket,) + pemb.shape[1:], pemb.dtype
            )
            qfeats = {
                prop: {
                    name: jax.ShapeDtypeStruct(
                        (bucket,) + arr.shape[1:], arr.dtype
                    )
                    for name, arr in tensors.items()
                }
                for prop, tensors in pf.items()
            }
        scorer.lower(
            q_emb, qfeats, corpus_emb, cfeats, mb, mb2, mi, qg, qr, ml
        ).compile()

    def dispatch_block(self, records: Sequence[Record], *,
                       group_filtering: bool):
        from ..ops import scoring as S
        import jax.numpy as jnp

        from .device_matcher import _PendingBlock

        index = self.index
        corpus = index.corpus
        n = len(records)
        min_logit = self._min_logit()

        if corpus.size == 0:
            return _BlockResult(
                np.full((n, 1), S.NEG_INF, np.float32),
                np.full((n, 1), -1, np.int32), min_logit,
            )

        qfeats, from_rows, query_row_j, query_group_j = self._prepare_queries(
            records, group_filtering
        )
        if from_rows:
            # gathered on device by the scorer; placeholder keeps the jit
            # signature stable for the cached from_rows variant
            q_emb = jnp.float32(0.0)
        else:
            q_emb = qfeats.pop(E.ANN_PROP)[E.ANN_TENSOR]

        cfeats_all, cvalid, cdeleted, cgroup = corpus.device_arrays()
        corpus_emb = cfeats_all[E.ANN_PROP][E.ANN_TENSOR]
        corpus_feats = {
            prop: tensors for prop, tensors in cfeats_all.items()
            if prop != E.ANN_PROP
        }

        def call(c):
            return self._scorer(c, group_filtering, from_rows)(
                q_emb, qfeats, corpus_emb, corpus_feats, cvalid, cdeleted,
                cgroup, query_group_j, query_row_j, jnp.float32(min_logit),
            )

        c = min(index.initial_top_c, corpus.capacity)
        # recall escalation: when every retrieved candidate cleared the
        # pruning bound the search saturated — double C so truncation can
        # never pass silently
        return _PendingBlock(
            corpus.capacity, n, min_logit, c, call,
            lambda cmax, cc: cmax >= cc, *call(c)
        )


class AnnProcessor(DeviceProcessor):
    """DeviceProcessor over an AnnIndex — the processor logic is identical
    (the index's scorer_cache supplies the ANN program); only the profiling
    semantics differ: pairs_compared counts rescored candidates, not the
    whole corpus."""

    exhaustive = False
