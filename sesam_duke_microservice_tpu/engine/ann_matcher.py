"""Embedding-ANN matching backend: cosine blocking + exact rescoring.

The third blocking backend (after the host inverted index and the device
brute-force corpus): candidate retrieval is a cosine top-C search over
hashed-n-gram record embeddings (``ops.encoder``), and only the retrieved
candidates are scored with the exact per-property kernels
(``ops.scoring.build_ann_scorer``).  Per query the device work drops from
O(N * L^2) comparator FLOPs to O(N * D) matmul FLOPs + O(C * L^2)
rescoring — the configuration for corpora where brute force stops being
free (BASELINE.json configs[3-4]).

Two multiplicative retrieval levers ride on top (ISSUE 9):

  * **int8 embedding storage** (``DUKE_EMB_INT8``): per-row symmetric
    int8 quantization with the scale vector as a second ANN_PROP tensor
    — half the embedding HBM and roughly double the retrieval matmul
    throughput, with the certified cosine error bound credited to the
    recall-escalation trigger (``ops.encoder.int8_cosine_eps``,
    ``ops.scoring.rescore_retrieved``);
  * **IVF clustered retrieval** (``DUKE_IVF``): k-means cells over the
    corpus embeddings with a two-stage cell-probe scan (``ops.ivf``) —
    ~10x fewer retrieval FLOPs at measured recall.  A saturated probe
    escalates ``nprobe`` in lockstep with the C ladder and finally falls
    back to the flat scan, so truncation can never pass silently.

Semantics vs the brute-force backend: emitted probabilities for retrieved
pairs are identical (same exact rescoring + host finalization path through
``DeviceProcessor``); the candidate *set* is approximate, bounded below by
recall escalation — when every retrieved candidate clears the pruning
threshold the search re-runs with doubled C, so a saturated result can
never silently truncate.  Recall against brute force is measured in
``tests/test_ann.py`` / ``tests/test_ivf.py`` and the bench harness,
mirroring how the reference's Lucene blocking bounds work per record via
``max_search_hits`` without a recall guarantee
(IncrementalLuceneDatabase.java:349-423).

The embedding matrix rides inside the ``DeviceCorpus`` feature tree as a
pseudo-property (``ops.encoder.ANN_PROP``), so append/growth/tombstone and
the incremental device-mirror update apply to it unchanged — including
the int8 scale vector.
"""

from __future__ import annotations

import logging
import time
from typing import Optional, Sequence

import numpy as np

from ..core.config import DukeSchema, MatchTunables
from ..core.records import Record
from ..ops import encoder as E
from ..ops import ivf as IVF
from ..telemetry.env import env_int
from .device_matcher import (
    DeviceIndex,
    DeviceProcessor,
    _BlockResult,
    _ScorerCache,
    _CHUNK,
)

logger = logging.getLogger("ann-matcher")

_ANN_DIM = env_int("DEVICE_ANN_DIM", 256)
_ANN_TOP_C = env_int("DEVICE_ANN_CANDIDATES", 64)


class AnnIndex(DeviceIndex):
    """``CandidateIndex`` with embedding-ANN candidate retrieval.

    Everything corpus-side is inherited from ``DeviceIndex``; this class
    adds the per-record embedding (computed at ingest, appended as a
    pseudo-property tensor) and swaps the scorer for the two-stage ANN
    program.
    """

    def __init__(self, schema: DukeSchema, *,
                 tunables: Optional[MatchTunables] = None,
                 values_per_record: Optional[int] = None,
                 dim: int = _ANN_DIM,
                 initial_top_c: int = _ANN_TOP_C):
        super().__init__(
            schema, tunables=tunables, values_per_record=values_per_record
        )
        self.dim = dim
        self.initial_top_c = initial_top_c
        self.encoder = E.RecordEncoder(schema, dim)
        # rides in the snapshot fingerprint: a snapshot written under a
        # different storage layout (pre-bf16 f32, or a DUKE_EMB_INT8
        # flip) must be rejected at load, or the first append would
        # silently mix dtypes in one corpus and forfeit the HBM win
        self.emb_storage = self.encoder.storage
        # IVF clustered retrieval (DUKE_IVF): resolved at construction;
        # trains lazily on the scoring path once the corpus crosses
        # DUKE_IVF_MIN_ROWS (ops.ivf — no lock of its own, the workload
        # lock already serializes every mutation site)
        self.ivf: Optional[IVF.IvfState] = (
            IVF.IvfState(nshards=self._ivf_shards()) if IVF.enabled()
            else None
        )

    def _ivf_shards(self) -> int:
        """Shard count for the IVF membership layout (the sharded index
        overrides with its mesh size)."""
        return 1

    def _extract(self, records: Sequence[Record], plan=None):
        # the embedding (bf16, or int8 + scale under DUKE_EMB_INT8 — see
        # ops.encoder) rides through extract_batch so feature + embedding
        # extraction share one entry point
        from ..ops import features as F

        return F.extract_batch(plan or self.plan, records,
                               encoder=self.encoder)

    @property
    def scorer_cache(self) -> "_AnnScorerCache":
        if self._scorer_cache is None:
            self._scorer_cache = _AnnScorerCache(self)
        return self._scorer_cache

    def explain_retrieval(self, record: Record, candidate: Record,
                          group_filtering: bool = False) -> dict:
        """ANN retrieval provenance (ISSUE 5, extended by ISSUE 9):
        embedding cosine between the pair plus — when safe — the
        candidate's actual rank in the query's top-C retrieval, the
        EFFECTIVE C after recall escalation (``initial_top_c`` alone
        understated what the search actually did), and under IVF the
        probed-cell list plus whether the candidate's cell was probed —
        the natural "why was this pair missed" answer.  The rank re-runs
        the two-stage scorer for this one query; in multi-host serving
        that would enqueue a device program followers never see
        (collective desync), so rank is skipped there and cosine alone
        is reported."""
        out = super().explain_retrieval(record, candidate, group_filtering)
        out["mode"] = "ann"
        out["exhaustive"] = False
        out["top_c"] = self.initial_top_c
        out["emb_storage"] = self.emb_storage
        e1 = self.encoder.encode(record)
        e2 = self.encoder.encode(candidate)
        out["cosine"] = float(np.dot(e1, e2))  # encode() normalizes
        row = self.id_to_row.get(candidate.record_id)
        from ..parallel import dispatch

        effective_c = None
        if row is not None and dispatch.current() is None:
            result = self.scorer_cache.score_block(
                [record], group_filtering=group_filtering
            )
            # the width the escalation loop actually finished at — the
            # truthful "how hard did retrieval look" figure
            effective_c = int(result.top_index.shape[1])
            out["effective_top_c"] = effective_c
            positions = np.nonzero(result.top_index[0] == row)[0]
            if positions.size:
                out["rank"] = int(positions[0])
                out["retrieved"] = True
            else:
                out["rank"] = None
                out["retrieved"] = False
        ivf = self.ivf
        if ivf is not None and ivf.ready:
            # host-side replay of the stage-1 probe (tiny: Q=1 x K) at
            # the EFFECTIVE escalated width — reporting the initial
            # nprobe could claim "cell not probed" for a pair the real
            # escalated (or flat-fallback) search did scan
            scores = e1 @ ivf.centroids.T
            nprobe = ivf.nprobe_for(
                effective_c if effective_c is not None
                else self.initial_top_c,
                self.initial_top_c,
            )
            probed = np.argsort(-scores, kind="stable")[:nprobe]
            out["ivf"] = {
                "cells": ivf.ncells,
                "nprobe": nprobe,
                # nprobe == ncells: the ladder ended in the flat scan,
                # every cell (hence every row) was scanned
                "flat_fallback": bool(nprobe >= ivf.ncells),
                "probed_cells": [int(c) for c in probed],
            }
            if row is not None and row < ivf.assigned_upto:
                cell = int(ivf.cell_of[row])
                out["ivf"]["candidate_cell"] = cell
                out["ivf"]["cell_probed"] = bool(cell in set(
                    int(c) for c in probed
                ))
        return out


class _AnnScorerCache(_ScorerCache):
    """Caches jitted ANN scorers per (top_c, group_filtering) and runs the
    recall-escalation loop — through the IVF cell-probe program when
    DUKE_IVF trained one, widening ``nprobe`` along the C ladder and
    falling back to the flat scan once every cell is probed."""

    escalation_stage = "top_c"
    # AOT store namespace (ISSUE 15): same ladder geometry as the corpus
    # scorer, different HLO — keys must never collide
    aot_builder = "ann"

    def _ladder_k(self, cap: int) -> int:
        return min(self.index.initial_top_c, cap)

    def _build(self, top_c: int, group_filtering: bool, from_rows: bool,
               plan=None):
        from ..ops import scoring as S

        return S.build_ann_scorer(
            plan or self.index.plan, chunk=_CHUNK, top_c=top_c,
            group_filtering=group_filtering, queries_from_rows=from_rows,
        )

    def _build_ivf(self, top_c: int, nprobe: int, group_filtering: bool,
                   from_rows: bool):
        return IVF.build_ivf_scorer(
            self.index.plan, top_c=top_c, nprobe=nprobe,
            group_filtering=group_filtering, queries_from_rows=from_rows,
        )

    def _ivf_scorer(self, top_c: int, nprobe: int, group_filtering: bool,
                    from_rows: bool):
        from ..utils.jit_cache import record_cache_hit, record_compile

        key = ("ivf", top_c, nprobe, group_filtering, from_rows)
        if key not in self._scorers:
            from ..telemetry import costs

            record_compile()
            t_compile = time.monotonic()
            self._scorers[key] = self._build_ivf(
                top_c, nprobe, group_filtering, from_rows
            )
            costs.note_compile(time.monotonic() - t_compile)
        else:
            record_cache_hit()
        return self._scorers[key]

    def _ivf_placers(self):
        """(place_centroids, place_cells) hooks for the IVF device
        tensors; None = default single-device placement.  The sharded
        cache overrides with replicated / record-axis-sharded placement."""
        return None, None

    def _ivf_ready(self):
        """Train/refresh/assign under the workload lock the dispatch
        path already holds; returns the ready IvfState or None."""
        ivf = self.index.ivf
        if ivf is None:
            return None
        return ivf if ivf.sync(self.index.corpus) else None

    def _lower_one(self, row_feats, cap: int, bucket: int,
                   group_filtering: bool, *, from_rows: bool = True,
                   probe_feats=None, plan=None):
        """ANN pre-warm: the scorer signature carries the embedding tree
        ({emb} or {emb, scale}) separately from the feature tree (see
        dispatch_block).  Covers both variants — from_rows=True (indexed
        batches gather on device) and from_rows=False (http-transform
        probes upload bucket-shaped qfeats + a bucket-sized query
        embedding tree).  The IVF program is deliberately NOT pre-warmed:
        its shapes depend on trained cell geometry, which only exists
        once data arrived."""
        row_feats = dict(row_feats)
        emb_tree = row_feats.pop(E.ANN_PROP)
        cfeats, (mb, mb2, mi, qg, qr, ml) = self._lower_args(
            row_feats, cap, bucket
        )
        corpus_tree = {
            name: self._sds((cap,) + arr.shape[1:], arr.dtype)
            for name, arr in emb_tree.items()
        }
        c = self._ladder_k(cap)
        # private jit instance via the shared builder — see
        # _ScorerCache._lower_one
        scorer = self._build(c, group_filtering, from_rows, plan=plan)
        if from_rows:
            q_emb = self._sds((), np.float32, "queries")
            qfeats = {}
        else:
            pf = dict(probe_feats)
            pemb = pf.pop(E.ANN_PROP)
            q_emb = {
                name: self._sds(
                    (bucket,) + arr.shape[1:], arr.dtype, "queries"
                )
                for name, arr in pemb.items()
            }
            qfeats = {
                prop: {
                    name: self._sds(
                        (bucket,) + arr.shape[1:], arr.dtype, "queries"
                    )
                    for name, arr in tensors.items()
                }
                for prop, tensors in pf.items()
            }
        return scorer.lower(
            q_emb, qfeats, corpus_tree, cfeats, mb, mb2, mi, qg, qr, ml
        ).compile()

    def dispatch_block(self, records: Sequence[Record], *,
                       group_filtering: bool):
        from ..ops import scoring as S
        import jax.numpy as jnp

        from .device_matcher import _PendingBlock

        index = self.index
        corpus = index.corpus
        n = len(records)
        min_logit = self._min_logit()

        if corpus.size == 0:
            return _BlockResult(
                np.full((n, 1), S.NEG_INF, np.float32),
                np.full((n, 1), -1, np.int32), min_logit,
            )

        qfeats, from_rows, query_row_j, query_group_j = self._prepare_queries(
            records, group_filtering
        )
        bucket = int(query_row_j.shape[0])
        if from_rows:
            # gathered on device by the scorer; placeholder keeps the jit
            # signature stable for the cached from_rows variant
            q_emb = jnp.float32(0.0)
        else:
            q_emb = qfeats.pop(E.ANN_PROP)

        cfeats_all, cvalid, cdeleted, cgroup = corpus.device_arrays()
        emb_tree = cfeats_all[E.ANN_PROP]
        corpus_feats = {
            prop: tensors for prop, tensors in cfeats_all.items()
            if prop != E.ANN_PROP
        }

        # lazy IVF maintenance (train on first crossing, assign appended
        # slices, refresh on doubling) — runs under the workload lock the
        # dispatch path holds, so no trainer lock exists
        ivf = self._ivf_ready()

        c0 = min(index.initial_top_c, corpus.capacity)

        def call(c):
            if ivf is not None:
                nprobe = ivf.nprobe_for(c, c0)
                if nprobe < ivf.ncells:
                    pc, pk = self._ivf_placers()
                    cents, cells = ivf.device_tensors(pc, pk)
                    return self._ivf_scorer(
                        c, nprobe, group_filtering, from_rows
                    )(
                        q_emb, qfeats, emb_tree, cents, cells, corpus_feats,
                        cvalid, cdeleted, cgroup, query_group_j, query_row_j,
                        jnp.float32(min_logit),
                    )
                # every cell probed: the probe degenerated to a worse
                # flat scan — fall back to the real one (today's path),
                # preserving the "escalation ends in exhaustive
                # retrieval" contract
            flat_args = (
                q_emb, qfeats, emb_tree, corpus_feats, cvalid, cdeleted,
                cgroup, query_group_j, query_row_j, jnp.float32(min_logit),
            )
            # AOT fast path (ISSUE 15) — flat-scan ladder only: the IVF
            # program's shapes depend on trained cell geometry, which
            # only exists once data arrived, so it is never stored
            out = self.aot_call(c, group_filtering, from_rows, bucket,
                                flat_args)
            if out is not None:
                return out
            return self._scorer(c, group_filtering, from_rows)(*flat_args)

        # recall escalation: when every retrieved candidate cleared the
        # pruning bound (or sat inside the int8 ambiguity band at the
        # cutoff) the search saturated — double C (and, under IVF,
        # nprobe) so truncation can never pass silently
        pending = _PendingBlock(
            corpus.capacity, n, min_logit, c0, call,
            lambda cmax, cc: cmax >= cc, *call(c0),
            stage="ivf" if ivf is not None else self.escalation_stage,
        )
        # dd rescore context (ISSUE 12): the kernel feature tensors only
        # (the ANN_PROP embedding tree was already split off above)
        pending.dd_ctx = (qfeats, from_rows, query_row_j)
        return pending


class AnnProcessor(DeviceProcessor):
    """DeviceProcessor over an AnnIndex — the processor logic is identical
    (the index's scorer_cache supplies the ANN program); only the profiling
    semantics differ: pairs_compared counts rescored candidates, not the
    whole corpus."""

    exhaustive = False
