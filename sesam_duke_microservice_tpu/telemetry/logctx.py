"""Structured logging context: per-request ids.

The HTTP handler stamps each request with a short id
(``new_request_id``) and sets it in a ``contextvars.ContextVar``.  The
handler thread runs the whole request — parse, workload lock, engine
batch, response — so every log line the request produces (including
engine lines like the escalation/prewarm logs) can carry the id with
zero plumbing: ``RequestIdFilter`` injects ``record.request_id`` from
the context var into every record passing a handler.

``install()`` attaches the filter to the root logger's handlers and is
idempotent; the service CLI calls it with a format that includes
``%(request_id)s``.  Library users who never install it see no change
(the filter only adds an attribute; no format references it).

Caveat (documented, deliberate): ingest microbatching means the thread
that wins the workload lock processes every queued request's batch as
one merged device batch — engine lines for a merged batch carry the
LEADER request's id.  The HTTP-layer lines (one per request) always
carry their own.
"""

from __future__ import annotations

import contextvars
import logging
import secrets

# "-" (not empty) so %(request_id)s renders something greppable for
# lines produced outside any request (startup, background prewarm)
request_id_var: contextvars.ContextVar = contextvars.ContextVar(
    "duke_request_id", default="-"
)


def new_request_id() -> str:
    return secrets.token_hex(6)


def current_request_id() -> str:
    return request_id_var.get()


class RequestIdFilter(logging.Filter):
    """Injects ``record.request_id`` from the context var (always passes)."""

    def filter(self, record: logging.LogRecord) -> bool:
        record.request_id = request_id_var.get()
        return True


_FILTER = RequestIdFilter()

DEFAULT_FORMAT = (
    "%(asctime)s %(levelname)s %(name)s [%(request_id)s] %(message)s"
)


def install(fmt: str = DEFAULT_FORMAT) -> None:
    """Attach the request-id filter (and format) to the root handlers.

    Idempotent.  Call AFTER logging.basicConfig — with no handlers yet
    this configures one, so the CLI can call just ``install()``.
    """
    root = logging.getLogger()
    if not root.handlers:
        logging.basicConfig(level=logging.INFO, format=fmt)
    for handler in root.handlers:
        if _FILTER not in handler.filters:
            handler.addFilter(_FILTER)
        if fmt is not None:
            handler.setFormatter(logging.Formatter(fmt))
