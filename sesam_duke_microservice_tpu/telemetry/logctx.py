"""Structured logging context: per-request ids + trace correlation.

The HTTP handler stamps each request with a short id
(``new_request_id``) and sets it in a ``contextvars.ContextVar``.  The
handler thread runs the whole request — parse, workload lock, engine
batch, response — so every log line the request produces (including
engine lines like the escalation/prewarm logs) can carry the id with
zero plumbing: ``RequestIdFilter`` injects ``record.request_id`` from
the context var into every record passing a handler.  The filter also
injects ``record.trace_id`` from the tracer's active context
(telemetry.tracing), so a probe-failure or SLO-violation log line joins
directly against ``/debug/traces/<id>`` — logs↔traces forensics without
any call-site plumbing.

``install()`` attaches the filter to the root logger's handlers and is
idempotent; the service CLI calls it with a format that includes
``%(request_id)s``.  Library users who never install it see no change
(the filter only adds attributes; no format references them).  With
``DUKE_LOG_JSON=1`` the installed formatter emits one JSON object per
line (ts/level/logger/message/request_id/trace_id) for log pipelines
that ingest structured streams.

Caveat (documented, deliberate): ingest microbatching means the thread
that wins the workload lock processes every queued request's batch as
one merged device batch — engine lines for a merged batch carry the
LEADER request's id.  The HTTP-layer lines (one per request) always
carry their own.
"""

from __future__ import annotations

import contextvars
import json
import logging
import secrets
import time

# "-" (not empty) so %(request_id)s renders something greppable for
# lines produced outside any request (startup, background prewarm)
request_id_var: contextvars.ContextVar = contextvars.ContextVar(
    "duke_request_id", default="-"
)


def new_request_id() -> str:
    return secrets.token_hex(6)


def current_request_id() -> str:
    return request_id_var.get()


class RequestIdFilter(logging.Filter):
    """Injects ``record.request_id`` and ``record.trace_id`` from the
    ambient contexts (always passes)."""

    def filter(self, record: logging.LogRecord) -> bool:
        record.request_id = request_id_var.get()
        # lazy import: tracing imports this module for its request-id
        # join; the filter closes the other direction of the loop
        from . import tracing

        record.trace_id = tracing.current_trace_id() or "-"
        return True


_FILTER = RequestIdFilter()

DEFAULT_FORMAT = (
    "%(asctime)s %(levelname)s %(name)s [%(request_id)s %(trace_id)s] "
    "%(message)s"
)


class JsonFormatter(logging.Formatter):
    """One JSON object per line, carrying the correlation ids the filter
    injected.  Opt-in via ``DUKE_LOG_JSON=1`` (see ``install``)."""

    def format(self, record: logging.LogRecord) -> str:
        out = {
            "ts": round(record.created, 3),
            "time": time.strftime(
                "%Y-%m-%dT%H:%M:%S", time.gmtime(record.created))
            + f".{int(record.msecs):03d}Z",
            "level": record.levelname,
            "logger": record.name,
            "message": record.getMessage(),
            "request_id": getattr(record, "request_id", "-"),
            "trace_id": getattr(record, "trace_id", "-"),
        }
        if record.exc_info:
            out["exc_info"] = self.formatException(record.exc_info)
        return json.dumps(out, separators=(",", ":"))


def _json_enabled() -> bool:
    from .env import env_flag

    return env_flag("DUKE_LOG_JSON", False)


def install(fmt: str = DEFAULT_FORMAT) -> None:
    """Attach the correlation filter (and format) to the root handlers.

    Idempotent.  Call AFTER logging.basicConfig — with no handlers yet
    this configures one, so the CLI can call just ``install()``.  With
    ``DUKE_LOG_JSON=1`` a ``JsonFormatter`` replaces the line format.
    """
    root = logging.getLogger()
    if not root.handlers:
        logging.basicConfig(level=logging.INFO, format=fmt)
    formatter: logging.Formatter
    if _json_enabled():
        formatter = JsonFormatter()
    else:
        formatter = logging.Formatter(fmt)
    for handler in root.handlers:
        if _FILTER not in handler.filters:
            handler.addFilter(_FILTER)
        if fmt is not None:
            handler.setFormatter(formatter)
