"""Device-time cost ledger (ISSUE 17 tentpole a).

Answers "who is spending the hardware" from measurements the engine
already takes: every batch's per-phase durations (encode / retrieve /
score / persist — ``engine.processor`` and ``engine.device_matcher``
both observe them into the workload's ``PhaseRecorder``) are ALSO summed
into this process-wide busy ledger by ``note_busy``, called once per
batch by the thread that measured them.  Compile time from the jit/AOT
warm paths accumulates separately through ``note_compile`` (compiles
overlap serving on the warm thread, so they are amortized capacity
spend, not batch latency).

Attribution invariant (proven by test): the per-workload × per-phase
``duke_cost_device_seconds_total`` counters — emitted at scrape time
from the same PhaseRecorders — sum to ``busy_seconds_total()`` within
float tolerance, because ``note_busy`` receives exactly the four phase
durations each batch observed.  The ledger survives config reloads (it
is process-global) while PhaseRecorders die with their workloads, so
``/debug/costs`` reports the residual as ``unattributed_seconds``
instead of pretending the books always balance.

Utilization: ``duke_device_utilization`` = busy seconds inside a
sliding window / window wall time — the busy fraction the autoscaler
(ROADMAP item 3) reads for scale-down headroom.  The window is a slot
ring like ``slo.SloTracker``'s, recomputed exactly at scrape.

Locking: one leaf lock, taken once per BATCH (never per record/pair)
and once per scrape — the same budget the SLO trackers spend.  The
bench's attribution-off arm calls ``configure(False)``; disabled,
``note_busy`` is one module-global read and a return.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from .registry import FamilySnapshot

# busy-fraction window: 12 slots of 5 s = a 60 s sliding window (short
# enough to track load swings, long enough to smooth batch granularity)
WINDOW_S = 60.0
_SLOT_S = 5.0
_N_SLOTS = int(WINDOW_S / _SLOT_S) + 1

_lock = threading.Lock()
_enabled = True  # guarded by: _lock [writes]
_busy_total = 0.0  # guarded by: _lock
_compile_total = 0.0  # guarded by: _lock
# [slot_index, busy_seconds] per 5 s slot, lazily recycled
_slots: List[List[float]] = [[-1, 0.0] for _ in range(_N_SLOTS)]  # guarded by: _lock
_started = time.monotonic()


def configure(enabled: bool) -> None:
    """Runtime toggle (the bench's attribution-off arm)."""
    global _enabled
    with _lock:
        _enabled = bool(enabled)


def enabled() -> bool:
    return _enabled


def note_busy(seconds: float, now: Optional[float] = None) -> None:
    """Credit one batch's measured device-busy seconds (the sum of its
    four phase durations).  Called once per batch by the thread that
    holds the workload lock — the leaf ``_lock`` nests under it but
    never the reverse."""
    if not _enabled or seconds <= 0.0:
        return
    now = time.monotonic() if now is None else now
    slot_idx = int(now // _SLOT_S)
    global _busy_total
    with _lock:
        _busy_total += seconds
        cell = _slots[slot_idx % _N_SLOTS]
        if cell[0] != slot_idx:
            cell[0], cell[1] = slot_idx, 0.0
        cell[1] += seconds


def note_compile(seconds: float) -> None:
    """Credit one scorer build/lowering pass (jit miss or AOT warm)."""
    if not _enabled or seconds <= 0.0:
        return
    global _compile_total
    with _lock:
        _compile_total += seconds


def busy_seconds_total() -> float:
    with _lock:
        return _busy_total


def compile_seconds_total() -> float:
    with _lock:
        return _compile_total


def utilization(now: Optional[float] = None) -> float:
    """Busy fraction over the sliding window (clamped to uptime so a
    fresh process is not under-reported against a window it has not
    lived through yet)."""
    now = time.monotonic() if now is None else now
    window = min(WINDOW_S, max(now - _started, _SLOT_S))
    min_slot = int((now - window) // _SLOT_S)
    with _lock:
        busy = sum(c[1] for c in _slots if c[0] >= min_slot)
    return min(1.0, busy / window)


def snapshot(now: Optional[float] = None) -> Dict[str, object]:
    """Process-wide ledger state for ``/debug/costs``."""
    now = time.monotonic() if now is None else now
    with _lock:
        busy, comp = _busy_total, _compile_total
        on = _enabled
    return {
        "enabled": on,
        "busy_seconds_total": round(busy, 6),
        "compile_seconds_total": round(comp, 6),
        "utilization": round(utilization(now), 6),
        "window_seconds": WINDOW_S,
    }


def _reset_for_tests() -> None:
    global _busy_total, _compile_total, _enabled, _started
    with _lock:
        _busy_total = 0.0
        _compile_total = 0.0
        _enabled = True
        for cell in _slots:
            cell[0], cell[1] = -1, 0.0
        _started = time.monotonic()


def collect() -> List[FamilySnapshot]:
    """Scrape-time collector (registered on ``telemetry.GLOBAL``, so
    every plane that renders GLOBAL serves the ledger)."""
    with _lock:
        busy, comp = _busy_total, _compile_total
    return [
        FamilySnapshot(
            "duke_cost_busy_seconds_total", "counter",
            "Measured device-busy seconds across all workloads (each "
            "batch's four phase durations, summed once per batch); the "
            "reconciliation target for duke_cost_device_seconds_total",
            [("", (), busy)]),
        FamilySnapshot(
            "duke_cost_compile_seconds_total", "counter",
            "Seconds spent building scorer programs (jit-cache misses "
            "and AOT warm-thread lowering) — amortized capacity spend "
            "that overlaps serving",
            [("", (), comp)]),
        FamilySnapshot(
            "duke_device_utilization", "gauge",
            "Busy device-seconds / wall over a sliding 60 s window "
            "(the autoscaler's busy-fraction headroom signal)",
            [("", (), utilization())]),
    ]
