"""HBM ledger: registration-based device-buffer accounting (ISSUE 17
tentpole b).

Owners of device-resident state register a components callable
(``register(owner, kind, name, fn)`` — ``fn() -> {component: bytes}``)
held through a weakref, so a reloaded-away workload drops out of the
books automatically.  ``engine.workload.Workload`` registers its corpus
tensors / int8 scales / IVF membership at construction; process-wide
components (the digest-keyed feature cache, the on-disk AOT executable
store) are computed here.

Scrape surfaces:

  * per-workload ``duke_device_bytes{kind,workload,component}`` — emitted
    by the app/group collectors (service/metrics.py) so the federation
    rollup relabels them per group, exactly like every other workload
    gauge; the collectors read this ledger via ``components_for``.
  * process-wide ``duke_device_bytes{component}`` (feature cache, AOT
    store), ``duke_device_headroom_bytes`` and
    ``duke_device_overflow_days`` — emitted by ``collect`` on
    ``telemetry.GLOBAL`` (one device budget per process, so headroom is
    process-scoped even when N federation groups share the process).

Headroom = budget − total registered bytes.  The budget resolves from
``DUKE_HBM_BUDGET_MB``, else the backend's reported ``bytes_limit``
(``Device.memory_stats``), else a documented 16 GiB default.  The
overflow forecast extrapolates the corpus-byte growth rate observed
across scrapes: days-to-overflow = headroom / (bytes per day); -1 means
"no growth observed" (never extrapolate from silence).

All byte math reads single-writer numpy mirrors lock-free (torn reads
tolerated — the /stats stance); the ledger's own dict is guarded by a
leaf lock taken only at register/scrape time.
"""

from __future__ import annotations

import os
import threading
import time
import weakref
from collections import deque
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from .env import env_int
from .registry import FamilySnapshot, add_render_hook

DEFAULT_BUDGET_BYTES = 16 << 30  # 16 GiB: one modern accelerator's HBM

_REG_LOCK = threading.Lock()
# id(owner) -> (weakref(owner), kind, name, components_fn, logical)
_ENTRIES: Dict[int, tuple] = {}  # guarded by: _REG_LOCK [writes]
# (unix_ts, corpus_bytes) scrape-time samples driving the growth forecast
_growth: deque = deque(maxlen=256)  # guarded by: _REG_LOCK


def register(owner: object, kind: str, name: str,
             fn: Callable[[], Dict[str, int]], *,
             logical: bool = False) -> None:
    """Enroll ``owner``'s device buffers; ``fn`` must be lock-free and
    must not strongly reference ``owner`` (close over a weakref).

    ``logical`` marks a per-tenant VIEW of bytes whose physical owner is
    registered elsewhere (ISSUE 19: arena-enabled workloads view corpus
    slabs the arena attributes once) — logical arena-tier components are
    reported per owner for attribution but excluded from the budget
    totals, so shared slabs are never double-counted against headroom."""
    key = id(owner)
    with _REG_LOCK:
        _ENTRIES[key] = (weakref.ref(owner), kind, name, fn, logical)


def _iter_live() -> List[Tuple[str, str, object, Callable, bool]]:
    """Live registrations, pruning dead/closed owners in passing."""
    out = []
    with _REG_LOCK:
        items = list(_ENTRIES.items())
    dead = []
    for key, (ref, kind, name, fn, logical) in items:
        owner = ref()
        if owner is None:
            dead.append(key)
            continue
        if getattr(owner, "closed", False):
            continue  # replaced by reload; the weakref reaps it later
        out.append((kind, name, owner, fn, logical))
    if dead:
        with _REG_LOCK:
            for key in dead:
                _ENTRIES.pop(key, None)
    return out


# -- once-per-scrape ledger pass (ISSUE 19 satellite) -------------------------
#
# The app/group collectors call components_for() per workload and the
# GLOBAL collector walks the whole ledger again in the SAME render — at
# 200 tenants that is 400+ component-callable evaluations per scrape.
# registry.render() brackets every scrape with the hooks below; inside
# a bracket the FIRST ledger read evaluates every callable once into a
# thread-local snapshot and every later read (either API) serves from
# it.  Direct calls outside a render (debug endpoints, tests) see no
# cache at all — no staleness window exists.

_PASS = threading.local()


def _begin_render() -> None:
    _PASS.active = True
    _PASS.snapshot = None


def _end_render() -> None:
    _PASS.active = False
    _PASS.snapshot = None


add_render_hook(_begin_render, _end_render)


def _eval_components(fn: Callable) -> Dict[str, float]:
    try:
        return {k: float(v) for k, v in fn().items() if v}
    except Exception:
        return {}  # a mid-mutation read must never fail a scrape


def _ledger_pass() -> Dict[int, tuple]:
    """id(owner) -> (kind, name, components, logical) — ONE evaluation
    of every live registration, cached for the duration of the active
    render (none active: computed fresh, never cached)."""
    snapshot = (getattr(_PASS, "snapshot", None)
                if getattr(_PASS, "active", False) else None)
    if snapshot is not None:
        return snapshot
    snapshot = {
        id(owner): (kind, name, _eval_components(fn), logical)
        for kind, name, owner, fn, logical in _iter_live()
    }
    if getattr(_PASS, "active", False):
        _PASS.snapshot = snapshot
    return snapshot


def components_for(owner: object) -> Dict[str, float]:
    """One owner's current component bytes (empty if unregistered) —
    the app/group collectors' per-workload read."""
    if getattr(_PASS, "active", False):
        entry = _ledger_pass().get(id(owner))
        return dict(entry[2]) if entry is not None else {}
    with _REG_LOCK:
        entry = _ENTRIES.get(id(owner))
    if entry is None:
        return {}
    return _eval_components(entry[3])


def process_components() -> Dict[str, float]:
    """Process-wide device/pinned buffers outside any workload."""
    out: Dict[str, float] = {}
    try:
        from ..ops import feature_cache as FC

        out["feature_cache"] = float(FC.stats()[3])
    except Exception:
        pass
    try:
        from ..utils.jit_cache import aot_dir

        total = 0
        with os.scandir(aot_dir()) as it:
            for entry in it:
                if entry.name.endswith(".aotx"):
                    total += entry.stat().st_size
        out["aot_executables"] = float(total)
    except OSError:
        pass  # store not created yet
    except Exception:
        pass
    return out


def budget_bytes() -> Tuple[float, str]:
    """(bytes, source) — DUKE_HBM_BUDGET_MB, else the backend's
    reported limit, else the documented default."""
    mb = env_int("DUKE_HBM_BUDGET_MB", 0)
    if mb > 0:
        return float(mb) * 1024 * 1024, "env"
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats()
        limit = (stats or {}).get("bytes_limit")
        if limit:
            return float(limit), "device"
    except Exception:
        pass
    return float(DEFAULT_BUDGET_BYTES), "default"


_CORPUS_COMPONENTS = ("corpus_tensors", "corpus_embeddings", "int8_scales",
                      "ivf_membership")
# components an arena-enabled workload only VIEWS (the arena owns the
# physical device bytes and attributes them once): excluded from the
# budget totals when the registration is logical.  ivf_membership stays
# physical either way — the arena does not manage IVF uploads.
_ARENA_VIEW_COMPONENTS = ("corpus_tensors", "corpus_embeddings",
                          "int8_scales")


def _totals(now_unix: Optional[float] = None
            ) -> Tuple[float, float, List[Tuple[str, str, str, float]]]:
    """(total_bytes, corpus_bytes, [(kind, name, component, bytes)]) and
    feed the growth ring with the corpus share.  Logical registrations'
    arena-tier components appear in the rows (per-tenant attribution)
    but never in the totals — the arena's own registration carries the
    physical bytes exactly once."""
    rows: List[Tuple[str, str, str, float]] = []
    total = 0.0
    corpus = 0.0
    for kind, name, comps, logical in _ledger_pass().values():
        for comp, nbytes in sorted(comps.items()):
            rows.append((kind, name, comp, nbytes))
            if logical and comp in _ARENA_VIEW_COMPONENTS:
                continue  # a view: the arena row already counted it
            total += nbytes
            if comp in _CORPUS_COMPONENTS:
                corpus += nbytes
    for comp, nbytes in sorted(process_components().items()):
        rows.append(("process", "", comp, nbytes))
        total += nbytes
    now_unix = time.time() if now_unix is None else now_unix
    with _REG_LOCK:
        if not _growth or _growth[-1][1] != corpus:
            _growth.append((now_unix, corpus))
    return total, corpus, rows


def growth_bytes_per_day() -> float:
    """Corpus-byte growth rate across observed scrapes (0 until two
    distinct observations exist)."""
    with _REG_LOCK:
        if len(_growth) < 2:
            return 0.0
        (t0, b0), (t1, b1) = _growth[0], _growth[-1]
    dt = t1 - t0
    if dt <= 0 or b1 <= b0:
        return 0.0
    return (b1 - b0) / dt * 86400.0


def overflow_days(headroom: float) -> float:
    """Days until the corpus growth rate consumes ``headroom``; -1 when
    no growth has been observed (never extrapolate from silence)."""
    rate = growth_bytes_per_day()
    if rate <= 0.0:
        return -1.0
    return max(0.0, headroom) / rate


def live_arrays_bytes() -> Optional[int]:
    """Backend cross-check: total bytes of all live jax arrays, or None
    where the backend/API does not support it."""
    try:
        import jax

        return int(sum(getattr(a, "nbytes", 0) for a in jax.live_arrays()))
    except Exception:
        return None


def debug_snapshot() -> Dict[str, object]:
    """``GET /debug/memory`` payload.

    The ``jax.live_arrays()`` cross-check reconciles against the
    PHYSICAL total only: arena-enabled workloads' corpus rows are
    logical views (their ``logical`` flag marks them here), and the
    backend's live arrays correspond to the arena's resident tier plus
    the non-logical registrations — spilled tenants' mirrors are host
    numpy, invisible to both sides of the check by construction."""
    budget, source = budget_bytes()
    total, corpus, rows = _totals()
    logical_owners = {
        (kind, name) for kind, name, _comps, logical
        in _ledger_pass().values() if logical
    }
    headroom = budget - total
    out = {
        "budget_bytes": int(budget),
        "budget_source": source,
        "total_bytes": int(total),
        "corpus_bytes": int(corpus),
        "headroom_bytes": int(headroom),
        "growth_bytes_per_day": round(growth_bytes_per_day(), 3),
        "overflow_days": round(overflow_days(headroom), 3),
        "workloads": [
            {"kind": kind, "workload": name, "component": comp,
             "bytes": int(nbytes),
             # the marker appears ONLY on arena-view rows so legacy
             # (non-arena) rows keep their exact shape
             **({"logical": True}
                if (kind, name) in logical_owners
                and comp in _ARENA_VIEW_COMPONENTS else {})}
            for kind, name, comp, nbytes in rows if kind != "process"
        ],
        "process": {comp: int(nbytes)
                    for kind, _n, comp, nbytes in rows if kind == "process"},
        "live_arrays_bytes": live_arrays_bytes(),
    }
    try:
        from ..ops import arena

        out["arena"] = arena.ARENA.debug_snapshot()
    except Exception:
        pass  # arena import must never fail the debug endpoint
    return out


def _reset_for_tests() -> None:
    # NOTE: this also drops the arena's import-time enrollment; tests
    # that assert arena attribution re-enroll via arena._enroll_ledger()
    with _REG_LOCK:
        _ENTRIES.clear()
        _growth.clear()


def collect() -> List[FamilySnapshot]:
    """Scrape-time collector (registered on ``telemetry.GLOBAL``):
    process-component bytes + the headroom/forecast gauges.  The
    per-workload ``duke_device_bytes`` samples come from the app/group
    collectors so the federation rollup can relabel them per group."""
    budget, _source = budget_bytes()
    total, _corpus, rows = _totals()
    headroom = budget - total
    proc_samples = [("", (("component", comp),), nbytes)
                    for kind, _n, comp, nbytes in rows if kind == "process"]
    return [
        FamilySnapshot(
            "duke_device_bytes", "gauge",
            "Registered device-buffer bytes by component (per-workload "
            "series carry kind/workload labels; process-wide components "
            "— feature cache, AOT executable store — carry only "
            "component)", proc_samples),
        FamilySnapshot(
            "duke_device_headroom_bytes", "gauge",
            "HBM budget (DUKE_HBM_BUDGET_MB, else the backend's "
            "bytes_limit, else 16 GiB) minus all registered device "
            "bytes", [("", (), headroom)]),
        FamilySnapshot(
            "duke_device_overflow_days", "gauge",
            "Days until the observed corpus-byte growth rate consumes "
            "the headroom (-1 = no growth observed yet)",
            [("", (), overflow_days(headroom))]),
    ]
