"""HBM ledger: registration-based device-buffer accounting (ISSUE 17
tentpole b).

Owners of device-resident state register a components callable
(``register(owner, kind, name, fn)`` — ``fn() -> {component: bytes}``)
held through a weakref, so a reloaded-away workload drops out of the
books automatically.  ``engine.workload.Workload`` registers its corpus
tensors / int8 scales / IVF membership at construction; process-wide
components (the digest-keyed feature cache, the on-disk AOT executable
store) are computed here.

Scrape surfaces:

  * per-workload ``duke_device_bytes{kind,workload,component}`` — emitted
    by the app/group collectors (service/metrics.py) so the federation
    rollup relabels them per group, exactly like every other workload
    gauge; the collectors read this ledger via ``components_for``.
  * process-wide ``duke_device_bytes{component}`` (feature cache, AOT
    store), ``duke_device_headroom_bytes`` and
    ``duke_device_overflow_days`` — emitted by ``collect`` on
    ``telemetry.GLOBAL`` (one device budget per process, so headroom is
    process-scoped even when N federation groups share the process).

Headroom = budget − total registered bytes.  The budget resolves from
``DUKE_HBM_BUDGET_MB``, else the backend's reported ``bytes_limit``
(``Device.memory_stats``), else a documented 16 GiB default.  The
overflow forecast extrapolates the corpus-byte growth rate observed
across scrapes: days-to-overflow = headroom / (bytes per day); -1 means
"no growth observed" (never extrapolate from silence).

All byte math reads single-writer numpy mirrors lock-free (torn reads
tolerated — the /stats stance); the ledger's own dict is guarded by a
leaf lock taken only at register/scrape time.
"""

from __future__ import annotations

import os
import threading
import time
import weakref
from collections import deque
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from .env import env_int
from .registry import FamilySnapshot

DEFAULT_BUDGET_BYTES = 16 << 30  # 16 GiB: one modern accelerator's HBM

_REG_LOCK = threading.Lock()
# id(owner) -> (weakref(owner), kind, name, components_fn)
_ENTRIES: Dict[int, tuple] = {}  # guarded by: _REG_LOCK [writes]
# (unix_ts, corpus_bytes) scrape-time samples driving the growth forecast
_growth: deque = deque(maxlen=256)  # guarded by: _REG_LOCK


def register(owner: object, kind: str, name: str,
             fn: Callable[[], Dict[str, int]]) -> None:
    """Enroll ``owner``'s device buffers; ``fn`` must be lock-free and
    must not strongly reference ``owner`` (close over a weakref)."""
    key = id(owner)
    with _REG_LOCK:
        _ENTRIES[key] = (weakref.ref(owner), kind, name, fn)


def _iter_live() -> List[Tuple[str, str, object, Callable]]:
    """Live registrations, pruning dead/closed owners in passing."""
    out = []
    with _REG_LOCK:
        items = list(_ENTRIES.items())
    dead = []
    for key, (ref, kind, name, fn) in items:
        owner = ref()
        if owner is None:
            dead.append(key)
            continue
        if getattr(owner, "closed", False):
            continue  # replaced by reload; the weakref reaps it later
        out.append((kind, name, owner, fn))
    if dead:
        with _REG_LOCK:
            for key in dead:
                _ENTRIES.pop(key, None)
    return out


def components_for(owner: object) -> Dict[str, float]:
    """One owner's current component bytes (empty if unregistered) —
    the app/group collectors' per-workload read."""
    with _REG_LOCK:
        entry = _ENTRIES.get(id(owner))
    if entry is None:
        return {}
    try:
        return {k: float(v) for k, v in entry[3]().items() if v}
    except Exception:
        return {}  # a mid-mutation read must never fail a scrape


def process_components() -> Dict[str, float]:
    """Process-wide device/pinned buffers outside any workload."""
    out: Dict[str, float] = {}
    try:
        from ..ops import feature_cache as FC

        out["feature_cache"] = float(FC.stats()[3])
    except Exception:
        pass
    try:
        from ..utils.jit_cache import aot_dir

        total = 0
        with os.scandir(aot_dir()) as it:
            for entry in it:
                if entry.name.endswith(".aotx"):
                    total += entry.stat().st_size
        out["aot_executables"] = float(total)
    except OSError:
        pass  # store not created yet
    except Exception:
        pass
    return out


def budget_bytes() -> Tuple[float, str]:
    """(bytes, source) — DUKE_HBM_BUDGET_MB, else the backend's
    reported limit, else the documented default."""
    mb = env_int("DUKE_HBM_BUDGET_MB", 0)
    if mb > 0:
        return float(mb) * 1024 * 1024, "env"
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats()
        limit = (stats or {}).get("bytes_limit")
        if limit:
            return float(limit), "device"
    except Exception:
        pass
    return float(DEFAULT_BUDGET_BYTES), "default"


_CORPUS_COMPONENTS = ("corpus_tensors", "corpus_embeddings", "int8_scales",
                      "ivf_membership")


def _totals(now_unix: Optional[float] = None
            ) -> Tuple[float, float, List[Tuple[str, str, str, float]]]:
    """(total_bytes, corpus_bytes, [(kind, name, component, bytes)]) and
    feed the growth ring with the corpus share."""
    rows: List[Tuple[str, str, str, float]] = []
    total = 0.0
    corpus = 0.0
    for kind, name, owner, _fn in _iter_live():
        for comp, nbytes in sorted(components_for(owner).items()):
            rows.append((kind, name, comp, nbytes))
            total += nbytes
            if comp in _CORPUS_COMPONENTS:
                corpus += nbytes
    for comp, nbytes in sorted(process_components().items()):
        rows.append(("process", "", comp, nbytes))
        total += nbytes
    now_unix = time.time() if now_unix is None else now_unix
    with _REG_LOCK:
        if not _growth or _growth[-1][1] != corpus:
            _growth.append((now_unix, corpus))
    return total, corpus, rows


def growth_bytes_per_day() -> float:
    """Corpus-byte growth rate across observed scrapes (0 until two
    distinct observations exist)."""
    with _REG_LOCK:
        if len(_growth) < 2:
            return 0.0
        (t0, b0), (t1, b1) = _growth[0], _growth[-1]
    dt = t1 - t0
    if dt <= 0 or b1 <= b0:
        return 0.0
    return (b1 - b0) / dt * 86400.0


def overflow_days(headroom: float) -> float:
    """Days until the corpus growth rate consumes ``headroom``; -1 when
    no growth has been observed (never extrapolate from silence)."""
    rate = growth_bytes_per_day()
    if rate <= 0.0:
        return -1.0
    return max(0.0, headroom) / rate


def live_arrays_bytes() -> Optional[int]:
    """Backend cross-check: total bytes of all live jax arrays, or None
    where the backend/API does not support it."""
    try:
        import jax

        return int(sum(getattr(a, "nbytes", 0) for a in jax.live_arrays()))
    except Exception:
        return None


def debug_snapshot() -> Dict[str, object]:
    """``GET /debug/memory`` payload."""
    budget, source = budget_bytes()
    total, corpus, rows = _totals()
    headroom = budget - total
    return {
        "budget_bytes": int(budget),
        "budget_source": source,
        "total_bytes": int(total),
        "corpus_bytes": int(corpus),
        "headroom_bytes": int(headroom),
        "growth_bytes_per_day": round(growth_bytes_per_day(), 3),
        "overflow_days": round(overflow_days(headroom), 3),
        "workloads": [
            {"kind": kind, "workload": name, "component": comp,
             "bytes": int(nbytes)}
            for kind, name, comp, nbytes in rows if kind != "process"
        ],
        "process": {comp: int(nbytes)
                    for kind, _n, comp, nbytes in rows if kind == "process"},
        "live_arrays_bytes": live_arrays_bytes(),
    }


def _reset_for_tests() -> None:
    with _REG_LOCK:
        _ENTRIES.clear()
        _growth.clear()


def collect() -> List[FamilySnapshot]:
    """Scrape-time collector (registered on ``telemetry.GLOBAL``):
    process-component bytes + the headroom/forecast gauges.  The
    per-workload ``duke_device_bytes`` samples come from the app/group
    collectors so the federation rollup can relabel them per group."""
    budget, _source = budget_bytes()
    total, _corpus, rows = _totals()
    headroom = budget - total
    proc_samples = [("", (("component", comp),), nbytes)
                    for kind, _n, comp, nbytes in rows if kind == "process"]
    return [
        FamilySnapshot(
            "duke_device_bytes", "gauge",
            "Registered device-buffer bytes by component (per-workload "
            "series carry kind/workload labels; process-wide components "
            "— feature cache, AOT executable store — carry only "
            "component)", proc_samples),
        FamilySnapshot(
            "duke_device_headroom_bytes", "gauge",
            "HBM budget (DUKE_HBM_BUDGET_MB, else the backend's "
            "bytes_limit, else 16 GiB) minus all registered device "
            "bytes", [("", (), headroom)]),
        FamilySnapshot(
            "duke_device_overflow_days", "gauge",
            "Days until the observed corpus-byte growth rate consumes "
            "the headroom (-1 = no growth observed yet)",
            [("", (), overflow_days(headroom))]),
    ]
