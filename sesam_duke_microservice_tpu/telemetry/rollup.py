"""Fleet metrics rollup (ISSUE 16 layer 2).

Merges per-group registry snapshots into one federation-plane scrape.
The merge rule, per family type:

  * **counters and histograms are key-wise SUMMED** across groups.
    Every latency histogram in the codebase shares the
    ``DEFAULT_LATENCY_BUCKETS`` ladder, so ``_bucket`` samples with
    identical ``le`` labels are cumulative counts on identical bucket
    ladders — bucket-wise addition is lossless (the sum of cumulative
    ladders is the cumulative ladder of the union), and ``_sum`` /
    ``_count`` add trivially.  Sample keys already disjoint across
    groups (e.g. per-workload labels that differ) pass through as plain
    sums of one term.
  * **gauges are RELABELED**, never summed: a gauge is point-in-time
    state (queue depth, EWMA, rows resident) whose cross-group sum is
    usually meaningless, so each sample gains a ``group="<idx>"`` label
    and the per-group series stay individually visible.  This keeps the
    rollup's gauge label sets disjoint from any single group's — the
    property the differential test asserts.

Locking: ``merge_groups`` touches snapshots only — plain lists already
detached from their registries.  The caller collects each group's
registry SEQUENTIALLY (``MetricRegistry.collect`` does its own brief
locking), so no group lock is ever held across another group's scrape.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Sequence, Tuple

from .registry import FamilySnapshot, MetricRegistry


def merge_groups(
    per_group: Sequence[Tuple[str, Iterable[FamilySnapshot]]],
) -> List[FamilySnapshot]:
    """Merge ``(group_label, snapshots)`` pairs under the sum/relabel
    rule above.  First declaration of a family wins HELP/TYPE (the
    ``render`` precedent)."""
    order: List[str] = []
    meta: Dict[str, Tuple[str, str]] = {}
    sums: Dict[str, Dict[Tuple[str, Tuple], float]] = {}
    relabeled: Dict[str, List] = {}
    for gid, snaps in per_group:
        for snap in snaps:
            if snap.name not in meta:
                order.append(snap.name)
                meta[snap.name] = (snap.mtype, snap.help)
                sums[snap.name] = {}
                relabeled[snap.name] = []
            if snap.mtype == "gauge":
                relabeled[snap.name].extend(
                    (suffix, labels + (("group", str(gid)),), value)
                    for suffix, labels, value in snap.samples)
            else:
                acc = sums[snap.name]
                for suffix, labels, value in snap.samples:
                    key = (suffix, labels)
                    acc[key] = acc.get(key, 0.0) + value
    out = []
    for name in order:
        mtype, help_text = meta[name]
        samples = [(suffix, labels, value)
                   for (suffix, labels), value in sums[name].items()]
        samples.extend(relabeled[name])
        out.append(FamilySnapshot(name, mtype, help_text, samples))
    return out


class GroupRollup:
    """A ``render()``-compatible view over N per-group registries: its
    ``collect()`` scrapes each group in sequence and returns the merged
    fleet snapshot.  Holds no lock of its own."""

    __slots__ = ("_groups",)

    def __init__(self, groups: Sequence[Tuple[str, MetricRegistry]]):
        self._groups = list(groups)

    def collect(self) -> List[FamilySnapshot]:
        return merge_groups(
            [(gid, reg.collect()) for gid, reg in self._groups])
