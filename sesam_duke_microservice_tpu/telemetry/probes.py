"""Black-box canary probing: corpus derivation, probe state, SLI families.

Every observability layer before this one is white-box — the process
reports on itself.  The probe plane closes the loop from the OUTSIDE:
a deterministic canary corpus is derived from the workload's own plan
(per-property perturbed record pairs at known edit distances straddling
the thresholds), expected verdicts are computed ONCE via the host f64
oracle (``Processor.compare`` — the same arbiter the finalize rescore
uses), and a background prober (service.prober) replays the corpus
through the real path every cycle: scheduler admission, scoring,
finalize, link journal, ``?since=`` feed materialization.  Any drift
between the oracle's verdict and what the served feed says is a
correctness incident, not a latency blip.

This module is the engine-free half: namespace contract, corpus
derivation, per-workload probe state (single-writer, scrape-time
snapshots — the PhaseRecorder discipline) and the ``duke_probe_*``
metric families.  ``service/prober.py`` owns workload lifecycles and
the injection loop.

Namespace contract: every probe workload and probe dataset id carries
the ``__probe__`` prefix.  Probe workloads are registered ONLY with the
prober — never in ``DukeApp.deduplications``/``record_linkages`` — so
no HTTP route can resolve them, and the HTTP layer additionally rejects
the prefix outright (service/app.py).  User-visible feed and link rows
are therefore bit-identical with the prober on or off; the differential
test in tests/test_probes.py proves it.
"""

from __future__ import annotations

import hashlib
import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

from .decisions import _MonitorHist, classify
from .env import env_flag, env_float, env_int
from .registry import DEFAULT_LATENCY_BUCKETS, FamilySnapshot

# Reserved namespace prefix for probe workload names AND probe dataset
# ids.  Anything carrying it is invisible to the HTTP surface.
PROBE_PREFIX = "__probe__"

#: Probe cycle stages, in path order: scheduler admission through batch
#: commit; link-journal verdict readback; full ``?since=`` feed walk.
STAGES = ("ingest", "score", "feed")


def probe_name(name: str) -> str:
    return PROBE_PREFIX + name


def is_probe_name(name: str) -> bool:
    return name.startswith(PROBE_PREFIX)


def probes_enabled() -> bool:
    return env_flag("DUKE_PROBE", True)


def probe_interval_s() -> float:
    return env_float("DUKE_PROBE_INTERVAL_S", 30.0)


# -- canary corpus ------------------------------------------------------------

_ALPHA = "abcdefghijklmnopqrstuvwxyz"


def _token(*parts: str) -> str:
    """Deterministic two-word lowercase value for (pair, property, side).

    Letters only, so the standard cleaners (lowercase/trim) are identity
    on it and edit-distance comparators see exactly the intended string.
    Two words matter: perturbations touch only the SECOND word, so the
    first stays an exact index token and the pair remains retrievable by
    token-level blocking (the inverted-index host backend) — the probe
    certifies the scoring/threshold path at a known edit distance, not
    the recall limits of exact-token candidate search."""
    h = hashlib.sha256("|".join(parts).encode("utf-8")).hexdigest()
    letters = [_ALPHA[int(c, 16) % 26] for c in h[:16]]
    return "".join(letters[:6]) + " " + "".join(letters[6:])


def _perturb_light(value: str) -> str:
    """Edit distance 1: flip the last character (second word)."""
    tail = "a" if value[-1] != "a" else "b"
    return value[:-1] + tail


def _perturb_heavy(value: str) -> str:
    """Rewrite the whole second word — similarity drops well under the
    0.5 comparator knee, so the property contributes its low
    probability, while the first word keeps the pair retrievable."""
    head, _, tail = value.rpartition(" ")
    flipped = "".join("a" if c != "a" else "b" for c in tail)
    return head + " " + flipped if head else flipped


class Canary:
    """One expected-verdict record pair: column values for both sides
    plus the oracle's verdict.  Entity ids are stamped per cycle by the
    prober (fresh ids each cycle keep ground truth unambiguous)."""

    __slots__ = ("key", "values_a", "values_b", "expected_prob",
                 "expected_verdict")

    def __init__(self, key: str, values_a: Dict[str, str],
                 values_b: Dict[str, str]):
        self.key = key
        self.values_a = values_a
        self.values_b = values_b
        self.expected_prob: Optional[float] = None
        self.expected_verdict: Optional[str] = None


def _columns_by_property(datasource) -> Dict[str, str]:
    """property name -> first mapped column name for one datasource."""
    out: Dict[str, str] = {}
    for col in datasource.config.columns:
        out.setdefault(col.property, col.name)
    return out


def derive_canaries(schema, ds_a, ds_b, compare) -> List[Canary]:
    """Derive the canary corpus from the plan and stamp oracle verdicts.

    ``ds_a``/``ds_b`` are the injection datasources (same one twice for
    dedup; one per group for linkage); ``compare`` is the host f64
    oracle bound to the probe workload's schema.  Pairs: one identical
    pair (expected match), per comparison property a light (edit
    distance 1) and a heavy (half rewritten) perturbation of just that
    property, and one fully disjoint pair (expected reject).  Values
    flow through ``record_for_entity`` — the real column/cleaner
    mapping — before the oracle sees them, so expectations track
    exactly what ingest will index.
    """
    cols_a = _columns_by_property(ds_a)
    cols_b = _columns_by_property(ds_b)
    # only properties both sides can express participate in canaries
    props = [p.name for p in schema.comparison_properties()
             if p.name in cols_a and p.name in cols_b]

    def base(pair_key: str, cols: Dict[str, str], side: str) -> Dict[str, str]:
        return {cols[p]: _token(pair_key, p, side) for p in props}

    canaries: List[Canary] = []

    same = {p: _token("identical", p, "ab") for p in props}
    canaries.append(Canary(
        "identical",
        {cols_a[p]: v for p, v in same.items()},
        {cols_b[p]: v for p, v in same.items()},
    ))

    for prop in props:
        for grade, perturb in (("near", _perturb_light),
                               ("far", _perturb_heavy)):
            key = f"{grade}-{prop}"
            shared = {p: _token(key, p, "ab") for p in props}
            va = {cols_a[p]: v for p, v in shared.items()}
            vb = {cols_b[p]: v for p, v in shared.items()}
            vb[cols_b[prop]] = perturb(shared[prop])
            canaries.append(Canary(key, va, vb))

    canaries.append(Canary(
        "disjoint",
        base("disjoint", cols_a, "a"),
        base("disjoint", cols_b, "b"),
    ))

    for canary in canaries:
        ea = dict(canary.values_a)
        ea["_id"] = f"{canary.key}-a"
        eb = dict(canary.values_b)
        eb["_id"] = f"{canary.key}-b"
        ra = ds_a.record_for_entity(ea)
        rb = ds_b.record_for_entity(eb)
        canary.expected_prob = compare(ra, rb)
        canary.expected_verdict = classify(
            canary.expected_prob, schema.threshold, schema.maybe_threshold
        )
    return canaries


# -- per-workload probe state -------------------------------------------------

def _history_limit() -> int:
    return max(1, env_int("DUKE_PROBE_HISTORY", 32))


class ProbeState:
    """Single-writer per-workload probe accounting (the prober's cycle
    thread writes, /metrics and /debug/probes snapshot at read time —
    plain attribute math, no locks on the cycle path)."""

    __slots__ = ("kind", "name", "cycles", "ok_cycles", "corpus_size",
                 "stage_hists", "failures", "mismatches", "probe_compiles",
                 "last_ok_monotonic", "last", "history")

    def __init__(self, kind: str, name: str):
        self.kind = kind
        self.name = name
        self.cycles = 0
        self.ok_cycles = 0
        self.corpus_size = 0
        self.stage_hists: Dict[str, _MonitorHist] = {
            stage: _MonitorHist(DEFAULT_LATENCY_BUCKETS) for stage in STAGES
        }
        self.failures: Dict[str, int] = {}
        self.mismatches = 0
        # XLA compiles attributed to the probe workload build (shared
        # AOT ladder contract: 0 when the user workload already warmed
        # the identical plan fingerprint)
        self.probe_compiles = 0
        self.last_ok_monotonic: Optional[float] = None
        self.last: Optional[dict] = None
        self.history: deque = deque(maxlen=_history_limit())

    def note_failure(self, reason: str) -> None:
        self.failures[reason] = self.failures.get(reason, 0) + 1

    def freshness_seconds(self, now: Optional[float] = None) -> Optional[float]:
        if self.last_ok_monotonic is None:
            return None
        if now is None:
            now = time.monotonic()
        return max(0.0, now - self.last_ok_monotonic)

    def snapshot(self) -> dict:
        out = {
            "kind": self.kind,
            "workload": self.name,
            "cycles": self.cycles,
            "ok_cycles": self.ok_cycles,
            "corpus_size": self.corpus_size,
            "failures": dict(self.failures),
            "verdict_mismatches": self.mismatches,
            "probe_compiles": self.probe_compiles,
            "freshness_seconds": self.freshness_seconds(),
            "last": self.last,
            "history": list(self.history),
        }
        return out


# -- metric families ----------------------------------------------------------

def probe_families(states: Sequence[ProbeState],
                   now: Optional[float] = None) -> List[FamilySnapshot]:
    """The four ``duke_probe_*`` families over a snapshot of states."""
    e2e: List[tuple] = []
    fresh: List[tuple] = []
    fails: List[tuple] = []
    mismatches: List[tuple] = []
    for st in states:
        base = (("kind", st.kind), ("workload", st.name))
        for stage in STAGES:
            e2e.extend(st.stage_hists[stage].samples(
                base + (("stage", stage),)))
        age = st.freshness_seconds(now)
        if age is not None:
            fresh.append(("", base, age))
        for reason, n in sorted(st.failures.items()):
            fails.append(("", base + (("reason", reason),), float(n)))
        mismatches.append(("", base, float(st.mismatches)))
    return [
        FamilySnapshot(
            "duke_probe_e2e_seconds", "histogram",
            "Black-box canary latency per cycle stage "
            "(ingest admission→commit, verdict readback, feed walk).",
            e2e,
        ),
        FamilySnapshot(
            "duke_probe_freshness_seconds", "gauge",
            "Seconds since the last fully successful probe cycle.",
            fresh,
        ),
        FamilySnapshot(
            "duke_probe_failures_total", "counter",
            "Probe cycle failures by reason (submit/observe/feed errors, "
            "missing feed rows).",
            fails,
        ),
        FamilySnapshot(
            "duke_probe_verdict_mismatches_total", "counter",
            "Canary pairs whose served verdict diverged from the host "
            "f64 oracle expectation.",
            mismatches,
        ),
    ]


def range_probe_family(checks: Dict[str, Dict[str, int]],
                       groups: Dict[str, int]) -> FamilySnapshot:
    """``duke_probe_range_checks_total{range,group,outcome}`` — per-range
    reachability probes through the federation router (service.prober.
    RangeProber).  Registered per group so GroupRollup merges the fleet
    view exactly like every other per-group family."""
    samples = []
    for range_id in sorted(checks):
        for outcome in ("ok", "fail"):
            n = checks[range_id].get(outcome, 0)
            samples.append((
                "",
                (("range", range_id),
                 ("group", str(groups.get(range_id, ""))),
                 ("outcome", outcome)),
                float(n),
            ))
    return FamilySnapshot(
        "duke_probe_range_checks_total", "counter",
        "Per-range black-box reachability probes via the federation "
        "router, by outcome.",
        samples,
    )
