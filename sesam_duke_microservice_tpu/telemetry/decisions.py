"""Match-decision flight recorder, quality-drift monitors, audit log.

ISSUE 5 tentpole: the engine's whole output is a stream of *decisions*
(match / maybe / reject / pruned, per scored pair), yet PR 1-2 only
observed phases and latency.  This module makes decisions observable
without touching the scoring hot path's complexity budget:

  * ``DecisionRecorder`` — one per processor, written ONLY by the
    coordinating thread that already emits listener events serially
    (single-writer, the ProfileStats/PhaseRecorder discipline), so every
    update is plain attribute math with no locks on the engine path.
    It maintains:

      - **drift monitors**: outcome counters
        (``duke_decisions_total{outcome}``), a device-vs-host
        disagreement counter, a pair-logit distribution histogram, a
        decisive-band margin-slack histogram, and per-property
        similarity histograms (fed from the sampled breakdowns only —
        the one non-O(1) piece).  All are scrape-time snapshots
        (service/metrics.py); the engine never writes a registry child.
      - **the decision ring**: a sampled, byte-bounded ``LatchedRing``
        of full decision records (``GET /debug/decisions``).  The tail
        latch keeps every *disagreement* and every
        *near-threshold band skip* regardless of the sample rate — the
        two decision classes an operator tuning thresholds or auditing
        the f32 device path actually needs.

  * ``AuditLog`` — optional append-only JSONL of confirmed link
    decisions (``DUKE_AUDIT_LOG=path``), flushed through the shared
    write-behind machinery (links.write_behind.WriteBehindBuffer) so a
    slow audit disk can never block scoring: past the pending cap the
    OLDEST batch drops (counted), the opposite of the link store's
    backpressure stance — links are truth, audit is evidence.

Disagreement definition (the ``duke_decision_disagreements_total``
contract): the float32 device verdict — classify(sigmoid(device_logit +
host_bound)) — lands on a different side of the thresholds than the
exact f64 rescore.  For schemas whose every property has a device kernel
(``host_bound == 0``) this is a true f32-vs-f64 numeric disagreement;
with host-scored properties the device term is the optimistic filter
bound, so the counter also surfaces how often the filter's optimism
crossed a threshold the exact rescore did not.  Near-threshold band
skip: a pruned survivor whose slack below the decisive bound is within
one certified margin — the skips that would flip first if the margin
were wrong.

Env knobs (read at recorder construction):
  DUKE_DECISION_RECORD   0 disables the whole subsystem (bench baseline)
  DUKE_DECISION_SAMPLE   ring/breakdown sample rate, default 0.01
  DUKE_DECISION_RING     ring capacity in records, default 256
  DUKE_DECISION_RING_KB  ring byte budget, default 512 KiB
  DUKE_AUDIT_LOG         JSONL path; unset disables the audit log
"""

from __future__ import annotations

import hashlib
import itertools
import json
import logging
import math
import random
import threading
import time
from bisect import bisect_left
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ..core.bayes import probability_logit
from .env import env_flag, env_float, env_int, env_str
from .logctx import current_request_id
from .registry import histogram_snapshot
from .rings import LatchedRing
from .tracing import current_trace_id

logger = logging.getLogger("decisions")

__all__ = [
    "DecisionRecorder",
    "PairDecision",
    "AuditLog",
    "audit_log",
    "classify",
    "probability_to_logit",
    "explanation_digest",
]

# Pair-logit distribution bounds: symmetric, dense around the typical
# threshold region (logit(0.8)=1.39, logit(0.95)=2.94), clamped wide for
# multi-property certainty sums.
PAIR_LOGIT_BOUNDS: Tuple[float, ...] = (
    -30.0, -20.0, -10.0, -5.0, -3.0, -2.0, -1.0, -0.5, 0.0,
    0.5, 1.0, 2.0, 3.0, 5.0, 10.0, 20.0, 30.0,
)

# Decisive-band slack (prune_logit - device_logit, logit units): log-ish
# ladder from "a whisker inside the band" to "nowhere near emitting".
MARGIN_SLACK_BOUNDS: Tuple[float, ...] = (
    0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 2.0, 5.0, 10.0, 20.0, 50.0,
)

# Comparator similarity in [0, 1]; finer near the top where Duke's
# quadratic probability map actually moves.
SIMILARITY_BOUNDS: Tuple[float, ...] = (
    0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7, 0.8, 0.9, 0.95, 0.99,
)

# THE engine's clamped logit (core.bayes): the drift monitors must
# report the same log-odds the Bayes fold actually sums, not a copy
# that could diverge on a clamp change
probability_to_logit = probability_logit


def classify(prob: float, threshold: float,
             maybe: Optional[float]) -> str:
    """The engine's threshold decision (engine.processor emit rules)."""
    if prob > threshold:
        return "match"
    if maybe is not None and maybe != 0.0 and prob > maybe:
        return "maybe"
    return "reject"


def explanation_digest(digest1: bytes, digest2: bytes,
                       probability: float) -> str:
    """Stable short digest joining an audit row to a later ``/explain``
    replay: record CONTENT digests (store.records.record_digest — so a
    re-indexed record changes the digest) plus the emitted probability."""
    h = hashlib.sha256(digest1)
    h.update(digest2)
    h.update(repr(float(probability)).encode())
    return h.hexdigest()[:16]


class _MonitorHist:
    """Single-writer histogram state (the PhaseRecorder discipline):
    plain attribute math on the engine path, ``samples()`` renders the
    Prometheus shape at scrape time."""

    __slots__ = ("bounds", "counts", "total", "count")

    def __init__(self, bounds: Sequence[float]):
        self.bounds = tuple(float(b) for b in bounds)
        self.counts = [0] * (len(self.bounds) + 1)
        self.total = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        self.counts[bisect_left(self.bounds, value)] += 1
        self.total += value
        self.count += 1

    def samples(self, labels: Tuple[Tuple[str, str], ...]):
        return histogram_snapshot(
            self.bounds, list(self.counts), self.total, self.count, labels
        )


class PairDecision:
    """One finalized pair's decision inputs, built by the finalize
    workers (cheap tuple-of-scalars) and consumed serially by the
    coordinator's ``DecisionRecorder.observe``."""

    __slots__ = ("candidate_id", "device_logit", "skipped", "probability",
                 "path")

    def __init__(self, candidate_id: str, device_logit: Optional[float],
                 skipped: bool, probability: Optional[float],
                 path: Optional[str] = None):
        self.candidate_id = candidate_id
        self.device_logit = device_logit
        self.skipped = skipped
        self.probability = probability
        # which finalization path skipped the pair: None (band skip /
        # rescored) or "device_certified" (dd certified reject, ISSUE 12)
        self.path = path


_DECISION_SEQ = itertools.count(1)


class DecisionRecorder:
    """Per-processor decision observability (see module docstring).

    ``breakdown(query, candidate)`` is the per-property explanation
    callable (engine.explain.host_breakdown bound to the schema) — only
    invoked for decisions entering the ring, so its cost rides the
    sample rate, not the pair rate.  ``resolver`` maps a candidate id to
    its live Record for that breakdown.
    """

    def __init__(self, threshold: float, maybe: Optional[float], *,
                 breakdown: Optional[Callable] = None,
                 resolver: Optional[Callable] = None,
                 sample_rate: Optional[float] = None,
                 capacity: Optional[int] = None,
                 byte_budget: Optional[int] = None,
                 enabled: Optional[bool] = None,
                 workload: str = "", kind: str = ""):
        if enabled is None:
            enabled = env_flag("DUKE_DECISION_RECORD", True)
        self.enabled = enabled
        self.threshold = float(threshold)
        self.maybe = maybe
        self._breakdown = breakdown
        self._resolver = resolver
        self.workload = workload
        self.kind = kind
        if sample_rate is None:
            sample_rate = env_float("DUKE_DECISION_SAMPLE", 0.01)
        self.sample_rate = min(1.0, max(0.0, sample_rate))
        if capacity is None:
            capacity = env_int("DUKE_DECISION_RING", 256)
        if byte_budget is None:
            byte_budget = env_int("DUKE_DECISION_RING_KB", 512) * 1024
        self.ring = LatchedRing(max(1, capacity), byte_budget)
        self._rng = random.Random()
        # single-writer drift-monitor state (scrape-time snapshots)
        self.outcomes: Dict[str, int] = {
            "match": 0, "maybe": 0, "reject": 0, "pruned": 0,
            "device_certified": 0,
        }
        self.disagreements = 0
        self.latched = 0
        self.sampled = 0
        self.pair_logit_hist = _MonitorHist(PAIR_LOGIT_BOUNDS)
        self.margin_slack_hist = _MonitorHist(MARGIN_SLACK_BOUNDS)
        self.similarity_hists: Dict[str, _MonitorHist] = {}

    # -- the engine-path write (single writer: the event coordinator) --------

    def observe(self, query, decisions: Sequence[PairDecision], *,
                prune: Optional[float] = None,
                margin: Optional[float] = None,
                host_bound: float = 0.0) -> None:
        """Fold one query's finalized pair decisions into the monitors
        and (sampled / latched) the ring.  ``prune`` and ``margin`` are
        the block's decisive-band bound and certified f32 margin
        (None on backends without a decisive band)."""
        if not self.enabled or not decisions:
            return
        threshold, maybe = self.threshold, self.maybe
        for d in decisions:
            latch = None
            pair_logit = None
            if d.skipped:
                if getattr(d, "path", None) == "device_certified":
                    # dd certified reject (ISSUE 12): not a band skip —
                    # the dd logit sat ABOVE the prune bound, so the
                    # band-slack histogram and near-band latch do not
                    # apply (its own certification band is ~1e-10 and
                    # residue pairs go to the host instead of
                    # skipping).  Ring SAMPLING below still does:
                    # certified rejects must stay auditable.
                    outcome = "device_certified"
                else:
                    outcome = "pruned"
                    if prune is not None and d.device_logit is not None:
                        slack = prune - d.device_logit
                        self.margin_slack_hist.observe(slack)
                        if margin is not None and slack <= margin:
                            # the skips that would flip first if the
                            # certified margin were wrong: always
                            # retained
                            latch = "near-band-skip"
            else:
                outcome = classify(d.probability, threshold, maybe)
                pair_logit = probability_to_logit(d.probability)
                self.pair_logit_hist.observe(pair_logit)
                if d.device_logit is not None:
                    f32_prob = 1.0 / (
                        1.0 + math.exp(-(d.device_logit + host_bound))
                    )
                    if classify(f32_prob, threshold, maybe) != outcome:
                        self.disagreements += 1
                        latch = "disagreement"
            self.outcomes[outcome] += 1
            sampled = (self.sample_rate > 0.0
                       and self._rng.random() < self.sample_rate)
            if latch is None and not sampled:
                continue
            if latch is not None:
                self.latched += 1
            if sampled:
                self.sampled += 1
            self._capture(query, d, outcome, pair_logit, prune, margin,
                          latch, sampled)

    def _capture(self, query, d: PairDecision, outcome: str,
                 pair_logit: Optional[float], prune: Optional[float],
                 margin: Optional[float], latch: Optional[str],
                 sampled: bool) -> None:
        """Build the full decision record (ring path only — never the
        per-pair fast path)."""
        record: Dict[str, Any] = {
            "id": f"d{next(_DECISION_SEQ):08d}",
            "time_unix": round(time.time(), 3),
            "query": query.record_id,
            "candidate": d.candidate_id,
            "outcome": outcome,
            "sampled": sampled,
            "latched": latch,
            "trace_id": current_trace_id(),
            "request_id": current_request_id(),
        }
        if d.device_logit is not None:
            record["device_logit"] = round(d.device_logit, 6)
        if prune is not None:
            record["decisive_prune_logit"] = round(prune, 6)
            if d.device_logit is not None and d.skipped:
                record["margin_slack"] = round(prune - d.device_logit, 6)
        if margin is not None:
            record["certified_margin"] = round(margin, 9)
        if d.probability is not None:
            record["probability"] = d.probability
            record["pair_logit"] = round(pair_logit, 6)
        if self._breakdown is not None and self._resolver is not None:
            candidate = self._resolver(d.candidate_id)
            if candidate is not None:
                try:
                    explained = self._breakdown(query, candidate)
                except Exception:  # degraded record, never a dead batch
                    logger.exception("decision breakdown failed")
                    explained = None
                if explained is not None:
                    record["properties"] = explained["properties"]
                    record["host_pair_logit"] = round(
                        explained["pair_logit"], 6)
                    for prop in explained["properties"]:
                        sim = prop.get("best_similarity")
                        if sim is None:
                            continue
                        hist = self.similarity_hists.get(prop["name"])
                        if hist is None:
                            hist = _MonitorHist(SIMILARITY_BOUNDS)
                            self.similarity_hists[prop["name"]] = hist
                        hist.observe(sim)
        nbytes = len(json.dumps(record, separators=(",", ":")))
        self.ring.put(record["id"], record, remarkable=latch is not None,
                      nbytes=nbytes)

    # -- host-engine convenience ---------------------------------------------

    def observe_pairs(self, query,
                      pairs: Sequence[Tuple[str, float]]) -> None:
        """Host-engine entry: (candidate_id, probability) pairs with no
        device pre-score (no band, no disagreement surface)."""
        if not self.enabled or not pairs:
            return
        self.observe(query, [
            PairDecision(cid, None, False, prob) for cid, prob in pairs
        ])

    # -- scrape-time reads ----------------------------------------------------

    def records(self) -> List[Dict[str, Any]]:
        return self.ring.records()

    def get(self, decision_id: str) -> Optional[Dict[str, Any]]:
        return self.ring.get(decision_id)


# -- audit log ----------------------------------------------------------------


class AuditLog:
    """Append-only JSONL of confirmed link decisions.

    Entries buffer through a ``WriteBehindBuffer`` (the link store's
    machinery) with ``drop_on_overflow`` — the audit file is evidence,
    not truth, so a stalled disk drops oldest batches (counted in
    ``dropped``) instead of backpressuring ingest.  A flush failure
    disables the log (logged once); scoring proceeds.
    """

    def __init__(self, path: str, *, max_pending: int = 64):
        from ..links.write_behind import WriteBehindBuffer

        self.path = path
        self.entries = 0
        self._disabled = False
        self._lock = threading.Lock()
        self._wb = WriteBehindBuffer(
            self._write_batch, max_pending=max_pending,
            drop_on_overflow=True, name="audit-log",
        )

    @property
    def dropped(self) -> int:
        return self._wb.dropped

    @property
    def disabled(self) -> bool:
        return self._disabled

    def _write_batch(self, batch: List[str]) -> None:
        with open(self.path, "a", encoding="utf-8") as f:
            f.write("".join(batch))

    def append(self, entry: Dict[str, Any]) -> None:
        """Buffer one entry; never raises into the scoring path."""
        if self._disabled:
            return
        try:
            line = json.dumps(entry, separators=(",", ":")) + "\n"
            with self._lock:
                self._wb.add(line)
        except Exception:
            self._disabled = True
            logger.exception(
                "audit log disabled after write-behind failure (%s)",
                self.path,
            )
            return
        self.entries += 1

    def flush(self) -> None:
        """Seal the buffered entries for the background flusher (called
        from listener ``batch_done`` — the persist phase, off the
        scoring loop)."""
        if self._disabled:
            return
        try:
            self._wb.commit()
        except Exception:
            self._disabled = True
            logger.exception("audit log disabled (flush enqueue failed)")

    def drain(self) -> None:
        if self._disabled:
            return
        try:
            self._wb.drain()
        except Exception:
            self._disabled = True
            logger.exception("audit log disabled (drain failed)")

    def close(self) -> None:
        self._wb.close()


_AUDIT_LOCK = threading.Lock()
_AUDIT: Optional[AuditLog] = None
_AUDIT_PATH: Optional[str] = None


def audit_log() -> Optional[AuditLog]:
    """The process-wide audit log for ``DUKE_AUDIT_LOG``, or None.

    One instance per path (multiple workloads share the single
    background writer, so JSONL lines never interleave mid-record); the
    env var is re-read so tests can point at a fresh temp file.
    """
    global _AUDIT, _AUDIT_PATH
    path = env_str("DUKE_AUDIT_LOG") or None
    with _AUDIT_LOCK:
        if path != _AUDIT_PATH:
            if _AUDIT is not None:
                _AUDIT.close()
            _AUDIT = AuditLog(path) if path else None
            _AUDIT_PATH = path
        return _AUDIT
