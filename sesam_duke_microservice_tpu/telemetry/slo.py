"""Always-on runtime SLO signals (ISSUE 16 layer 3).

Promotes bench.py's offline tail machinery into scrape-served families
the roadmap's autoscaling controller (item 3) and push-delivery
consumer-lag contract (item 2) can consume at runtime:

  * ``SloTracker`` — per (signal, workload) latency accounting measured
    from SCHEDULER ARRIVAL for ingest (the queueing delay the PR 14
    open-loop harness proved closed-loop benches hide) and from handler
    entry for feed reads.  Each tracker keeps a latency histogram on the
    shared ``DEFAULT_LATENCY_BUCKETS`` ladder, a monotone violation
    counter, a coarse 10 s slot ring of request counts covering the
    longest window, and the **burn-rate ring**: a bounded deque of
    ``(timestamp, exemplar trace id)`` violation entries from which the
    5 m / 1 h windowed violation counts are recomputed exactly at scrape
    time.  The exemplar is the sampled trace id active when the
    violating request was recorded (None when unsampled), so
    ``GET /debug/slo`` can link a burn-rate alert straight to a causal
    tree in ``/debug/traces`` (ISSUE 17 satellite).
  * burn rate (Google SRE Workbook multi-window discipline): the
    fraction of the error budget consumed per unit time —
    ``(violations/requests in window) / (1 - target)``.  A burn rate of
    1.0 spends exactly the budget; alerting pairs a fast window (5m)
    with a slow one (1h) so a page needs both to fire.
  * ``FeedLagMeter`` — per-workload ``duke_feed_lag_seconds``: age of
    the oldest link-feed row written since the last time a ``?since=``
    consumer drained the feed (0 when caught up).  Writers touch plain
    attributes (dispatcher thread / feed handler); torn reads are
    tolerated, the /stats stance.

Recording takes the tracker's leaf lock ONCE per dispatched microbatch
(``record_batch``) — never on the scoring path, never while any other
lock is held, so the lock hierarchy gains only leaves.

Env knobs: ``DUKE_SLO_INGEST_MS`` (default 1000), ``DUKE_SLO_FEED_MS``
(default 500) set the per-signal latency objectives;
``DUKE_SLO_TARGET`` (default 0.99) the success-ratio target shared by
the burn-rate gauges.
"""

from __future__ import annotations

import threading
import time
from bisect import bisect_left
from collections import deque
from typing import Deque, Dict, List, Optional, Sequence, Tuple

from .env import env_float
from .registry import (DEFAULT_LATENCY_BUCKETS, FamilySnapshot,
                       histogram_snapshot)

# (label value, window seconds) — multi-window burn-rate pairs; the 5m
# window catches fast burns, the 1h window keeps slow burns visible.
WINDOWS: Tuple[Tuple[str, float], ...] = (("5m", 300.0), ("1h", 3600.0))

_SLOT_S = 10.0
_N_SLOTS = int(WINDOWS[-1][1] / _SLOT_S) + 1  # covers the longest window
_VIOLATION_RING = 8192  # burn-rate ring capacity (violation timestamps)


def _objective_seconds(signal: str) -> float:
    if signal == "feed":
        return env_float("DUKE_SLO_FEED_MS", 500.0) / 1000.0
    return env_float("DUKE_SLO_INGEST_MS", 1000.0) / 1000.0


def _target() -> float:
    # clamp away 1.0: a zero error budget makes burn rate undefined
    return min(env_float("DUKE_SLO_TARGET", 0.99), 0.9999)


class SloTracker:
    """Latency-objective accounting for one (signal, workload) pair.

    All mutable state is guarded by the leaf ``_lock``; nothing is ever
    called with another lock held (the rollup/scrape rule)."""

    __slots__ = ("objective_s", "target", "_lock", "_slots",
                 "_violation_ts", "violations_total", "_counts", "_sum",
                 "_count")

    def __init__(self, objective_s: float, target: float):
        self.objective_s = objective_s
        self.target = target
        self._lock = threading.Lock()
        # [slot_index, requests] per 10s slot, lazily recycled
        self._slots: List[List[float]] = [
            [-1, 0] for _ in range(_N_SLOTS)]  # guarded by: self._lock
        # the burn-rate ring: (monotonic ts, exemplar trace id or None)
        self._violation_ts: Deque[Tuple[float, Optional[str]]] = deque(
            maxlen=_VIOLATION_RING)  # guarded by: self._lock
        self.violations_total = 0  # guarded by: self._lock
        # latency histogram on the shared ladder (+Inf slot last)
        self._counts = [0] * (len(DEFAULT_LATENCY_BUCKETS) + 1)  # guarded by: self._lock
        self._sum = 0.0  # guarded by: self._lock
        self._count = 0  # guarded by: self._lock

    def record_batch(self, latencies: Sequence[float],
                     now: Optional[float] = None,
                     trace_ids: Optional[Sequence[Optional[str]]] = None
                     ) -> None:
        """One lock acquisition for a whole dispatched microbatch.

        ``trace_ids`` (parallel to ``latencies`` when given) supplies
        the sampled exemplar trace id per request; None entries mean
        the request's trace was unsampled."""
        if not latencies:
            return
        now = time.monotonic() if now is None else now
        slot_idx = int(now // _SLOT_S)
        with self._lock:
            cell = self._slots[slot_idx % _N_SLOTS]
            if cell[0] != slot_idx:
                cell[0], cell[1] = slot_idx, 0
            cell[1] += len(latencies)
            for i, lat in enumerate(latencies):
                self._counts[bisect_left(DEFAULT_LATENCY_BUCKETS, lat)] += 1
                self._sum += lat
                self._count += 1
                if lat > self.objective_s:
                    self.violations_total += 1
                    exemplar = trace_ids[i] if trace_ids else None
                    self._violation_ts.append((now, exemplar))

    def record(self, latency_s: float, now: Optional[float] = None,
               trace_id: Optional[str] = None) -> None:
        self.record_batch((latency_s,), now, (trace_id,))

    def scrape(self, now: Optional[float] = None):
        """(hist_samples_state, violations_total, {window: (requests,
        violations, burn_rate)}) under one lock hold."""
        now = time.monotonic() if now is None else now
        budget = 1.0 - self.target
        with self._lock:
            counts = list(self._counts)
            total, count = self._sum, self._count
            violations_total = self.violations_total
            windows = {}
            for wname, wsec in WINDOWS:
                min_slot = int((now - wsec) // _SLOT_S)
                requests = sum(int(c[1]) for c in self._slots
                               if c[0] >= min_slot)
                cutoff = now - wsec
                violations = sum(1 for t, _tid in self._violation_ts
                                 if t >= cutoff)
                rate = ((violations / requests) / budget) if requests else 0.0
                windows[wname] = (requests, violations, rate)
        return (counts, total, count), violations_total, windows

    def recent_violations(self, limit: int = 20
                          ) -> List[Tuple[float, Optional[str]]]:
        """Newest-first (monotonic ts, exemplar trace id) entries."""
        with self._lock:
            tail = list(self._violation_ts)[-limit:]
        tail.reverse()
        return tail


_TRACKERS: Dict[Tuple[str, str, str], SloTracker] = {}  # guarded by: _REG_LOCK [writes]
_REG_LOCK = threading.Lock()


def tracker(signal: str, kind: str, name: str) -> SloTracker:
    """Get-or-create the tracker for (signal, kind, workload); the
    steady state is one dict hit (callers may also cache the return)."""
    key = (signal, kind, name)
    t = _TRACKERS.get(key)
    if t is None:
        with _REG_LOCK:
            t = _TRACKERS.get(key)
            if t is None:
                t = SloTracker(_objective_seconds(signal), _target())
                _TRACKERS[key] = t
    return t


class FeedLagMeter:
    """Per-workload feed-cursor lag: plain attributes, single writer per
    field (dispatcher notes writes, feed handler notes drains)."""

    __slots__ = ("last_write_unix", "oldest_pending_unix")

    def __init__(self):
        self.last_write_unix = 0.0
        self.oldest_pending_unix = 0.0

    def note_write(self, now: Optional[float] = None) -> None:
        now = time.time() if now is None else now
        self.last_write_unix = now
        if not self.oldest_pending_unix:
            self.oldest_pending_unix = now

    def note_drain(self) -> None:
        """A ``?since=`` consumer just drained the feed — caught up."""
        self.oldest_pending_unix = 0.0

    def lag_seconds(self, now: Optional[float] = None) -> float:
        pending = self.oldest_pending_unix
        if not pending:
            return 0.0
        now = time.time() if now is None else now
        return max(0.0, now - pending)


_METERS: Dict[Tuple[str, str], FeedLagMeter] = {}  # guarded by: _REG_LOCK [writes]


def feed_meter(kind: str, name: str) -> FeedLagMeter:
    key = (kind, name)
    m = _METERS.get(key)
    if m is None:
        with _REG_LOCK:
            m = _METERS.get(key)
            if m is None:
                m = FeedLagMeter()
                _METERS[key] = m
    return m


def _reset_for_tests() -> None:
    with _REG_LOCK:
        _TRACKERS.clear()
        _METERS.clear()


def debug_snapshot(limit: int = 20) -> Dict[str, object]:
    """``GET /debug/slo`` payload: per-tracker objective, totals and
    burn-rate windows plus the newest violations with exemplar trace
    links (``/debug/traces/<id>``) where the violating request's trace
    was sampled."""
    with _REG_LOCK:
        trackers = sorted(_TRACKERS.items())
    now_mono = time.monotonic()
    now_unix = time.time()
    out = []
    for (signal, kind, name), t in trackers:
        _hist, v_total, windows = t.scrape(now_mono)
        violations = []
        for ts, trace_id in t.recent_violations(limit):
            violations.append({
                "unix_ts": round(now_unix - (now_mono - ts), 3),
                "age_seconds": round(now_mono - ts, 3),
                "trace_id": trace_id,
                "trace": f"/debug/traces/{trace_id}" if trace_id else None,
            })
        out.append({
            "signal": signal,
            "kind": kind,
            "workload": name,
            "objective_seconds": t.objective_s,
            "target": t.target,
            "violations_total": v_total,
            "windows": {
                wname: {"requests": req, "violations": viol,
                        "burn_rate": round(rate, 6)}
                for wname, (req, viol, rate) in windows.items()
            },
            "recent_violations": violations,
        })
    return {"trackers": out}


def collect() -> List[FamilySnapshot]:
    """Scrape-time collector (registered on ``telemetry.GLOBAL``).

    Each tracker's lock is taken once, sequentially — never nested with
    any other lock."""
    with _REG_LOCK:
        trackers = sorted(_TRACKERS.items())
        meters = sorted(_METERS.items())
    now = time.monotonic()
    ingest_hist, feed_hist = [], []
    violations, burn, objective = [], [], []
    for (signal, kind, name), t in trackers:
        base = (("kind", kind), ("workload", name))
        (counts, total, count), v_total, windows = t.scrape(now)
        hist = histogram_snapshot(DEFAULT_LATENCY_BUCKETS, counts, total,
                                  count, base)
        (feed_hist if signal == "feed" else ingest_hist).extend(hist)
        sig = base + (("signal", signal),)
        violations.append(("", sig + (("window", "all"),), v_total))
        for wname, (_requests, wviol, rate) in windows.items():
            violations.append(("", sig + (("window", wname),), wviol))
            burn.append(("", sig + (("window", wname),), rate))
        objective.append(("", sig, t.objective_s))
    lag = [("", (("kind", kind), ("workload", name)), m.lag_seconds())
           for (kind, name), m in meters]
    return [
        FamilySnapshot(
            "duke_slo_ingest_latency_seconds", "histogram",
            "Per-workload ingest latency measured from scheduler arrival "
            "to microbatch completion (includes queueing delay)",
            ingest_hist),
        FamilySnapshot(
            "duke_slo_feed_latency_seconds", "histogram",
            "Per-workload ?since= feed read latency measured at the "
            "handler", feed_hist),
        FamilySnapshot(
            "duke_slo_violations_total", "counter",
            "Requests over the latency objective; window=all is the "
            "monotone total, window=5m/1h are recomputed at scrape from "
            "the violation-timestamp ring", violations),
        FamilySnapshot(
            "duke_slo_burn_rate", "gauge",
            "Error-budget burn rate per window: (violation ratio) / "
            "(1 - DUKE_SLO_TARGET); 1.0 spends exactly the budget",
            burn),
        FamilySnapshot(
            "duke_slo_objective_seconds", "gauge",
            "Latency objective per signal (DUKE_SLO_INGEST_MS / "
            "DUKE_SLO_FEED_MS)", objective),
        FamilySnapshot(
            "duke_feed_lag_seconds", "gauge",
            "Age of the oldest link-feed row written since a ?since= "
            "consumer last drained the feed (0 when caught up)", lag),
    ]
