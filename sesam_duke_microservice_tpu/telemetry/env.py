"""Shared env-knob parsing for the telemetry layer.

Malformed values fall back to the default — several of these run at
import time (the global trace recorder) or per-processor construction,
and a typo'd manifest must not keep the service from starting (the
convention every env knob in this codebase follows).
"""

from __future__ import annotations

import os
from typing import Optional

__all__ = ["env_int", "env_float", "env_int_tuple", "env_str", "env_flag"]


def env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def env_str(name: str, default: Optional[str] = None) -> Optional[str]:
    """Raw string knob.  ``default=None`` preserves set-vs-unset
    distinctions (several knobs auto-tune only while unset)."""
    return os.environ.get(name, default)


_FLAG_OFF = ("0", "false", "no", "off")
_FLAG_ON = ("1", "true", "yes", "on")


def env_flag(name: str, default: bool) -> bool:
    """Boolean knob.  ``0/false/no/off`` disable, ``1/true/yes/on``
    enable, anything else (including unset) keeps the default — the
    fail-to-default convention, applied to booleans."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    raw = raw.strip().lower()
    if raw in _FLAG_OFF:
        return False
    if raw in _FLAG_ON:
        return True
    return default


def env_int_tuple(name: str, default: str) -> tuple:
    """Comma-separated int list knob (e.g. DEVICE_QUERY_BUCKETS).  ONE
    copy of the parse + default so every consumer (the device matcher's
    ladder, the ingest scheduler's jax-less fallback) stays in sync."""
    raw = os.environ.get(name) or default
    try:
        return tuple(int(b) for b in raw.split(","))
    except ValueError:
        return tuple(int(b) for b in default.split(","))


def env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default
