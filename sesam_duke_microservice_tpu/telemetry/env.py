"""Shared env-knob parsing for the telemetry layer.

Malformed values fall back to the default — several of these run at
import time (the global trace recorder) or per-processor construction,
and a typo'd manifest must not keep the service from starting (the
convention every env knob in this codebase follows).
"""

from __future__ import annotations

import os

__all__ = ["env_int", "env_float"]


def env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default
