"""Distributed tracing + flight recorder (ISSUE 2 tentpole).

PR 1 made the service measurable in aggregate; this module makes ONE
request explainable.  The model is Dapper-style (Sigelman et al., 2010):
low-overhead always-on span recording with causal span trees, head
sampling, and — because aggregates exist precisely to find the slow
outliers — a **tail latch**: every request records its spans into a
per-trace scratch regardless of the sampling decision, and the tree is
retained if it was head-sampled *or* its total latency crossed
``TRACE_SLOW_MS``.  Slow requests are never lost.

Pieces:

  * ``Span`` — name, monotonic-ns start/end, attributes, status, causal
    parent.  Plain ``__slots__`` object; creating one is two monotonic
    reads and a list append.
  * ``span()`` — nesting context manager over a ``contextvars.ContextVar``
    (composes with ``logctx``'s request ids; worker threads join via
    ``current_context()``/``attach()``).  With no active trace it is a
    single contextvar read — libraries can span unconditionally.
  * ``start_trace()`` — opens a root span + scratch, honoring an inbound
    W3C ``traceparent`` (``parse_traceparent``/``format_traceparent``),
    and on exit applies the tail latch and lands the tree in the
    ``FlightRecorder``.
  * ``FlightRecorder`` — two bounded rings: retained trace trees
    (``/debug/traces``) and an always-on last-N request digest ring with
    per-phase timings even for unretained requests (``/debug/requests``).
  * ``capture_remote()``/``graft_remote()`` — follower-side replay spans
    serialized into the dispatch digest handshake and re-anchored into
    the leader's live trace, so one tree spans the whole mesh
    (parallel/dispatch.py).
  * ``chrome_trace()`` — Chrome trace-event JSON (loadable in Perfetto /
    chrome://tracing).

Overhead stance (the budget in ISSUE 2): the unsampled fast path per
span is one contextvar get, a set/reset pair, two ``monotonic_ns`` reads
and a list append — no locks on the span path (GIL-atomic appends, the
registry's single-writer tolerance), no device syncs ever, and all
exporter/digest work happens at retention time.  Device-timeline
bridging (``annotate=True``) activates only while a ``jax.profiler``
capture is live, so idle serving never touches jax from here.
"""

from __future__ import annotations

import contextlib
import contextvars
import itertools
import json
import random
import re
import threading
import time
from collections import deque
from typing import Any, Dict, Iterator, List, Optional

from .env import env_float, env_int
from .logctx import current_request_id

__all__ = [
    "Span",
    "TraceContext",
    "FlightRecorder",
    "RECORDER",
    "span",
    "add_span",
    "add_phase_spans",
    "start_trace",
    "current_context",
    "attach",
    "current_trace_id",
    "parse_traceparent",
    "format_traceparent",
    "propagation_context",
    "capture_remote",
    "graft_remote",
    "chrome_trace",
    "trace_to_json",
    "set_device_annotations",
    "device_annotations_active",
]


# -- env knobs (read at call time so tests and reloads take effect) ----------

# shared fallback-on-ValueError parsing (telemetry.env): this runs at
# import via the global RECORDER
_env_int = env_int


def _sample_rate() -> float:
    """Head-sampling probability in [0, 1] (``TRACE_SAMPLE_RATE``)."""
    return min(1.0, max(0.0, env_float("TRACE_SAMPLE_RATE", 0.01)))


def _slow_ms() -> float:
    """Tail-latch threshold (``TRACE_SLOW_MS``); <= 0 disables the latch."""
    return env_float("TRACE_SLOW_MS", 1000.0)


def _max_spans() -> int:
    """Per-trace span cap (``TRACE_MAX_SPANS``) — a pathological request
    (per-link spans over a huge feed) must stay O(cap), not O(work)."""
    return max(1, env_int("TRACE_MAX_SPANS", 512))


# id generation: uniqueness, not cryptographic strength — a per-process
# PRNG (urandom-seeded once) plus a monotone counter tail keeps the
# always-on span path free of per-span os.urandom syscalls while making
# in-process collisions impossible (the counter) and cross-process
# collisions 2^-104 (the random prefix).  getrandbits/next are single
# C calls, atomic under the GIL.
_RNG = random.Random()
_SEQ = itertools.count()


def _new_trace_id() -> str:
    return f"{_RNG.getrandbits(104):026x}{next(_SEQ) & 0xFFFFFF:06x}"


def _new_span_id() -> str:
    return f"{_RNG.getrandbits(40):010x}{next(_SEQ) & 0xFFFFFF:06x}"


class Span:
    """One timed operation.  ``start_ns``/``end_ns`` are monotonic."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "start_ns",
                 "end_ns", "attributes", "status")

    def __init__(self, trace_id: str, span_id: str, parent_id: Optional[str],
                 name: str, start_ns: int,
                 attributes: Optional[Dict[str, Any]] = None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.start_ns = start_ns
        self.end_ns = start_ns
        self.attributes = attributes
        self.status = "ok"

    @property
    def duration_ns(self) -> int:
        return self.end_ns - self.start_ns

    def set_attribute(self, key: str, value: Any) -> None:
        if self.attributes is None:
            self.attributes = {}
        self.attributes[key] = value

    def to_dict(self, base_ns: int) -> Dict[str, Any]:
        return {
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "start_us": (self.start_ns - base_ns) / 1000.0,
            "duration_us": self.duration_ns / 1000.0,
            "status": self.status,
            "attributes": self.attributes or {},
        }


class _Trace:
    """Per-request scratch: the span buffer behind the tail latch.

    Appends are plain list ops (GIL-atomic) — worker threads adopted via
    ``attach()`` may append concurrently and the rare torn ``dropped``
    increment is accepted, matching the registry's unlocked-child
    stance.
    """

    __slots__ = ("trace_id", "sampled", "spans", "started_ns",
                 "started_unix", "max_spans", "dropped")

    def __init__(self, trace_id: str, sampled: bool):
        self.trace_id = trace_id
        self.sampled = sampled
        self.spans: List[Span] = []
        self.started_ns = time.monotonic_ns()
        self.started_unix = time.time()
        self.max_spans = _max_spans()
        self.dropped = 0

    def add(self, span_obj: Span) -> None:
        if len(self.spans) >= self.max_spans:
            self.dropped += 1
            return
        self.spans.append(span_obj)


# (trace, active span id) — None outside any request
_ACTIVE: contextvars.ContextVar = contextvars.ContextVar(
    "duke_trace", default=None
)


def current_context():
    """Opaque (trace, span-id) token for cross-thread propagation: a
    worker thread re-enters the request's trace with ``attach(token)``."""
    return _ACTIVE.get()


@contextlib.contextmanager
def attach(ctx) -> Iterator[None]:
    """Adopt a ``current_context()`` token on another thread."""
    token = _ACTIVE.set(ctx)
    try:
        yield
    finally:
        _ACTIVE.reset(token)


def current_trace_id() -> Optional[str]:
    active = _ACTIVE.get()
    return active[0].trace_id if active is not None else None


def sampled_trace_id() -> Optional[str]:
    """The active trace id only when that trace is actually recorded —
    the form exemplars (SLO violations) must use, because an unsampled
    id would be a dead link in /debug/traces."""
    active = _ACTIVE.get()
    if active is None or not active[0].sampled:
        return None
    return active[0].trace_id


# -- W3C trace context -------------------------------------------------------

class TraceContext:
    """Parsed ``traceparent``: remote trace id + parent span + sampled."""

    __slots__ = ("trace_id", "parent_id", "sampled")

    def __init__(self, trace_id: str, parent_id: str, sampled: bool):
        self.trace_id = trace_id
        self.parent_id = parent_id
        self.sampled = sampled


_TRACEPARENT_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$"
)


def parse_traceparent(value: Optional[str]) -> Optional[TraceContext]:
    """W3C traceparent: ``version-traceid-parentid-flags`` (lower hex).
    Returns None for absent/malformed/all-zero values (the spec's
    restart semantics: an invalid header starts a fresh trace)."""
    if not value:
        return None
    m = _TRACEPARENT_RE.match(value.strip())
    if m is None:
        return None
    version, trace_id, parent_id, flags = m.groups()
    if version == "ff" or set(trace_id) == {"0"} or set(parent_id) == {"0"}:
        return None
    return TraceContext(trace_id, parent_id, bool(int(flags, 16) & 0x01))


def format_traceparent(trace_id: str, span_id: str, sampled: bool) -> str:
    return f"00-{trace_id}-{span_id}-{'01' if sampled else '00'}"


# -- flight recorder ---------------------------------------------------------

class TraceRecord:
    """One retained trace tree plus its summary row."""

    __slots__ = ("trace_id", "name", "request_id", "started_unix",
                 "base_ns", "duration_ms", "spans", "sampled", "slow",
                 "status", "dropped")

    def __init__(self, trace: _Trace, root: Span, *, slow: bool):
        self.trace_id = trace.trace_id
        self.name = root.name
        self.request_id = (root.attributes or {}).get(
            "request_id", current_request_id())
        self.started_unix = trace.started_unix
        self.base_ns = root.start_ns
        self.duration_ms = root.duration_ns / 1e6
        self.spans = trace.spans
        self.sampled = trace.sampled
        self.slow = slow
        self.status = root.status
        self.dropped = trace.dropped

    def summary(self) -> Dict[str, Any]:
        return {
            "trace_id": self.trace_id,
            "name": self.name,
            "request_id": self.request_id,
            "time_unix": round(self.started_unix, 3),
            "duration_ms": round(self.duration_ms, 3),
            "span_count": len(self.spans),
            "slow": self.slow,
            "sampled": self.sampled,
            "status": self.status,
        }


def _phase_seconds(spans: List[Span]) -> Dict[str, float]:
    """Per-phase seconds summed from engine phase spans (the four names
    from engine/processor.py)."""
    out: Dict[str, float] = {}
    for s in spans:
        if s.name in ("encode", "retrieve", "score", "persist"):
            out[s.name] = out.get(s.name, 0.0) + s.duration_ns / 1e9
    return {k: round(v, 6) for k, v in out.items()}


class FlightRecorder:
    """Two bounded rings: retained trace trees + always-on request digests.

    Ring sizes come from ``TRACE_RING_SIZE`` (retained trees, default 128)
    and ``REQUEST_RING_SIZE`` (digests, default 512) at construction.
    All mutation happens at retention time under a short lock — never on
    the span recording path.  The retained-tree ring is a
    ``rings.LatchedRing`` (the eviction/latch core shared with the
    decision recorder): slow/errored traces are *remarkable*, so an
    upstream stamping every request sampled cannot flush them.
    """

    def __init__(self, trace_capacity: Optional[int] = None,
                 digest_capacity: Optional[int] = None):
        from .rings import LatchedRing

        if trace_capacity is None:
            trace_capacity = _env_int("TRACE_RING_SIZE", 128)
        if digest_capacity is None:
            digest_capacity = _env_int("REQUEST_RING_SIZE", 512)
        self._lock = threading.Lock()
        self._ring = LatchedRing(max(1, trace_capacity))
        self._digests: deque = deque(maxlen=max(1, digest_capacity))

    def finish(self, trace: _Trace, root: Span) -> bool:
        """Apply the tail latch to a completed trace: always digest,
        retain the tree when sampled / slow / errored.  Returns whether
        the tree was retained."""
        duration_ms = root.duration_ns / 1e6
        slow_ms = _slow_ms()
        slow = slow_ms > 0 and duration_ms >= slow_ms
        retain = trace.sampled or slow or root.status != "ok"
        digest = {
            "trace_id": trace.trace_id,
            "request_id": (root.attributes or {}).get(
                "request_id", current_request_id()),
            "name": root.name,
            "time_unix": round(trace.started_unix, 3),
            "duration_ms": round(duration_ms, 3),
            "span_count": len(trace.spans),
            "status": root.status,
            "phase_seconds": _phase_seconds(trace.spans),
            "slow": slow,
            "sampled": trace.sampled,
            "retained": retain,
        }
        with self._lock:
            self._digests.append(digest)
        if retain:
            with self._ring.lock:
                existing = self._ring.get(trace.trace_id)
                if existing is not None:
                    # the same trace id retained again — a follower
                    # replaying several ops of one request, or a client
                    # reusing a traceparent: MERGE into the stored tree
                    # (same-process monotonic clock, so the first
                    # record's base anchors the added spans correctly)
                    # rather than silently dropping the earlier trees.
                    # Bounded: a fixed traceparent must not grow one
                    # record without limit (4x the per-trace cap, then
                    # overflow counts as dropped)
                    room = 4 * _max_spans() - len(existing.spans)
                    added = trace.spans[:max(0, room)]
                    existing.spans = existing.spans + added
                    existing.dropped += (trace.dropped
                                         + len(trace.spans) - len(added))
                    existing.slow = existing.slow or slow
                    if root.status != "ok":
                        existing.status = root.status
                    existing.duration_ms = max(
                        existing.duration_ms, root.duration_ns / 1e6)
                    record = existing
                else:
                    record = TraceRecord(trace, root, slow=slow)
                # keeps the key's ring position on merge; eviction
                # prefers unremarkable (fast, ok) records — rings.py
                self._ring.put(
                    record.trace_id, record,
                    remarkable=record.slow or record.status != "ok",
                )
        return retain

    def summaries(self) -> List[Dict[str, Any]]:
        """Most-recent-first summary rows for ``GET /debug/traces``."""
        return [r.summary() for r in self._ring.records()]

    def get(self, trace_id: str) -> Optional[TraceRecord]:
        return self._ring.get(trace_id)

    def digests(self) -> List[Dict[str, Any]]:
        """Most-recent-first request digests for ``GET /debug/requests``."""
        with self._lock:
            return list(reversed(self._digests))

    def clear(self) -> None:
        self._ring.clear()
        with self._lock:
            self._digests.clear()


RECORDER = FlightRecorder()


# -- device-timeline bridging ------------------------------------------------

# flipped by utils/profiling while a jax.profiler capture is live: spans
# created with ``annotate=True`` then also enter jax.profiler
# TraceAnnotation so the device timeline carries the same names.  A plain
# bool read on the span path; jax is touched only while capturing.
_ANNOTATE = False


def set_device_annotations(enabled: bool) -> None:
    global _ANNOTATE
    _ANNOTATE = bool(enabled)


def device_annotations_active() -> bool:
    return _ANNOTATE


def _enter_annotation(name: str):
    try:
        import jax

        ann = jax.profiler.TraceAnnotation(name)
        ann.__enter__()
        return ann
    except Exception:
        return None


# -- span recording ----------------------------------------------------------

class _SpanCtx:
    """The ``span()`` context manager as a slotted class: the unsampled
    fast path stays one contextvar get (+ a set/reset pair and two
    monotonic reads when a trace is active)."""

    __slots__ = ("_name", "_attributes", "_annotate", "_span", "_token",
                 "_trace", "_ann")

    def __init__(self, name: str, attributes: Optional[Dict[str, Any]],
                 annotate: bool):
        self._name = name
        self._attributes = attributes
        self._annotate = annotate
        self._span = None
        self._token = None
        self._trace = None
        self._ann = None

    def __enter__(self) -> Optional[Span]:
        active = _ACTIVE.get()
        if active is None:
            return None
        trace, parent_id = active
        s = Span(trace.trace_id, _new_span_id(), parent_id, self._name,
                 time.monotonic_ns(), self._attributes)
        self._span = s
        self._trace = trace
        self._token = _ACTIVE.set((trace, s.span_id))
        if self._annotate and _ANNOTATE:
            self._ann = _enter_annotation(self._name)
        return s

    def __exit__(self, exc_type, exc, tb) -> bool:
        s = self._span
        if s is None:
            return False
        if self._ann is not None:
            try:
                self._ann.__exit__(exc_type, exc, tb)
            except Exception:
                pass
        _ACTIVE.reset(self._token)
        s.end_ns = time.monotonic_ns()
        if exc_type is not None:
            s.status = "error"
            s.set_attribute("error", repr(exc))
        self._trace.add(s)
        return False


def span(name: str, attributes: Optional[Dict[str, Any]] = None,
         *, annotate: bool = False) -> _SpanCtx:
    """Open a child span under the active trace (no-op outside one).

    ``annotate=True`` additionally bridges the span into
    ``jax.profiler.TraceAnnotation`` while a device capture is live, so
    the device timeline carries the same phase names."""
    return _SpanCtx(name, attributes, annotate)


def add_span(name: str, start_ns: int, end_ns: int,
             attributes: Optional[Dict[str, Any]] = None) -> None:
    """Record an already-measured interval as a completed child span.

    Used where phase boundaries interleave (the engine's retrieve/score
    accounting splits one region by accumulated stats) — the caller
    supplies the interval; nothing re-reads the clock."""
    active = _ACTIVE.get()
    if active is None:
        return
    trace, parent_id = active
    s = Span(trace.trace_id, _new_span_id(), parent_id, name, start_ns,
             attributes)
    s.end_ns = max(start_ns, end_ns)
    trace.add(s)


def add_phase_spans(start_ns: int, retrieve_seconds: float,
                    score_seconds: float) -> None:
    """The engines' shared retrieve/score span layout: both phases
    interleave (per record on the host, per double-buffered block on the
    device), so their spans carry the ACCUMULATED durations laid out
    sequentially from the matching region's start — the timeline shows
    where the batch's time went, not exact intervals."""
    r_end = start_ns + int(retrieve_seconds * 1e9)
    add_span("retrieve", start_ns, r_end, {"aggregate": True})
    add_span("score", r_end, r_end + int(score_seconds * 1e9),
             {"aggregate": True})


class _RootCtx:
    """``start_trace()``: root span + scratch + tail-latch retention."""

    __slots__ = ("_name", "_attributes", "_traceparent", "_sampled",
                 "_recorder", "_trace", "_root", "_token", "retained")

    def __init__(self, name: str, attributes, traceparent, sampled,
                 recorder):
        self._name = name
        self._attributes = attributes
        self._traceparent = traceparent
        self._sampled = sampled
        self._recorder = recorder
        self._trace = None
        self._root = None
        self._token = None
        self.retained = False

    def __enter__(self) -> Span:
        ctx = parse_traceparent(self._traceparent)
        if ctx is not None:
            trace_id, parent_id, sampled = (
                ctx.trace_id, ctx.parent_id, ctx.sampled)
        else:
            trace_id, parent_id = _new_trace_id(), None
            sampled = _RNG.random() < _sample_rate()
        if self._sampled is not None:
            sampled = bool(self._sampled)
        trace = _Trace(trace_id, sampled)
        root = Span(trace_id, _new_span_id(), parent_id, self._name,
                    trace.started_ns, self._attributes)
        self._trace, self._root = trace, root
        self._token = _ACTIVE.set((trace, root.span_id))
        return root

    def __exit__(self, exc_type, exc, tb) -> bool:
        _ACTIVE.reset(self._token)
        root = self._root
        root.end_ns = time.monotonic_ns()
        if exc_type is not None:
            root.status = "error"
            root.set_attribute("error", repr(exc))
        trace = self._trace
        if trace.dropped:
            root.set_attribute("spans_dropped", trace.dropped)
        # the root bypasses the span cap: a pathological request must
        # still land its tree's anchor (and the digest's duration source)
        trace.spans.append(root)
        recorder = self._recorder if self._recorder is not None else RECORDER
        self.retained = recorder.finish(trace, root)
        return False


def start_trace(name: str, *, traceparent: Optional[str] = None,
                attributes: Optional[Dict[str, Any]] = None,
                sampled: Optional[bool] = None,
                recorder: Optional[FlightRecorder] = None) -> _RootCtx:
    """Open a root span (one per request / bench batch).

    An inbound W3C ``traceparent`` is honored: its trace id continues
    and its sampled flag is inherited, so a mesh of services shares one
    head-sampling decision.  ``sampled`` forces the decision (bench);
    ``recorder`` overrides the process recorder (tests)."""
    return _RootCtx(name, attributes, traceparent, sampled, recorder)


def propagation_context() -> Optional[Dict[str, Any]]:
    """The active trace context as a small picklable dict, for embedding
    in dispatch op tuples (parallel/dispatch.py).  None outside a trace
    — callers skip the op-tuple field entirely."""
    active = _ACTIVE.get()
    if active is None:
        return None
    trace, span_id = active
    return {"trace_id": trace.trace_id, "parent_id": span_id,
            "sampled": trace.sampled}


# -- remote (follower) spans -------------------------------------------------

class _RemoteCapture:
    """Follower-side capture of one replay as a remote child span tree.

    Opens a detached trace continuing the leader's ids so nested engine
    spans (the replica's commit path) land in the same tree; ``wire()``
    serializes the collected spans (offsets relative to the capture
    root) for the digest handshake.  With ``ctx=None`` (no active trace
    on the leader) the capture is a no-op and ``wire()`` is empty.

    Ops with no response channel (score, rematch) pass ``recorder``
    instead: the replay tree lands in the follower's LOCAL flight
    recorder under the leader's trace id (same tail-latch rules).
    """

    __slots__ = ("_ctx", "_name", "_attributes", "_trace", "_root",
                 "_token", "_recorder")

    def __init__(self, name: str, ctx: Optional[Dict[str, Any]],
                 attributes: Optional[Dict[str, Any]],
                 recorder: Optional[FlightRecorder] = None):
        self._name = name
        self._ctx = ctx
        self._attributes = attributes
        self._recorder = recorder
        self._trace = None
        self._root = None
        self._token = None

    def __enter__(self) -> "_RemoteCapture":
        if self._ctx is None:
            return self
        trace = _Trace(str(self._ctx["trace_id"]),
                       bool(self._ctx.get("sampled")))
        root = Span(trace.trace_id, _new_span_id(),
                    self._ctx.get("parent_id"), self._name,
                    trace.started_ns, self._attributes)
        self._trace, self._root = trace, root
        self._token = _ACTIVE.set((trace, root.span_id))
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if self._root is None:
            return False
        _ACTIVE.reset(self._token)
        self._root.end_ns = time.monotonic_ns()
        if exc_type is not None:
            self._root.status = "error"
            self._root.set_attribute("error", repr(exc))
        self._trace.spans.append(self._root)  # root bypasses the cap
        if self._recorder is not None:
            self._recorder.finish(self._trace, self._root)
        return False

    def wire(self) -> bytes:
        """Collected spans as compact JSON (raw bytes for the handshake
        frame — never pickle on the response path)."""
        if self._root is None:
            return b""
        base = self._root.start_ns
        rows = []
        for s in self._trace.spans:
            rows.append({
                "trace_id": s.trace_id,
                "span_id": s.span_id,
                "parent_id": s.parent_id,
                "name": s.name,
                "offset_ns": s.start_ns - base,
                "duration_ns": s.duration_ns,
                "status": s.status,
                "attributes": s.attributes or {},
            })
        return json.dumps(rows, separators=(",", ":")).encode("utf-8")


def capture_remote(name: str, ctx: Optional[Dict[str, Any]],
                   attributes: Optional[Dict[str, Any]] = None,
                   recorder: Optional[FlightRecorder] = None
                   ) -> _RemoteCapture:
    """Wrap a follower replay in a remote child span of the leader's
    trace (see ``_RemoteCapture``)."""
    return _RemoteCapture(name, ctx, attributes, recorder)


def graft_remote(payload: bytes) -> int:
    """Leader side: splice follower replay spans into the active trace.

    Follower monotonic clocks are unrelated to the leader's, so the
    remote tree is re-anchored to end at graft time (the handshake read
    just completed, so that is within socket latency of the truth).
    Returns the number of spans grafted (0 on no payload / no active
    trace / trace-id mismatch)."""
    if not payload:
        return 0
    active = _ACTIVE.get()
    if active is None:
        return 0
    trace, _ = active
    try:
        rows = json.loads(payload.decode("utf-8"))
    except (ValueError, UnicodeDecodeError):
        return 0
    if not isinstance(rows, list) or not rows:
        return 0
    try:
        total_ns = max(int(r["offset_ns"]) + int(r["duration_ns"])
                       for r in rows)
        anchor = time.monotonic_ns() - total_ns
        grafted = 0
        for r in rows:
            if r.get("trace_id", trace.trace_id) != trace.trace_id:
                continue
            s = Span(trace.trace_id, str(r["span_id"]),
                     r.get("parent_id"), str(r["name"]),
                     anchor + int(r["offset_ns"]),
                     dict(r.get("attributes") or {}) or None)
            s.end_ns = s.start_ns + int(r["duration_ns"])
            s.status = str(r.get("status", "ok"))
            s.set_attribute("remote", True)
            trace.add(s)
            grafted += 1
        return grafted
    except (KeyError, TypeError, ValueError):
        return 0


# -- exporters ---------------------------------------------------------------

def trace_to_json(record: TraceRecord) -> Dict[str, Any]:
    """Flat JSON tree for ``GET /debug/traces/<id>`` (default format)."""
    out = record.summary()
    out["spans"] = [s.to_dict(record.base_ns) for s in record.spans]
    out["spans_dropped"] = record.dropped
    return out


def chrome_trace(record: TraceRecord) -> Dict[str, Any]:
    """Chrome trace-event JSON (the Perfetto-loadable export target).

    Complete ("X") events with microsecond timestamps relative to the
    root span; remote (follower) spans land on their own tid row so the
    leader/follower split reads directly off the timeline."""
    events: List[Dict[str, Any]] = [
        {"ph": "M", "name": "process_name", "pid": 0, "tid": 0,
         "args": {"name": f"duke {record.name}"}},
        {"ph": "M", "name": "thread_name", "pid": 0, "tid": 0,
         "args": {"name": "leader"}},
        {"ph": "M", "name": "thread_name", "pid": 0, "tid": 1,
         "args": {"name": "followers"}},
    ]
    for s in record.spans:
        attrs = s.attributes or {}
        events.append({
            "name": s.name,
            "ph": "X",
            "ts": (s.start_ns - record.base_ns) / 1000.0,
            "dur": max(s.duration_ns, 0) / 1000.0,
            "pid": 0,
            "tid": 1 if attrs.get("remote") else 0,
            "cat": "duke",
            "args": {
                "span_id": s.span_id,
                "parent_id": s.parent_id,
                "status": s.status,
                **attrs,
            },
        })
    return {
        "displayTimeUnit": "ms",
        "otherData": {
            "trace_id": record.trace_id,
            "request_id": record.request_id,
        },
        "traceEvents": events,
    }
