"""Telemetry subsystem: metrics registry, Prometheus exposition, request ids.

Two registries exist at runtime:

  * ``GLOBAL`` (here) — process-wide instruments owned by layers that
    have no DukeApp in scope: the JIT compile/cache counters
    (utils/jit_cache.py), query-padding-bucket and corpus-growth
    counters (engine/device_matcher.py), mesh/dispatch instruments
    (engine/sharded_matcher.py, parallel/dispatch.py).
  * a per-``DukeApp`` registry (service/metrics.py) — HTTP families and
    the workload-walking collector (engine phase histograms, corpus
    gauges, link-store rows).  Per-app so tests and hot reloads never
    leak series across app instances.

``GET /metrics`` renders both (``registry.render(app.metrics, GLOBAL)``).

Naming scheme: every family is ``duke_<subsystem>_<metric>[_total]`` with
base units (seconds, bytes, rows); latency histograms share the fixed
log-scale ladder ``DEFAULT_LATENCY_BUCKETS``.
"""

from .registry import (  # noqa: F401
    CONTENT_TYPE,
    DEFAULT_LATENCY_BUCKETS,
    Counter,
    FamilySnapshot,
    Gauge,
    Histogram,
    MetricRegistry,
    PhaseRecorder,
    histogram_snapshot,
    render,
)

# the tracing layer (ISSUE 2): imported as a submodule attribute so every
# layer can `from ..telemetry import tracing` without a second import line
from . import tracing  # noqa: F401  (imports only stdlib + .logctx)

GLOBAL = MetricRegistry()

# -- JIT layer (written via utils/jit_cache record_* helpers) ----------------
# Label-less: both exist (at 0) from first import, so the acceptance
# contract — /metrics always includes the JIT compile counter — holds on
# every backend including pure-host serving.  Unlocked: the cache-hit
# increment sits on the per-block scoring path, which must stay
# lock-free; concurrent workloads racing an increment may very rarely
# lose a count, accepted for these visibility counters.
JIT_COMPILES = GLOBAL.counter(
    "duke_jit_compiles_total",
    "Scorer/updater program builds (jit cache misses + pre-warm compiles)",
    locked=False,
)
JIT_CACHE_HITS = GLOBAL.counter(
    "duke_jit_cache_hits_total",
    "Scorer lookups served from the in-process jit program cache",
    locked=False,
)
# AOT executable cache (ISSUE 15: utils/jit_cache.AotStore).  Loads run
# at startup / on shape-fingerprint changes, never per block, so the
# labeled family is fine; the per-call fast path only bumps
# JIT_CACHE_HITS above.
AOT_LOADS = GLOBAL.counter(
    "duke_aot_loads_total",
    "Plan-keyed AOT executable-store load attempts by outcome (hit = "
    "deserialized and serving, miss = no entry for the key, reject = "
    "entry present but unusable — recompiled and re-saved by the warm "
    "thread)",
    ("outcome",),
)
PREWARM_FAILURES = GLOBAL.counter(
    "duke_prewarm_failures_total",
    "Scorer pre-warm / AOT warm-thread failures: scoring still works but "
    "the replica is silently cold (first-contact shapes pay live "
    "compiles).  The last error is surfaced in /healthz detail.",
)
COLD_START_SECONDS = GLOBAL.gauge(
    "duke_cold_start_seconds",
    "Seconds from service construction to the first successfully served "
    "scoring batch (time-to-first-200; 0 until a batch lands)",
)

# -- device corpus growth (engine/device_matcher.py) -------------------------
# Process-wide (not per-corpus) so value-slot rebuilds — which replace the
# corpus object — can never reset the series mid-scrape.
CORPUS_GROWTHS = GLOBAL.counter(
    "duke_corpus_capacity_growths_total",
    "Corpus capacity-doubling events (each forces a full device re-upload)",
)
CORPUS_FULL_UPLOADS = GLOBAL.counter(
    "duke_corpus_full_uploads_total",
    "Whole-corpus device uploads (growth, restore, or mask-refresh fallback)",
)

# -- query padding buckets (engine/device_matcher.py) ------------------------
# Unlocked: incremented once per dispatched block by the thread holding
# that workload's lock; concurrent blocks from DIFFERENT workloads can in
# principle race a child, and the rare lost count is accepted — these
# counters exist to make recompile storms and padding waste visible, and
# the scoring path must stay lock-free (acceptance criterion).
QUERY_BLOCKS = GLOBAL.counter(
    "duke_query_blocks_total",
    "Dispatched query blocks by padded bucket size",
    ("bucket",), locked=False,
)
QUERY_PAD_ROWS = GLOBAL.counter(
    "duke_query_padding_rows_total",
    "Padding rows added to reach the block's bucket size",
    ("bucket",), locked=False,
)
SCORER_ESCALATIONS = GLOBAL.counter(
    "duke_scorer_escalations_total",
    "K/C-escalation re-runs of the device scoring program",
)
# stage-attributed escalation series (ISSUE 9): which retrieval stage
# saturated — brute-force top-K, flat-ANN top-C, or the IVF cell probe
# (whose ladder widens nprobe and terminally falls back to the flat
# scan).  The label set is closed (three stages), written only on the
# rare escalation path.
RETRIEVAL_ESCALATIONS = GLOBAL.counter(
    "duke_retrieval_escalations_total",
    "Retrieval-width escalation re-runs by saturated stage "
    "(top_k = brute force, top_c = flat ANN, ivf = cell probe)",
    ("stage",),
)

# -- streaming encode (engine/device_matcher.py) -----------------------------
# Unlocked: incremented by the thread holding the workload lock (same
# discipline as QUERY_BLOCKS).  The encode-cache hit/miss/evicted rows and
# cache-bytes gauge are scrape-time snapshots of ops.feature_cache state
# (service/metrics.make_process_collector) — the encode path never writes
# a registry child for them.
STREAM_APPEND_SLICES = GLOBAL.counter(
    "duke_stream_append_slices_total",
    "Device-corpus append slices flushed under the extract/upload overlap "
    "(DUKE_STREAM_APPEND)",
    locked=False,
)

# -- multi-host dispatch (parallel/dispatch.py) ------------------------------
DISPATCH_OPS = GLOBAL.counter(
    "duke_dispatch_ops_total",
    "Ops broadcast on the multi-host dispatch stream, by op tag",
    ("op",),
)
DISPATCH_BYTES = GLOBAL.counter(
    "duke_dispatch_bytes_total",
    "Serialized bytes broadcast on the multi-host dispatch stream",
)
DISPATCH_FOLLOWERS = GLOBAL.gauge(
    "duke_dispatch_followers",
    "Connected follower processes (frontend only)",
)
DISPATCH_DOWN = GLOBAL.gauge(
    "duke_dispatch_down",
    "1 once the dispatcher latched failed (mesh ops refused until restart)",
)
FOLLOWER_REPLAY_SECONDS = GLOBAL.histogram(
    "duke_follower_replay_seconds",
    "Follower-side replay time per dispatch op",
    ("op",),
)

# -- HA serving group (ISSUE 8: parallel/dispatch.py, links/replica.py) ------
FOLLOWER_EVICTIONS = GLOBAL.counter(
    "duke_follower_evictions_total",
    "Followers evicted from the serving group after exhausted send "
    "retries, a dead digest handshake, or mirror divergence — the slice "
    "degrades to the survivors instead of latching down",
)
DISPATCH_EPOCH = GLOBAL.gauge(
    "duke_epoch",
    "Leadership epoch fencing the dispatch op stream (followers reject "
    "lower-epoch ops from a zombie ex-leader; promotion bumps it)",
)
REPLICA_LAG = GLOBAL.gauge(
    "duke_replica_lag_ops",
    "Link-stream ops this follower has seen but not yet applied to its "
    "replica link DB (head seq - applied watermark), by workload",
    ("kind", "workload"),
)
FAULTS_INJECTED = GLOBAL.counter(
    "duke_faults_injected_total",
    "Faults injected by the deterministic DUKE_FAULTS chaos layer, by "
    "kind",
    ("kind",),
)

# -- crash-consistent ingest (ISSUE 10: links/journal.py, recovery) ----------
# Written on startup/rare paths only; the journal's per-workload gauges
# (duke_journal_batches, duke_journal_bytes) are scrape-time snapshots in
# the app collector, so the append path never writes a registry child.
JOURNAL_TORN_TAILS = GLOBAL.counter(
    "duke_journal_torn_tails_total",
    "Torn or corrupt link-journal tails truncated by the startup scan "
    "(a crash mid-append; bounded to the final partial frame, logged, "
    "never fatal)",
)
RECOVERY_REPLAYED = GLOBAL.counter(
    "duke_recovery_replayed_total",
    "Journaled link batches replayed into the durable link store by "
    "startup recovery (batches a crash stranded between ack and flush)",
)
# recovery progress (ISSUE 16): while /readyz says `recovering`, these
# distinguish "almost done" from "wedged" — remaining counts down chunk
# by chunk as the replay loop applies, applied counts up monotonically.
RECOVERY_REPLAY_REMAINING = GLOBAL.gauge(
    "duke_recovery_replay_remaining_batches",
    "Journaled link batches still awaiting replay by the running "
    "startup recovery (0 when recovery is idle or done)",
)
RECOVERY_REPLAY_APPLIED = GLOBAL.counter(
    "duke_recovery_replay_applied_total",
    "Journaled link batches applied by startup recovery replay loops "
    "since process start (advances chunk by chunk while /readyz still "
    "says recovering)",
)
SNAPSHOT_FALLBACKS = GLOBAL.counter(
    "duke_snapshot_fallbacks_total",
    "Corpus snapshots rejected into a full store replay, by reason "
    "(corrupt = unreadable archive, checksum = stamped content checksum "
    "mismatch, content = store drifted past the snapshot, schema = "
    "plan/tensor-shape mismatch, fingerprint = env/plan fingerprint "
    "mismatch)",
    ("reason",),
)

# -- mesh (engine/sharded_matcher.py) ----------------------------------------
MESH_DEVICES = GLOBAL.gauge(
    "duke_mesh_devices",
    "Devices in the serving mesh (0 until a sharded backend builds one)",
)

# -- runtime SLO signals (ISSUE 16: telemetry/slo.py) ------------------------
# Imported last: slo only needs .env/.registry, and registering its
# scrape-time collector here keeps every process that renders GLOBAL —
# leader app, replica plane, federation plane — serving the burn-rate,
# latency-objective and feed-lag families with no per-surface wiring.
from . import slo  # noqa: E402,F401

GLOBAL.register_collector(slo.collect)

# -- resource attribution (ISSUE 17: telemetry/{costs,memory}.py) ------------
# Same pattern: the device-time cost ledger and the HBM ledger register
# scrape-time collectors on GLOBAL so every plane serves the busy /
# compile / utilization and headroom / overflow families for free.
from . import costs  # noqa: E402,F401
from . import memory  # noqa: E402,F401

GLOBAL.register_collector(costs.collect)
GLOBAL.register_collector(memory.collect)
