"""Dependency-free metrics registry with Prometheus text exposition.

The observability spine (ISSUE 1): ``Counter`` / ``Gauge`` / ``Histogram``
families with labels, rendered in the Prometheus text format (0.0.4) by
``render``.  Nothing here imports jax or anything else from this package —
the registry must be importable from every layer (service, engine, utils,
parallel) without cycles and must work in processes that never touch a
device.

Concurrency model (mirrors the codebase's existing /stats stance,
engine/device_matcher.py live_records):

  * Children created with the default ``locked=True`` take a per-child
    lock around updates — correct for multi-writer sites like the HTTP
    handler threads, where the nanosecond lock is nowhere near a device
    hot path.
  * Children created with ``locked=False`` update plain attributes with
    no lock at all.  That is the ENGINE contract: scoring-path
    instruments are written by exactly one thread at a time (the
    workload lock already serializes batches), so unlocked updates are
    exact there, and the scoring path acquires no locks for metrics.
    Scrapes read these fields lock-free and tolerate a torn multi-field
    read, exactly like the existing lock-free /stats counters.
  * Child creation (``labels()``) locks the family; steady state is a
    plain dict hit.

Histogram buckets default to a fixed log-scale latency ladder
(100 µs .. 2 min) so every latency family shares one bucket layout and
recording stays O(#buckets) with zero allocation.
"""

from __future__ import annotations

import math
import re
import threading
from bisect import bisect_left
from typing import Callable, Dict, Iterable, List, Sequence, Tuple

# ~log-scale (1 / 2.5 / 5 per decade) from 100 microseconds to 2 minutes:
# wide enough for pair-scoring microbatches and for multi-second
# first-contact XLA compiles on the same ladder.
DEFAULT_LATENCY_BUCKETS: Tuple[float, ...] = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 120.0,
)

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")


class _NullLock:
    """No-op context manager for single-writer (engine-side) children."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_LOCK = _NullLock()


def _fmt(value: float) -> str:
    """Prometheus sample value: integers without the trailing .0 noise."""
    if value == math.inf:
        return "+Inf"
    if value == -math.inf:
        return "-Inf"
    if value != value:  # NaN
        return "NaN"
    as_int = int(value)
    return str(as_int) if as_int == value else repr(value)


def escape_label_value(value: str) -> str:
    return (str(value).replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _fmt_labels(labels: Sequence[Tuple[str, str]]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{escape_label_value(v)}"' for k, v in labels
    )
    return "{" + inner + "}"


class FamilySnapshot:
    """One family's scrape-time state: metadata + flat samples.

    ``samples`` rows are ``(name_suffix, labels, value)`` where
    ``name_suffix`` is appended to the family name (histograms emit
    ``_bucket`` / ``_sum`` / ``_count``) and ``labels`` is an ordered
    (key, value) tuple sequence.
    """

    __slots__ = ("name", "mtype", "help", "samples")

    def __init__(self, name: str, mtype: str, help: str,
                 samples: List[Tuple[str, Tuple[Tuple[str, str], ...], float]]):
        self.name = name
        self.mtype = mtype
        self.help = help
        self.samples = samples


class _Child:
    __slots__ = ("_lock",)

    def __init__(self, locked: bool):
        self._lock = threading.Lock() if locked else _NULL_LOCK


class CounterChild(_Child):
    __slots__ = ("_value",)

    def __init__(self, locked: bool):
        super().__init__(locked)
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        return self._value


class GaugeChild(_Child):
    __slots__ = ("_value",)

    def __init__(self, locked: bool):
        super().__init__(locked)
        self._value = 0.0

    def set(self, value: float) -> None:
        self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value -= amount

    @property
    def value(self) -> float:
        return self._value


class HistogramChild(_Child):
    __slots__ = ("_bounds", "_counts", "_sum", "_count")

    def __init__(self, locked: bool, bounds: Tuple[float, ...]):
        super().__init__(locked)
        self._bounds = bounds
        # per-bucket (NON-cumulative) counts; +Inf bucket is the last slot
        self._counts = [0] * (len(bounds) + 1)
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float) -> None:
        i = bisect_left(self._bounds, value)
        with self._lock:
            self._counts[i] += 1
            self._sum += value
            self._count += 1

    def snapshot(self) -> Tuple[List[int], float, int]:
        """(cumulative bucket counts incl. +Inf, sum, count) — reads the
        fields without the child lock; a scrape racing a writer sees a
        momentarily inconsistent (sum, count) pair, the same tolerance
        the lock-free /stats reads already accept."""
        counts = list(self._counts)
        cumulative = []
        acc = 0
        for c in counts:
            acc += c
            cumulative.append(acc)
        return cumulative, self._sum, self._count


class _Family:
    child_class: type = None  # type: ignore[assignment]
    mtype = ""

    def __init__(self, name: str, help: str, labelnames: Sequence[str] = (),
                 *, locked: bool = True, **child_kwargs):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        for ln in labelnames:
            if not _LABEL_RE.match(ln) or ln.startswith("__"):
                raise ValueError(f"invalid label name {ln!r}")
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._locked = locked
        self._child_kwargs = child_kwargs
        # lock-free double-checked reads in labels(); inserts only under
        # the family lock
        self._children: Dict[Tuple[str, ...], _Child] = {}  # guarded by: self._family_lock [writes]
        self._family_lock = threading.Lock()
        if not self.labelnames:
            # label-less families expose one implicit child so the family
            # renders (at zero) before the first event — scrape targets
            # expect series to exist from process start
            self._children[()] = self._make_child()

    def _make_child(self):
        return self.child_class(self._locked, **self._child_kwargs)

    def labels(self, **labelvalues: str):
        if set(labelvalues) != set(self.labelnames):
            raise ValueError(
                f"{self.name} expects labels {self.labelnames}, "
                f"got {tuple(labelvalues)}"
            )
        key = tuple(str(labelvalues[ln]) for ln in self.labelnames)
        child = self._children.get(key)
        if child is None:
            with self._family_lock:
                child = self._children.get(key)
                if child is None:
                    child = self._make_child()
                    self._children[key] = child
        return child

    # label-less convenience: family proxies its single child.  Public:
    # hot paths pre-resolve the child once at import (`FAMILY.single()`)
    # so the per-event write is a bare child op — the DK501/DK502 pattern.
    def single(self):
        if self.labelnames:
            raise ValueError(f"{self.name} requires labels()")
        return self._children[()]

    def inc(self, amount: float = 1.0) -> None:
        self.single().inc(amount)

    def set(self, value: float) -> None:
        self.single().set(value)

    def dec(self, amount: float = 1.0) -> None:
        self.single().dec(amount)

    def observe(self, value: float) -> None:
        self.single().observe(value)

    def _label_pairs(self, key: Tuple[str, ...]) -> Tuple[Tuple[str, str], ...]:
        return tuple(zip(self.labelnames, key))

    def collect(self) -> FamilySnapshot:
        samples = []
        for key, child in list(self._children.items()):
            samples.extend(self._child_samples(self._label_pairs(key), child))
        return FamilySnapshot(self.name, self.mtype, self.help, samples)

    def _child_samples(self, labels, child):
        raise NotImplementedError


class Counter(_Family):
    child_class = CounterChild
    mtype = "counter"

    def _child_samples(self, labels, child):
        return [("", labels, child.value)]


class Gauge(_Family):
    child_class = GaugeChild
    mtype = "gauge"

    def _child_samples(self, labels, child):
        return [("", labels, child.value)]


class Histogram(_Family):
    child_class = HistogramChild
    mtype = "histogram"

    def __init__(self, name: str, help: str, labelnames: Sequence[str] = (),
                 *, buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS,
                 locked: bool = True):
        bounds = tuple(float(b) for b in buckets)
        if list(bounds) != sorted(bounds) or len(set(bounds)) != len(bounds):
            raise ValueError("histogram buckets must be sorted and unique")
        if bounds and bounds[-1] == math.inf:
            bounds = bounds[:-1]  # +Inf is implicit
        self.bounds = bounds
        super().__init__(name, help, labelnames, locked=locked, bounds=bounds)

    def _child_samples(self, labels, child):
        cumulative, total, count = child.snapshot()
        out = []
        for bound, c in zip(self.bounds + (math.inf,), cumulative):
            out.append(("_bucket", labels + (("le", _fmt(bound)),), c))
        out.append(("_sum", labels, total))
        out.append(("_count", labels, count))
        return out


def histogram_snapshot(bounds: Sequence[float],
                       counts: Sequence[int], total: float, count: int,
                       labels: Tuple[Tuple[str, str], ...]):
    """Histogram-typed samples from externally maintained state (the
    engine's single-writer ``PhaseRecorder``): same wire shape as
    ``Histogram._child_samples``.  ``counts`` are non-cumulative with the
    +Inf slot last."""
    out = []
    acc = 0
    for bound, c in zip(tuple(bounds) + (math.inf,), counts):
        acc += c
        out.append(("_bucket", labels + (("le", _fmt(bound)),), acc))
    out.append(("_sum", labels, total))
    out.append(("_count", labels, count))
    return out


class PhaseRecorder:
    """Single-writer per-phase duration accumulator for one processor.

    The engine writes this with PLAIN attribute math — no locks, no
    device syncs — under the workload lock's existing single-writer
    guarantee; /metrics and /stats read it lock-free (torn reads
    tolerated, matching the ProfileStats/live_records stance).  Scrape
    code turns it into histogram samples via ``collect_samples``.
    """

    __slots__ = ("bounds", "_phases")

    def __init__(self, bounds: Sequence[float] = DEFAULT_LATENCY_BUCKETS):
        self.bounds = tuple(float(b) for b in bounds)
        self._phases: Dict[str, list] = {}

    def observe(self, phase: str, seconds: float) -> None:
        state = self._phases.get(phase)
        if state is None:
            # first observation for a phase; the single writer is the
            # only thread that ever inserts
            state = [[0] * (len(self.bounds) + 1), 0.0, 0]
            self._phases[phase] = state
        state[0][bisect_left(self.bounds, seconds)] += 1
        state[1] += seconds
        state[2] += 1

    def phase_seconds(self) -> Dict[str, float]:
        """Total seconds per phase (for /stats and the bench breakdown)."""
        return {phase: state[1] for phase, state in self._phases.items()}

    def collect_samples(self, base_labels: Tuple[Tuple[str, str], ...]):
        out = []
        for phase, state in list(self._phases.items()):
            out.extend(histogram_snapshot(
                self.bounds, list(state[0]), state[1], state[2],
                base_labels + (("phase", phase),),
            ))
        return out


class MetricRegistry:
    """A set of metric families plus scrape-time collectors.

    ``counter`` / ``gauge`` / ``histogram`` are idempotent per name (the
    existing family returns, so module-level singletons and per-app
    registries can both declare-at-use); a name re-declared as a
    different type raises.  ``register_collector`` adds a zero-arg
    callable returning ``FamilySnapshot``s evaluated at scrape time —
    used for state that already has a lock-free home (corpus sizes,
    ProfileStats, PhaseRecorders) rather than double-accounting it into
    registry children.
    """

    def __init__(self):
        self._families: Dict[str, _Family] = {}  # guarded by: self._lock [writes]
        self._collectors: List[Callable[[], Iterable[FamilySnapshot]]] = []  # guarded by: self._lock [writes]
        self._lock = threading.Lock()

    def _family(self, cls, name: str, help: str, labelnames=(), **kwargs):
        with self._lock:
            existing = self._families.get(name)
            if existing is not None:
                if not isinstance(existing, cls):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{existing.mtype}"
                    )
                return existing
            fam = cls(name, help, labelnames, **kwargs)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help: str, labelnames=(), *,
                locked: bool = True) -> Counter:
        return self._family(Counter, name, help, labelnames, locked=locked)

    def gauge(self, name: str, help: str, labelnames=(), *,
              locked: bool = True) -> Gauge:
        return self._family(Gauge, name, help, labelnames, locked=locked)

    def histogram(self, name: str, help: str, labelnames=(), *,
                  buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS,
                  locked: bool = True) -> Histogram:
        return self._family(Histogram, name, help, labelnames,
                            buckets=buckets, locked=locked)

    def register_collector(
            self, fn: Callable[[], Iterable[FamilySnapshot]]) -> None:
        with self._lock:
            self._collectors.append(fn)

    def unregister_collector(
            self, fn: Callable[[], Iterable[FamilySnapshot]]) -> None:
        with self._lock:
            if fn in self._collectors:
                self._collectors.remove(fn)

    def collect(self) -> List[FamilySnapshot]:
        with self._lock:
            families = list(self._families.values())
            collectors = list(self._collectors)
        out = [fam.collect() for fam in families]
        for fn in collectors:
            out.extend(fn())
        return out


# scrape-pass hooks (ISSUE 19 satellite): (begin, end) pairs invoked
# around one render() so expensive state (the HBM ledger's component
# callables) is snapshotted ONCE per scrape no matter how many
# collectors read it — at 200 tenants the per-collector recompute made
# /metrics a hot path.  Registration is import-time only (the ledger
# module's tail), so the list is read-mostly and needs no lock.
_RENDER_HOOKS: List[tuple] = []


def add_render_hook(begin: Callable[[], None],
                    end: Callable[[], None]) -> None:
    _RENDER_HOOKS.append((begin, end))


def render(*registries: MetricRegistry) -> str:
    """Prometheus text exposition (0.0.4) over one or more registries.

    Snapshots sharing a family name merge under one HELP/TYPE header
    (first declaration wins) — required for validity: a name may appear
    in only one block.
    """
    for begin, _end in _RENDER_HOOKS:
        begin()
    try:
        merged: Dict[str, FamilySnapshot] = {}
        for registry in registries:
            for snap in registry.collect():
                existing = merged.get(snap.name)
                if existing is None:
                    merged[snap.name] = FamilySnapshot(
                        snap.name, snap.mtype, snap.help, list(snap.samples)
                    )
                else:
                    existing.samples.extend(snap.samples)
        lines: List[str] = []
        for snap in merged.values():
            help_text = snap.help.replace("\\", "\\\\").replace("\n", "\\n")
            lines.append(f"# HELP {snap.name} {help_text}")
            lines.append(f"# TYPE {snap.name} {snap.mtype}")
            for suffix, labels, value in snap.samples:
                lines.append(
                    f"{snap.name}{suffix}{_fmt_labels(labels)} {_fmt(value)}"
                )
        return "\n".join(lines) + "\n"
    finally:
        for _begin, end in _RENDER_HOOKS:
            end()


CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"
