"""Sub-range heat maps (ISSUE 17 tentpole c).

The federation router's range stats stop at whole ranges: a range can
look evenly loaded while 80% of its traffic lands in 5% of its keyspace,
which makes the midpoint split the rebalancer would pick today exactly
wrong.  Each ``FederationRouter`` owns a ``HeatMap`` that buckets every
routed record's ``route_key`` into a fixed 256-bucket histogram over the
owning range's ``[lo, hi)`` span, fed on the ingest path with a plain
``counts[i] += 1`` — no lock, same stance as the engine's unlocked
QUERY_BLOCKS counters: increments from concurrent submit threads may
rarely tear, and a heat map that is 99.9% accurate still points at the
same hot band.  Counts reset when a range's bounds change (splits /
migrations re-key the span, so old buckets would lie).

Bucket math: ``bucket = min(255, (key - lo) * 256 // (hi - lo))``.

Scrape rolls the buckets up as
``duke_fed_subrange_records_total{range,bucket}`` (non-zero buckets
only — 256 series x N ranges of zeros would drown the exposition), and
``GET /debug/loadmap`` serves per-range bucket arrays plus a suggested
split point: the bucket boundary whose prefix sum best bisects the
observed load (ties to the lower key).  Routing notes fire on every
routing pass, so a record re-routed after a live migration is counted
once per attempt — a <1-in-10^4 event bounded by migration frequency,
and irrelevant to where the hot band is.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from .registry import FamilySnapshot

N_BUCKETS = 256


class HeatMap:
    """Per-router sub-range load histogram, keyed by range_id."""

    __slots__ = ("_ranges",)

    def __init__(self) -> None:
        # range_id -> [lo, hi, counts]; written by submit threads and
        # replaced wholesale on bound changes (dict assignment is
        # atomic); counts increments are intentionally unlocked.
        self._ranges: Dict[str, list] = {}

    def note(self, rng, key: int) -> None:
        """Count one routed record for ``rng`` (a federation ``Range``)."""
        entry = self._ranges.get(rng.range_id)
        if entry is None or entry[0] != rng.lo or entry[1] != rng.hi:
            entry = [rng.lo, rng.hi, [0] * N_BUCKETS]
            self._ranges[rng.range_id] = entry
        span = entry[1] - entry[0]
        if span <= 0:
            return
        bucket = (key - entry[0]) * N_BUCKETS // span
        if 0 <= bucket < N_BUCKETS:
            entry[2][bucket] += 1

    def snapshot(self) -> List[Tuple[str, int, int, List[int]]]:
        """[(range_id, lo, hi, counts-copy)] sorted by range_id."""
        out = []
        for range_id, entry in sorted(self._ranges.items()):
            out.append((range_id, entry[0], entry[1], list(entry[2])))
        return out

    def _reset_for_tests(self) -> None:
        self._ranges.clear()


def suggest_split(lo: int, hi: int, counts: List[int]) -> Optional[str]:
    """The bucket boundary best bisecting observed load, as a 16-hex-digit
    route key (None when the range saw no traffic or has a unit span)."""
    total = sum(counts)
    if total <= 0 or hi - lo < 2:
        return None
    best_k, best_err, prefix = 1, float("inf"), 0
    for k in range(1, N_BUCKETS):
        prefix += counts[k - 1]
        err = abs(prefix - total / 2)
        if err < best_err:
            best_k, best_err = k, err
    split = lo + (hi - lo) * best_k // N_BUCKETS
    if split <= lo or split >= hi:
        return None
    return f"{split:016x}"


def loadmap(heatmap: Optional[HeatMap]) -> Dict[str, object]:
    """``GET /debug/loadmap`` payload for one router's heat map."""
    ranges = []
    for range_id, lo, hi, counts in (heatmap.snapshot() if heatmap else []):
        total = sum(counts)
        hot_share = max(counts) / total if total else 0.0
        ranges.append({
            "range": range_id,
            "lo": f"{lo:016x}",
            "hi": f"{hi:016x}",
            "records_total": total,
            "buckets": counts,
            "hot_bucket_share": round(hot_share, 4),
            "suggested_split": suggest_split(lo, hi, counts),
        })
    return {"n_buckets": N_BUCKETS, "ranges": ranges}


def collect_family(heatmap: Optional[HeatMap]) -> FamilySnapshot:
    """``duke_fed_subrange_records_total`` rollup for the federation
    scrape (non-zero buckets only)."""
    samples = []
    for range_id, _lo, _hi, counts in (heatmap.snapshot() if heatmap else []):
        for bucket, n in enumerate(counts):
            if n:
                samples.append(
                    ("", (("range", range_id), ("bucket", str(bucket))),
                     float(n)))
    return FamilySnapshot(
        "duke_fed_subrange_records_total", "counter",
        "Records routed per 256th of each owned range's keyspan "
        "(non-zero buckets only); the rebalancer's split-point signal",
        samples)
