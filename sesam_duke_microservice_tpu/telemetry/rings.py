"""Bounded keyed rings with tail-latch-aware eviction (ISSUE 5 satellite).

The flight recorder (tracing.FlightRecorder, ISSUE 2) and the decision
recorder (telemetry.decisions.DecisionRecorder, ISSUE 5) share one
retention problem: a bounded ring of records where *remarkable* entries —
slow/errored traces, device-vs-host disagreements, near-threshold band
skips — must survive pressure from a flood of unremarkable sampled ones.
Both used to need their own ring + eviction loop; this module is the ONE
copy of that core so the two recorders can never drift onto different
latch semantics.

``LatchedRing`` is a keyed insertion-order ring bounded by record count
and (optionally) bytes.  Eviction prefers the OLDEST UNREMARKABLE entry;
only when every other entry is remarkable does plain FIFO apply — so an
upstream that floods the ring with sampled records cannot flush the
latched ones the ring exists to keep, yet a ring saturated with latched
records stays LIVE (oldest latched falls off rather than every new
record dying on arrival).  The byte budget is a hard bound (memory
safety beats retention), except that the newest record is never evicted
— a single over-budget record survives alone.

All methods take the ring's re-entrant lock; callers composing compound
read-modify-write operations (the flight recorder's same-trace-id merge)
hold ``ring.lock`` around the sequence.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, List, Optional

__all__ = ["LatchedRing"]


class _Entry:
    __slots__ = ("record", "remarkable", "nbytes")

    def __init__(self, record: Any, remarkable: bool, nbytes: int):
        self.record = record
        self.remarkable = remarkable
        self.nbytes = nbytes


class LatchedRing:
    """Keyed bounded ring; eviction prefers unremarkable entries."""

    def __init__(self, capacity: int, byte_budget: int = 0):
        self.lock = threading.RLock()
        self._capacity = max(1, int(capacity))
        self._byte_budget = max(0, int(byte_budget))
        self._order: deque = deque()          # keys, oldest first
        self._entries: dict = {}              # key -> _Entry
        self._bytes = 0
        self.evicted = 0                      # lifetime evictions (stats)

    def __len__(self) -> int:
        with self.lock:
            return len(self._order)

    @property
    def bytes(self) -> int:
        return self._bytes

    @property
    def capacity(self) -> int:
        return self._capacity

    def put(self, key: str, record: Any, *, remarkable: bool = False,
            nbytes: int = 0) -> None:
        """Insert or replace.  Replacing keeps the key's ring position
        (the flight recorder merges follower spans into an existing trace
        without promoting it to newest)."""
        with self.lock:
            entry = self._entries.get(key)
            if entry is not None:
                self._bytes += nbytes - entry.nbytes
                entry.record = record
                entry.remarkable = remarkable
                entry.nbytes = nbytes
            else:
                self._entries[key] = _Entry(record, remarkable, nbytes)
                self._order.append(key)
                self._bytes += nbytes
            self._evict(key)

    def _evict(self, newest: str) -> None:
        # called with the lock held; ``newest`` is the key just written
        # and is never the victim — a budget saturated by latched
        # records must rotate (oldest latched out) rather than drop
        # every fresh record on arrival
        while len(self._order) > self._capacity or (
            self._byte_budget
            and self._bytes > self._byte_budget
            and len(self._order) > 1
        ):
            victim = None
            for key in self._order:
                if key != newest and not self._entries[key].remarkable:
                    victim = key
                    break
            if victim is None:
                for key in self._order:  # all remarkable: plain FIFO
                    if key != newest:
                        victim = key
                        break
            if victim is None:
                return  # only the newest record remains
            self._order.remove(victim)
            entry = self._entries.pop(victim)
            self._bytes -= entry.nbytes
            self.evicted += 1

    def get(self, key: str) -> Optional[Any]:
        with self.lock:
            entry = self._entries.get(key)
            return entry.record if entry is not None else None

    def records(self) -> List[Any]:
        """Most-recent-first snapshot of the retained records."""
        with self.lock:
            return [self._entries[k].record for k in reversed(self._order)]

    def clear(self) -> None:
        with self.lock:
            self._order.clear()
            self._entries.clear()
            self._bytes = 0
