"""Scatter-gather partition router over N serving groups (ISSUE 14).

Ingest batches partition by the owner group of each entity's routing key
and fan out concurrently with per-group timeouts and bounded full-jitter
retries (``utils.backoff`` — the ONE policy copy).  Link feeds merge
across groups under the composite per-range cursor from
``federation.ranges`` (the opaque federated ``?since=`` token).

Degradation contract (the robustness point of the tier): a dead group
takes down only ITS ranges.  Ingest touching a dead range surfaces 503
with a Retry-After (the max across contacted groups' hints) and the
degraded-range list in the error body; everything owned by live ranges
keeps succeeding, and the merged feed keeps serving every live group's
links while the dead ranges' cursors simply stop advancing (the client
resumes them loss-free once the group returns).

``LocalGroup`` is the in-process stand-in for a group's leader endpoint
— the seam where a real deployment slots an RPC client.  It enforces
the epoch fence: a router presenting an epoch below the group's fence
(its map predates a freeze/cutover) is refused with
``StaleRouterEpoch`` and must refresh its map, so a stale router can
never write into a range's old owner.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from ..engine.workload import Workload
from ..links.replica import feed_row
from ..telemetry import heat, slo, tracing
from ..telemetry.decisions import _MonitorHist
from ..telemetry.env import env_flag, env_float, env_int
from ..telemetry.registry import DEFAULT_LATENCY_BUCKETS
from ..utils import faults
from ..utils.backoff import full_jitter_delay
from .ranges import (
    PartitionMap,
    Range,
    StaleRouterEpoch,
    decode_cursor,
    encode_cursor,
    route_key,
)

logger = logging.getLogger("federation-router")

# scatter knobs: per-group call budget and transient-failure retries —
# resolved per call (the failure path is rare; the env read is not hot)
DEFAULT_FED_TIMEOUT_S = 30.0
DEFAULT_FED_RETRIES = 2
_RETRY_BASE_S = 0.05
_RETRY_CAP_S = 1.0
# Retry-After floor when a dead group offers no hint of its own
DEFAULT_FED_RETRY_AFTER_S = 2


def _fed_timeout() -> float:
    return max(0.1, env_float("DUKE_FED_TIMEOUT", DEFAULT_FED_TIMEOUT_S))


def _fed_retries() -> int:
    return max(0, env_int("DUKE_FED_RETRIES", DEFAULT_FED_RETRIES))


class GroupUnavailable(RuntimeError):
    """The group could not be reached (dead process, injected
    ``fed_down``, closed workload, scatter timeout)."""

    def __init__(self, message: str,
                 retry_after: int = DEFAULT_FED_RETRY_AFTER_S):
        super().__init__(message)
        self.retry_after = retry_after


class GroupBusy(GroupUnavailable):
    """The group is alive but its workload lock did not yield within the
    read timeout — carries the group's own write-hold Retry-After
    hint."""


class UnknownFederatedWorkload(KeyError):
    pass


class FrozenRange(RuntimeError):
    """The batch touches a range frozen by a live migration: the whole
    batch answers 429 + Retry-After (partial admission would make the
    client's at-least-once resend semantics range-dependent)."""

    def __init__(self, range_ids: List[str], retry_after: int):
        super().__init__(
            f"range(s) {', '.join(range_ids)} frozen by a live "
            "migration; retry the batch shortly")
        self.range_ids = range_ids
        self.retry_after = retry_after


class PartialIngestFailure(RuntimeError):
    """Scatter-gather partial failure: the live groups' sub-batches
    applied; the dead groups' did not.  Carries the degraded-range list
    and the max Retry-After across contacted groups (ISSUE 14 satellite:
    backpressure propagates through the router)."""

    def __init__(self, degraded_ranges: List[str], retry_after: int,
                 errors: Dict[int, str]):
        super().__init__(
            f"{len(errors)} group(s) unavailable; degraded ranges: "
            f"{', '.join(degraded_ranges) or '(none touched)'}")
        self.degraded_ranges = degraded_ranges
        self.retry_after = retry_after
        self.errors = errors


class LocalGroup:
    """In-process handle on one serving group's leader.

    Holds the group's workloads (each a full ``build_workload`` stack
    over the group's own data folder) and the group-side half of the
    epoch fence.  All methods are transport-shaped: plain values in,
    plain values out, failures as exceptions — an RPC client drops into
    the same seam."""

    READ_LOCK_TIMEOUT_S = 1.0

    def __init__(self, idx: int, workloads: Dict[Tuple[str, str], Workload],
                 epoch: int = 1):
        self.idx = idx
        self.workloads = workloads
        # the write fence: the highest map epoch at which this group's
        # ownership changed.  Plain int, GIL-atomic single writer (the
        # migrator); read on every ingest.
        self.fence_epoch = epoch
        self.closed = False

    # -- plumbing -------------------------------------------------------------

    def _check_reachable(self) -> None:
        if self.closed:
            raise GroupUnavailable(f"group {self.idx} is closed")
        plan = faults.active()
        if plan is not None and plan.fed_group_down(self.idx):
            raise GroupUnavailable(
                f"group {self.idx} unreachable (injected fed_down)")

    def workload(self, kind: str, name: str) -> Workload:
        wl = self.workloads.get((kind, name))
        if wl is None:
            raise UnknownFederatedWorkload(f"{kind}/{name}")
        return wl

    def fence(self, epoch: int) -> None:
        """Raise the write fence (migrator, at freeze and cutover)."""
        if epoch > self.fence_epoch:
            self.fence_epoch = epoch

    def _check_epoch(self, epoch: int) -> None:
        if epoch < self.fence_epoch:
            raise StaleRouterEpoch(self.fence_epoch, epoch)

    # -- ingest ---------------------------------------------------------------

    def ingest(self, kind: str, name: str, dataset_id: str,
               entities: List[dict], *, epoch: int,
               trace_ctx: Optional[dict] = None) -> bytes:
        """Apply one routed sub-batch; returns the group-side remote
        span tree as wire bytes (``b""`` untraced — the exact shape an
        RPC response would carry back, per the dispatch.py precedent)."""
        # the capture opens a DETACHED trace continuing the router's ids
        # in this scatter thread (threads inherit no contextvars), so the
        # engine spans the scheduler attaches land in the same tree;
        # with trace_ctx None every span inside stays a no-op.
        with tracing.capture_remote(
                "group.ingest", trace_ctx,
                {"group": self.idx, "entities": len(entities)}) as cap:
            t0 = time.monotonic()
            self._check_reachable()
            self._check_epoch(epoch)
            wl = self.workload(kind, name)
            if dataset_id not in wl.datasources:
                raise UnknownFederatedWorkload(f"{kind}/{name}/{dataset_id}")
            if wl.submit_batch(dataset_id, entities) is None:
                raise GroupUnavailable(
                    f"group {self.idx} workload {kind}/{name} was replaced "
                    "mid-batch")
            # fence RE-CHECK after the write: the pre-write check is
            # check-then-act — a freeze can land between it and the batch
            # taking the workload lock, and a write completing after the
            # migration's locked snapshot walk would be acked yet invisible
            # (its range's rows filtered at the old owner forever).  Raising
            # HERE withholds the ack instead: the client resends, the
            # refreshed router routes to the live owner, and the idempotent
            # assert absorbs any rows the snapshot DID capture.  Sound
            # because the freeze fences BEFORE its snapshot takes the
            # workload lock: if this read still sees the old fence, the
            # write completed before any snapshot could have started.
            self._check_epoch(epoch)
            # always-on ingest SLO (ISSUE 16): group ingest bypasses the
            # service scheduler, so the group boundary is its
            # scheduler-arrival equivalent — lock-wait (the queueing
            # here) included.  Leaf tracker lock, no other lock held.
            done = time.monotonic()
            slo.tracker("ingest", kind, name).record(
                done - t0, done, tracing.sampled_trace_id())
            slo.feed_meter(kind, name).note_write()
        return cap.wire()

    # -- feed walk ------------------------------------------------------------

    def links_walk(self, kind: str, name: str, since: int, limit: int,
                   trace_ctx: Optional[dict] = None
                   ) -> Tuple[List[tuple], bool, bytes]:
        """One bounded page of this group's link stream past ``since``:
        ``([(id1, timestamp, feed_row), ...], drained, span_wire)``.
        Rows carry their owner endpoint id so the ROUTER applies the
        ownership filter (the group does not hold the map).  Takes the
        workload lock with the read timeout — contention surfaces as
        GroupBusy with the workload's own Retry-After hint, never a
        hang."""
        with tracing.capture_remote(
                "group.links_walk", trace_ctx,
                {"group": self.idx, "since": since}) as cap:
            self._check_reachable()
            wl = self.workload(kind, name)
            if not wl.lock.acquire(timeout=self.READ_LOCK_TIMEOUT_S):
                raise GroupBusy(
                    f"group {self.idx} workload lock busy",
                    retry_after=wl.busy_retry_after())
            try:
                if wl.closed:
                    raise GroupUnavailable(
                        f"group {self.idx} workload {kind}/{name} closed")
                links = wl.link_database.get_changes_page(since, limit)
                prefetch = getattr(getattr(wl.index, "records", None),
                                   "prefetch", None)
                if prefetch is not None and links:
                    prefetch({l.id1 for l in links} | {l.id2 for l in links})
                rows = [(l.id1, l.timestamp,
                         feed_row(l, wl.index.find_record_by_id))
                        for l in links]
            finally:
                wl.lock.release()
        return rows, len(links) < limit, cap.wire()

    def close(self) -> None:
        self.closed = True
        for wl in self.workloads.values():
            with wl.lock:
                wl.close()


class FederationRouter:
    """The scatter-gather tier: routes by the live partition map, keeps
    per-group health, and propagates backpressure.

    Lock discipline: ``_health_lock`` guards only the plain counters —
    it is NEVER held across a group call, so a wedged group can stall
    only its own scatter thread, not the router."""

    def __init__(self, map_provider: Callable[[], PartitionMap],
                 groups: List[LocalGroup]):
        self._map_provider = map_provider
        self.groups = groups
        self._health_lock = threading.Lock()
        # consecutive scatter failures + last error, per group index
        self._failures: Dict[int, int] = {}  # guarded by: self._health_lock [writes]
        self._last_error: Dict[int, str] = {}  # guarded by: self._health_lock [writes]
        self._last_ok: Dict[int, float] = {}  # guarded by: self._health_lock [writes]
        # request outcomes for the duke_fed_requests_total snapshot
        self.outcomes = {"ok": 0, "degraded": 0, "frozen": 0}  # guarded by: self._health_lock [writes]
        # per-range scatter accounting (ISSUE 16: the hot-range signal)
        # — written AFTER the scatter returns, so like _health_lock this
        # leaf lock is never held across a group call
        self._range_lock = threading.Lock()
        # range_id -> [ {outcome: count}, _MonitorHist ]
        self._range_stats: Dict[str, list] = {}  # guarded by: self._range_lock [writes]
        # sub-range heat map (ISSUE 17): fed per routed record in
        # _route_entities with unlocked increments (torn counts
        # tolerated — the QUERY_BLOCKS stance); DUKE_FED_HEAT=0 turns
        # the bookkeeping off entirely (the bench's attribution-off arm)
        self.heat: Optional[heat.HeatMap] = (
            heat.HeatMap() if env_flag("DUKE_FED_HEAT", True) else None)

    # -- health bookkeeping ---------------------------------------------------

    def _mark(self, group: int, error: Optional[BaseException]) -> None:
        with self._health_lock:
            if error is None:
                self._failures.pop(group, None)
                self._last_error.pop(group, None)
                self._last_ok[group] = time.monotonic()
            else:
                self._failures[group] = self._failures.get(group, 0) + 1
                self._last_error[group] = repr(error)

    def last_contact(self, group: int) -> Optional[float]:
        """Monotonic timestamp of the last successful contact with the
        group, or None (never reached) — the scatter plane's lag signal
        (duke_fed_group_seconds_since_contact)."""
        with self._health_lock:
            return self._last_ok.get(group)

    def outcomes_snapshot(self) -> Dict[str, int]:
        with self._health_lock:
            return dict(self.outcomes)

    def _count_outcome(self, outcome: str) -> None:
        with self._health_lock:
            self.outcomes[outcome] = self.outcomes.get(outcome, 0) + 1

    def group_health(self) -> List[dict]:
        pmap = self._map_provider()
        with self._health_lock:
            failures = dict(self._failures)
            errors = dict(self._last_error)
        return [
            {
                "group": g.idx,
                "up": failures.get(g.idx, 0) == 0 and not g.closed,
                "consecutive_failures": failures.get(g.idx, 0),
                "last_error": errors.get(g.idx),
                "fence_epoch": g.fence_epoch,
                "ranges": [r.range_id for r in pmap.group_ranges(g.idx)],
            }
            for g in self.groups
        ]

    def _note_range(self, range_ids: List[str], outcome: str,
                    elapsed_s: float) -> None:
        with self._range_lock:
            for rid in range_ids:
                st = self._range_stats.get(rid)
                if st is None:
                    st = self._range_stats[rid] = [
                        {}, _MonitorHist(DEFAULT_LATENCY_BUCKETS)]
                st[0][outcome] = st[0].get(outcome, 0) + 1
                st[1].observe(elapsed_s)

    def range_stats_snapshot(self) -> Dict[str, tuple]:
        """Per-range scatter stats for the fed collector:
        ``{range_id: ({outcome: count}, (bucket_counts, sum, count))}``
        — plain copies, detached from the lock."""
        with self._range_lock:
            return {
                rid: (dict(st[0]),
                      (list(st[1].counts), st[1].total, st[1].count))
                for rid, st in self._range_stats.items()
            }

    def degraded_range_ids(self) -> List[str]:
        """Ranges owned by groups whose LAST scatter contact failed —
        the live degraded set for /readyz and the gauge."""
        pmap = self._map_provider()
        with self._health_lock:
            down = {g for g, n in self._failures.items() if n > 0}
        out: List[str] = []
        for g in self.groups:
            if g.idx in down or g.closed:
                out.extend(r.range_id for r in pmap.group_ranges(g.idx))
        return sorted(out)

    # -- scatter machinery ----------------------------------------------------

    def _call_group(self, group: LocalGroup, fn: Callable, *args, **kwargs):
        """One group call with bounded transient retries (full jitter).
        GroupBusy/GroupUnavailable retry; anything else propagates."""
        retries = _fed_retries()
        attempt = 0
        while True:
            try:
                return fn(*args, **kwargs)
            except GroupUnavailable as e:
                if attempt >= retries:
                    raise
                attempt += 1
                delay = full_jitter_delay(attempt, _RETRY_BASE_S,
                                          _RETRY_CAP_S)
                logger.warning(
                    "group %d call failed (attempt %d/%d; retrying in "
                    "%.3f s): %r", group.idx, attempt, retries, delay, e)
                time.sleep(delay)

    def _scatter(self, jobs: Dict[int, Callable]) -> Dict[int, tuple]:
        """Run one callable per group concurrently; returns
        ``{group: (ok, value_or_error)}``.  A job that misses the
        per-group deadline is marked GroupUnavailable (its thread may
        still finish in the background — the at-least-once/idempotent
        write contract makes that safe, same as any client resend)."""
        results: Dict[int, tuple] = {}
        results_lock = threading.Lock()

        def run(gidx: int, job: Callable) -> None:
            try:
                value = job()
                with results_lock:
                    results[gidx] = (True, value)
            except BaseException as e:  # collected, not propagated
                with results_lock:
                    results[gidx] = (False, e)

        threads = [
            threading.Thread(target=run, args=(gidx, job), daemon=True,
                             name=f"fed-scatter-g{gidx}")
            for gidx, job in jobs.items()
        ]
        for t in threads:
            t.start()
        deadline = time.monotonic() + _fed_timeout()
        for t in threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
        with results_lock:
            for gidx in jobs:
                if gidx not in results:
                    results[gidx] = (False, GroupUnavailable(
                        f"group {gidx} timed out after "
                        f"{_fed_timeout():.1f} s"))
            return dict(results)

    # -- ingest ---------------------------------------------------------------

    def _route_entities(self, kind: str, name: str, dataset_id: str,
                        entities: List[dict], pmap: PartitionMap):
        """Partition a batch by owner group; surfaces frozen ranges."""
        ds_owner = self.groups[0].workload(kind, name)
        datasource = ds_owner.datasources.get(dataset_id)
        if datasource is None:
            raise UnknownFederatedWorkload(f"{kind}/{name}/{dataset_id}")
        ranges = pmap.ranges()
        per_group: Dict[int, List[dict]] = {}
        frozen: List[str] = []
        touched: Dict[int, List[str]] = {}
        for entity in entities:
            rid = datasource.record_id_for_entity(entity)
            key = route_key(rid)
            owner = next(r for r in ranges if r.contains(key))
            if owner.frozen:
                if owner.range_id not in frozen:
                    frozen.append(owner.range_id)
                continue
            if self.heat is not None:
                # counts every routing pass: a record re-routed after a
                # live migration is noted once per attempt — rare, and
                # irrelevant to where the hot band sits
                self.heat.note(owner, key)
            per_group.setdefault(owner.group, []).append(entity)
            group_touched = touched.setdefault(owner.group, [])
            if owner.range_id not in group_touched:
                group_touched.append(owner.range_id)
        return per_group, frozen, touched

    @staticmethod
    def _group_outcome(ok: bool, err, attempts: int) -> str:
        """The fed.group span / per-range outcome vocabulary."""
        if ok:
            return "retried" if attempts > 1 else "ok"
        if isinstance(err, StaleRouterEpoch):
            return "stale-epoch"
        return "degraded"

    def _ingest_job(self, gidx: int, kind: str, name: str, dataset_id: str,
                    sub: List[dict], epoch: int, ctx: Optional[dict],
                    cell: list) -> Callable:
        """One scatter job that times itself into ``cell`` =
        ``[start_ns, end_ns, attempts, wire]`` — plain list writes from
        the scatter thread, read by the router thread only after the
        scatter joins (or defaulted on timeout)."""
        group = self.groups[gidx]

        def call():
            cell[2] += 1
            return group.ingest(kind, name, dataset_id, sub, epoch=epoch,
                                trace_ctx=ctx)

        def job():
            cell[0] = time.monotonic_ns()
            try:
                cell[3] = self._call_group(group, call) or b""
                return True
            finally:
                cell[1] = time.monotonic_ns()

        return job

    def _trace_scatter(self, results: Dict[int, tuple], meta: Dict[int, list],
                       ranges_by_group: Dict[int, List[str]]) -> None:
        """Per-group ``fed.group`` spans + remote-tree grafts, emitted in
        the ROUTER thread (the scatter threads have no trace context),
        and the per-range request/latency accounting.  No-cost without
        an active trace except the range bookkeeping."""
        now_ns = time.monotonic_ns()
        for gidx, (ok, value) in results.items():
            start_ns, end_ns, attempts, wire = meta[gidx]
            start_ns = start_ns or now_ns
            end_ns = end_ns or now_ns  # timed out: thread still running
            outcome = self._group_outcome(ok, value, attempts)
            owned = ranges_by_group.get(gidx, [])
            tracing.add_span("fed.group", start_ns, end_ns, {
                "group": gidx, "ranges": owned, "outcome": outcome,
                "attempts": attempts})
            if ok:
                tracing.graft_remote(wire)
            self._note_range(owned, outcome,
                             max(0.0, (end_ns - start_ns) / 1e9))

    def submit(self, kind: str, name: str, dataset_id: str,
               entities: List[dict]) -> dict:
        """Scatter one ingest batch to the owning groups.  Raises
        FrozenRange (whole batch, 429), PartialIngestFailure (503 with
        degraded ranges + max Retry-After), UnknownFederatedWorkload, or
        StaleRouterEpoch (after one map refresh + re-route attempt)."""
        for attempt in ("route", "re-route"):
            pmap = self._map_provider()
            epoch = pmap.epoch
            with tracing.span("fed.partition", {"attempt": attempt,
                                                "entities": len(entities)}):
                per_group, frozen, touched = self._route_entities(
                    kind, name, dataset_id, entities, pmap)
            if frozen:
                self._count_outcome("frozen")
                raise FrozenRange(
                    frozen, retry_after=DEFAULT_FED_RETRY_AFTER_S)
            # [start_ns, end_ns, attempts, wire] per scatter job
            meta = {gidx: [0, 0, 0, b""] for gidx in per_group}
            with tracing.span("fed.fanout", {"groups": len(per_group),
                                             "attempt": attempt}):
                ctx = tracing.propagation_context()
                jobs = {
                    gidx: self._ingest_job(gidx, kind, name, dataset_id,
                                           sub, epoch, ctx, meta[gidx])
                    for gidx, sub in per_group.items()
                }
                results = self._scatter(jobs)
                self._trace_scatter(results, meta, touched)
            if any(not ok and isinstance(err, StaleRouterEpoch)
                   for ok, err in results.values()) and attempt == "route":
                # our map raced a freeze/cutover: refresh and re-route
                # ONCE — the sub-batches that landed are idempotent under
                # the resend
                logger.warning("stale router epoch during scatter; "
                               "refreshing the partition map and "
                               "re-routing")
                continue
            break
        with tracing.span("fed.merge", {"groups": len(per_group)}):
            failures = {g: err for g, (ok, err) in results.items() if not ok}
            # a stale-epoch refusal is FENCING, not group ill-health: the
            # group is alive and did its job — never mark it failed (its
            # ranges must not surface as degraded) and surface the stale
            # signal itself so the plane answers the retry-shortly 503
            # instead of a bogus group-unavailable
            stale = [e for e in failures.values()
                     if isinstance(e, StaleRouterEpoch)]
            genuine = {g: e for g, e in failures.items()
                       if not isinstance(e, StaleRouterEpoch)}
            for gidx in per_group:
                self._mark(gidx, genuine.get(gidx))
            if not failures:
                self._count_outcome("ok")
                return {"success": True, "groups": len(per_group)}
            self._count_outcome("degraded")
            if not genuine:
                # every failure was fencing: topology moved twice during
                # this submit — nothing landed for those sub-batches, the
                # client retries against the settled map
                raise stale[0]
            pmap = self._map_provider()
            degraded: List[str] = []
            for gidx in genuine:
                degraded.extend(r.range_id for r in pmap.group_ranges(gidx))
            retry_after = max(
                [getattr(e, "retry_after", DEFAULT_FED_RETRY_AFTER_S)
                 for e in genuine.values()] + [DEFAULT_FED_RETRY_AFTER_S])
            raise PartialIngestFailure(
                sorted(set(degraded)), retry_after,
                {g: repr(e) for g, e in genuine.items()})

    # -- federated feed -------------------------------------------------------

    def feed_page(self, kind: str, name: str, token: str,
                  limit: int) -> dict:
        """One merged feed page: scatter a bounded walk to every group,
        filter each row by CURRENT range ownership (the one-place dedup
        rule — a stale copy at a range's old owner can never be emitted
        twice), advance per-range cursors, and merge by timestamp.

        Returns ``{rows, next_since, drained, degraded_ranges,
        retry_after}`` — a dead group contributes no rows and leaves its
        ranges' cursors untouched (the client resumes them loss-free
        later), while every live group's links keep flowing."""
        # validate the workload exists anywhere before touching cursors
        self.groups[0].workload(kind, name)
        pmap = self._map_provider()
        ranges = pmap.ranges()
        positions = decode_cursor(token)
        legacy = positions.get("*")

        def pos_for(range_id: str) -> int:
            if legacy is not None:
                return max(int(legacy), int(positions.get(range_id, 0)))
            return int(positions.get(range_id, 0))

        by_group: Dict[int, List[Range]] = {}
        for r in ranges:
            by_group.setdefault(r.group, []).append(r)

        def walk(gidx: int, owned: List[Range], ctx: Optional[dict],
                 cell: list):
            group = self.groups[gidx]
            cursor_floor = min(pos_for(r.range_id) for r in owned)
            emitted: List[tuple] = []
            pos = cursor_floor
            drained = False
            cell[0] = time.monotonic_ns()
            try:
                while len(emitted) < limit:
                    rows, drained, wire = self._call_group(
                        group, group.links_walk, kind, name, pos, limit,
                        ctx)
                    cell[2] += 1
                    if wire:
                        cell[3].append(wire)
                    for id1, ts, row in rows:
                        pos = ts
                        key = route_key(id1)
                        owner = next(r for r in ranges if r.contains(key))
                        if owner.group != gidx:
                            continue  # stale copy at the range's old owner
                        if ts <= pos_for(owner.range_id):
                            continue  # consumed before the range moved here
                        emitted.append((ts, owner.range_id, row))
                    if drained:
                        break
            finally:
                cell[1] = time.monotonic_ns()
            return emitted, pos, drained

        # [start_ns, end_ns, pages, wires] per scatter job
        meta = {gidx: [0, 0, 0, []] for gidx in by_group}
        with tracing.span("fed.fanout", {"groups": len(by_group),
                                         "op": "feed"}):
            ctx = tracing.propagation_context()
            jobs = {
                gidx: (lambda g=gidx, owned=owned:
                       walk(g, owned, ctx, meta[g]))
                for gidx, owned in by_group.items()
            }
            results = self._scatter(jobs)
            now_ns = time.monotonic_ns()
            for gidx, (ok, value) in results.items():
                start_ns, end_ns, pages, wires = meta[gidx]
                tracing.add_span("fed.group", start_ns or now_ns,
                                 end_ns or now_ns, {
                                     "group": gidx,
                                     "ranges": [r.range_id
                                                for r in by_group[gidx]],
                                     "outcome": self._group_outcome(
                                         ok, value, 1),
                                     "pages": pages, "op": "feed"})
                for wire in wires:  # pages that landed before a failure
                    tracing.graft_remote(wire)
        with tracing.span("fed.merge", {"groups": len(by_group)}):
            merged: List[tuple] = []
            new_positions: Dict[str, int] = {
                r.range_id: pos_for(r.range_id) for r in ranges}
            degraded: List[str] = []
            retry_hints: List[int] = []
            all_drained = True
            for gidx, (ok, value) in results.items():
                owned = by_group[gidx]
                if not ok:
                    self._mark(gidx, value)
                    degraded.extend(r.range_id for r in owned)
                    retry_hints.append(
                        getattr(value, "retry_after",
                                DEFAULT_FED_RETRY_AFTER_S))
                    all_drained = False
                    continue
                self._mark(gidx, None)
                emitted, walked_to, drained = value
                merged.extend(emitted)
                all_drained = all_drained and drained
                # the group's stream is one timestamp-ordered walk:
                # having processed it to ``walked_to``, EVERY range it
                # owns is consumed to there
                for r in owned:
                    new_positions[r.range_id] = max(
                        new_positions[r.range_id], walked_to)
            merged.sort(key=lambda t: (t[0], t[2].get("_id", "")))
            if len(merged) > limit:
                # bound the MERGED page too (each group walked up to
                # ``limit`` on its own, so the concatenation can reach
                # n_groups × limit): keep a timestamp-tie-extended prefix
                # — the same tie rule as ``get_changes_page``, since
                # per-range cursors are strictly-greater-than and a cut
                # mid-tie would skip the tied remainder on resume — and
                # rebuild the cursors from the KEPT rows only (the walked
                # positions would skip every trimmed row)
                cut = limit
                boundary = merged[limit - 1][0]
                while cut < len(merged) and merged[cut][0] == boundary:
                    cut += 1
                merged = merged[:cut]
                all_drained = False
                new_positions = {
                    r.range_id: pos_for(r.range_id) for r in ranges}
                for ts, range_id, _row in merged:
                    new_positions[range_id] = max(
                        new_positions[range_id], ts)
            self._count_outcome("degraded" if degraded else "ok")
            return {
                "rows": [row for _, _, row in merged],
                "next_since": encode_cursor(pmap.version, new_positions),
                "drained": all_drained,
                "degraded_ranges": sorted(set(degraded)),
                "retry_after": max(retry_hints) if retry_hints else None,
            }
