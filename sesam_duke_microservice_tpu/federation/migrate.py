"""Live range migration — crash-consistent rebalancing (ISSUE 14).

Moving a digest range between serving groups with zero lost and zero
duplicated links, while everything else keeps serving.  The state
machine composes primitives that already shipped:

  1. **freeze** — the partition map marks the range frozen and bumps the
     epoch (persisted atomically, tmp + ``os.replace``); the source
     group's write fence rises, so a stale router can never land a
     write in the range's old owner (PR 8's epoch fencing, generalized).
     Writes to the range answer 429 + Retry-After at the router until
     cutover; reads and every other range are untouched.
  2. **snapshot** — the range's record rows (source store) and link rows
     (source durable link store) are captured with CRC32 checksums and
     shipped to the target through the same load-state shape as the
     PR 8 follower bootstrap (encoded link rows + watermark), applied
     through the target's idempotent ``assert_links``.  The snapshot
     deliberately does NOT drain the source's write-behind flusher: the
     capture is consistent as of the journal's applied watermark, and
     everything past the watermark rides step 3 — so a wedged flusher
     cannot wedge a migration.
  3. **journal-slice replay** — the source journal's batches past the
     snapshot watermark (PR 10's redo log, pinned against compaction for
     the walk) are filtered to the moving range and replayed at the
     target; idempotent re-application makes at-least-once delivery
     exactly-once in effect.
  4. **cutover** — one atomic partition-map persist flips the owner and
     thaws the range.  Before it the source owns the range; after it the
     target does; a crash can never expose an in-between state.
  5. **drain** — the source's now-stale copies are retired: record rows
     tombstone out of its retrieval index (values kept, so link-endpoint
     resolution for rows that STAY at the source still works) and the
     migration state file is removed.  Stale link rows at the source are
     harmless by construction — the router's ownership filter is the
     one-place dedup rule.

Crash consistency: the ONLY durable decision points are the state file,
the two map persists (freeze, cutover) and the target's own journaled
writes.  Resume re-derives everything else: interrupted before cutover →
redo freeze/snapshot/replay from scratch (all idempotent, and the frozen
range guarantees the source view is stable); interrupted after cutover →
finish the drain.  ``utils.faults`` kill sites (``pre_freeze``,
``post_snapshot``, ``mid_replay``, ``pre_cutover``, ``post_cutover``)
let the chaos differential SIGKILL a real process at each decision
boundary and prove the recovered federation bit-identical to an
unmigrated control (tests/test_federation_chaos.py).
"""

from __future__ import annotations

import json
import logging
import os
import time
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

from ..core.records import DELETED_PROPERTY_NAME, Record
from ..links.replica import decode_link, encode_link, rows_checksum
from ..store.records import serialize_record
from ..telemetry import tracing
from ..telemetry.env import env_int
from ..utils import faults
from .ranges import route_key

logger = logging.getLogger("federation-migrate")

MIGRATION_STATE_FILE = "migration.json"

# phase codes for the duke_fed_migration_phase gauge (0 = idle)
PHASE_CODES = {"idle": 0, "frozen": 1, "copied": 2, "cutover": 3,
               "drain": 4}

# journal-slice replay applies in bounded chunks so the mid_replay kill
# site sits between real durable steps, not after an all-or-nothing apply
_REPLAY_CHUNK_ROWS = 256

# retained phase timelines for GET /debug/migrations (ISSUE 16):
# bounded ring, in-memory only — restart starts an empty ring and the
# resumed migration writes a fresh timeline with resumed=True
DEFAULT_TIMELINE_RING = 64


def _timeline_ring() -> int:
    return max(1, env_int("DUKE_MIGRATION_RING", DEFAULT_TIMELINE_RING))


def _record_rows_checksum(rows: List[list]) -> int:
    """CRC32 chained over ``[rid, serialized]`` record rows (the record
    half of the snapshot integrity stamp; link rows use
    ``links.replica.rows_checksum``)."""
    import zlib

    crc = 0
    for rid, data in rows:
        crc = zlib.crc32(data.encode("utf-8", "surrogatepass"),
                         zlib.crc32(rid.encode("utf-8", "surrogatepass"),
                                    crc))
    return crc


class SnapshotIntegrityError(RuntimeError):
    """The shipped range snapshot failed its checksum — the load refuses
    (a half-applied corrupt snapshot would be silent row loss; the
    migration re-snapshots instead)."""


class RangeMigrator:
    """Drives (and resumes) one range migration over a ``Federation``."""

    def __init__(self, federation):
        self.fed = federation
        self.state_path = os.path.join(federation.data_folder,
                                       MIGRATION_STATE_FILE)
        # status snapshot for /stats and the phase gauge: whole-dict
        # replacement, read lock-free by scrapes
        self._status: Dict = {"active": False, "phase": "idle"}
        # outcome counters for duke_fed_migrations_total (single writer:
        # migrations are serialized by Federation._admin_lock)
        self.outcomes = {"completed": 0, "resumed": 0, "failed": 0}
        # phase-timeline ring for /debug/migrations: appended by the one
        # serialized migration driver, read lock-free by the plane
        # (list() copy — the _status whole-value stance)
        self.timelines: Deque[dict] = deque(maxlen=_timeline_ring())

    # -- phase timeline (ISSUE 16) --------------------------------------------

    def timelines_snapshot(self) -> List[dict]:
        """Newest-first copies of the retained migration timelines."""
        return [dict(t, phases=list(t["phases"]))
                for t in reversed(list(self.timelines))]

    @staticmethod
    def _log_phase(timeline: dict, phase: str, start_unix: float,
                   duration_ns: int, **attrs) -> None:
        """One completed phase: a retained timeline row plus (when the
        driver runs under a trace) a ``migrate.<phase>`` span laid out
        from its accumulated duration (the add_phase_spans precedent)."""
        row = {"phase": phase, "start_unix": round(start_unix, 6),
               "duration_ms": round(duration_ns / 1e6, 3)}
        row.update(attrs)
        timeline["phases"].append(row)
        end_ns = time.monotonic_ns()
        tracing.add_span(f"migrate.{phase}", end_ns - duration_ns, end_ns,
                         dict(attrs) or None)

    # -- status ---------------------------------------------------------------

    def status(self) -> dict:
        return dict(self._status)

    def phase_code(self) -> int:
        return PHASE_CODES.get(self._status.get("phase", "idle"), 0)

    def _set_phase(self, state: dict, phase: str) -> None:
        self._status = {
            "active": phase not in ("idle", "done"),
            "phase": phase if phase != "done" else "idle",
            "range": state.get("range"),
            "source": state.get("source"),
            "target": state.get("target"),
        }

    # -- state file -----------------------------------------------------------

    def _write_state(self, state: dict) -> None:
        from ..utils.atomicio import atomic_write_json

        atomic_write_json(self.state_path, state)

    def _load_state(self) -> Optional[dict]:
        if not os.path.exists(self.state_path):
            return None
        with open(self.state_path, "r", encoding="utf-8") as f:
            return json.load(f)

    def _clear_state(self) -> None:
        try:
            os.remove(self.state_path)
        except FileNotFoundError:
            pass

    # -- entry points ---------------------------------------------------------

    def migrate(self, range_id: str, target_group: int) -> dict:
        pmap = self.fed.map
        r = pmap.find(range_id)  # raises KeyError for an unknown range
        if not (0 <= target_group < len(self.fed.groups)):
            raise ValueError(f"unknown target group {target_group}")
        if r.group == target_group and not r.frozen:
            return {"range": range_id, "source": r.group,
                    "target": target_group, "moved_records": 0,
                    "moved_links": 0, "replayed_slices": 0,
                    "already_owned": True}
        state = {"range": range_id, "source": r.group,
                 "target": target_group}
        self._write_state(state)
        # kill site: intent durable, map untouched — restart resumes and
        # performs the whole migration
        faults.check_crash("pre_freeze")
        return self._drive(state)

    def resume(self) -> Optional[dict]:
        """Finish a migration a crash interrupted (called by the
        Federation constructor before serving starts)."""
        state = self._load_state()
        if state is None:
            return None
        self.outcomes["resumed"] += 1
        logger.warning(
            "resuming interrupted migration of range %s: group %d -> %d",
            state["range"], state["source"], state["target"])
        return self._drive(state, resumed=True)

    # -- the state machine ----------------------------------------------------

    def _drive(self, state: dict, resumed: bool = False) -> dict:
        range_id = state["range"]
        source, target = int(state["source"]), int(state["target"])
        pmap = self.fed.map
        # the retained timeline rides the ring from the start so a
        # migration that dies in flight still shows its completed phases
        timeline = {
            "range": range_id, "source": source, "target": target,
            "resumed": resumed, "started_unix": round(time.time(), 6),
            "trace_id": tracing.current_trace_id(),
            "outcome": "in-flight", "phases": [],
        }
        self.timelines.append(timeline)
        try:
            r = pmap.find(range_id)
            if r.group == target and not r.frozen:
                # crash landed after the cutover persisted: only the
                # drain is left
                logger.warning("range %s already cut over to group %d; "
                               "finishing drain", range_id, target)
                moved = {"records": 0, "links": 0, "slices": 0}
            else:
                # freeze (idempotent on resume) and fence the source so
                # stale routers bounce off the old owner
                t0, m0 = time.time(), time.monotonic_ns()
                epoch = pmap.freeze(range_id)
                self.fed.groups[source].fence(epoch)
                self._set_phase(state, "frozen")
                self._log_phase(timeline, "freeze", t0,
                                time.monotonic_ns() - m0, epoch=epoch)
                moved = self._copy_range(range_id, source, target, timeline)
                self._set_phase(state, "copied")
                # rebalanced ranges start hot (ISSUE 15): the copy may
                # have grown the target's corpus past a capacity
                # doubling, so warm its scorer ladder (AOT
                # deserialization + background miss-fill — the same
                # path a cold start uses) BEFORE the cutover points
                # traffic at it.  Best-effort with a bounded wait: a
                # cold target serves correctly, just slower.
                self._warm_target(target)
                # kill site: target complete and durable, map still
                # names the source — restart redoes the copy (idempotent)
                faults.check_crash("pre_cutover")
                t0, m0 = time.time(), time.monotonic_ns()
                epoch = pmap.assign(range_id, target)
                self.fed.groups[source].fence(epoch)
                self.fed.groups[target].fence(epoch)
                self._set_phase(state, "cutover")
                self._log_phase(timeline, "cutover", t0,
                                time.monotonic_ns() - m0, epoch=epoch)
                # kill site: ownership flipped, drain pending
                faults.check_crash("post_cutover")
            t0, m0 = time.time(), time.monotonic_ns()
            self._drain_source(range_id, source)
            self._set_phase(state, "drain")
            self._log_phase(timeline, "drain", t0,
                            time.monotonic_ns() - m0)
            self._clear_state()
            self.outcomes["completed"] += 1
            timeline["outcome"] = "completed"
            self._set_phase(state, "done")
            logger.info(
                "range %s migrated: group %d -> %d (%d record(s), %d "
                "link row(s), %d journal slice batch(es))", range_id,
                source, target, moved["records"], moved["links"],
                moved["slices"])
            return {"range": range_id, "source": source, "target": target,
                    "moved_records": moved["records"],
                    "moved_links": moved["links"],
                    "replayed_slices": moved["slices"]}
        except BaseException:
            # the state file stays: the migration is still in flight and
            # MUST complete (resume) — the frozen range keeps rejecting
            # writes until it does, which is the safe failure mode
            self.outcomes["failed"] += 1
            timeline["outcome"] = "failed"
            self._set_phase(state, "idle")
            raise

    def _warm_target(self, target: int) -> None:
        """Warm every target workload's scorer ladder before cutover
        (no-op for host backends and unchanged shape fingerprints).
        Bounded: waits for in-flight warm compiles up to
        ``DUKE_FED_WARM_TIMEOUT`` seconds so a slow compile ladder can
        delay — but never wedge — the cutover."""
        from ..telemetry.env import env_float

        deadline = time.monotonic() + env_float("DUKE_FED_WARM_TIMEOUT",
                                                120.0)
        caches = []
        for wl in self.fed.groups[target].workloads.values():
            cache = getattr(wl.index, "scorer_cache", None)
            if cache is not None:
                cache.prewarm_async(wl.config.is_record_linkage)
                caches.append(cache)
        for cache in caches:
            t = getattr(cache, "_warm_thread", None)
            if t is not None and t.is_alive():
                t.join(timeout=max(0.0, deadline - time.monotonic()))

    # -- copy: snapshot + ship + journal slice --------------------------------

    def _copy_range(self, range_id: str, source: int, target: int,
                    timeline: Optional[dict] = None) -> Dict[str, int]:
        r = self.fed.map.find(range_id)
        span = (r.lo, r.hi)
        totals = {"records": 0, "links": 0, "slices": 0}
        src_group = self.fed.groups[source]
        tgt_group = self.fed.groups[target]
        # per-workload snapshot/replay intervals interleave, so the
        # timeline rows carry ACCUMULATED durations (the add_phase_spans
        # stance) with row/byte attributes summed across workloads
        copy_start = time.time()
        snapshot_ns = replay_ns = 0
        mirrors = record_bytes = 0
        for wl_key in src_group.workloads:
            t = time.monotonic_ns()
            snapshot = self._snapshot_workload(src_group, wl_key, span)
            # kill site: snapshot captured, nothing shipped
            faults.check_crash("post_snapshot")
            journal = snapshot.pop("journal")
            try:
                self._load_snapshot(tgt_group, wl_key, snapshot)
                totals["records"] += len(snapshot["records"])
                totals["links"] += len(snapshot["links"])
                mirrors += len(snapshot["mirrors"])
                record_bytes += sum(len(data)
                                    for _rid, data in snapshot["records"])
                snapshot_ns += time.monotonic_ns() - t
                t = time.monotonic_ns()
                totals["slices"] += self._replay_slice(
                    journal, snapshot["watermark"], span, src_group,
                    tgt_group, wl_key)
                replay_ns += time.monotonic_ns() - t
            finally:
                if snapshot["pin"] is not None:
                    snapshot["pin"].__exit__(None, None, None)
            # the target's write-behind flush is drained per workload so
            # cutover never points readers at a store that is still
            # catching up on the shipped rows
            tgt_group.workloads[wl_key].link_database.drain()
        if timeline is not None:
            self._log_phase(timeline, "snapshot", copy_start, snapshot_ns,
                            records=totals["records"],
                            links=totals["links"], mirrors=mirrors,
                            record_bytes=record_bytes)
            self._log_phase(timeline, "replay",
                            copy_start + snapshot_ns / 1e9, replay_ns,
                            slices=totals["slices"])
        return totals

    def _snapshot_workload(self, src_group, wl_key: Tuple[str, str],
                           span: Tuple[int, int]) -> dict:
        """Checksummed range snapshot of one workload at the source.

        Captured under the source workload lock for a stable view.  The
        journal watermark is read BEFORE the link rows: a batch applied
        after the watermark read lands in the slice too — re-applying it
        is idempotent, while the reverse order could lose a batch that
        applied (and compacted) between the two reads."""
        lo, hi = span
        wl = src_group.workloads[wl_key]
        with wl.lock:
            journal = getattr(wl.link_database, "journal", None)
            pin = journal.retained() if journal is not None else None
            if pin is not None:
                pin.__enter__()
            try:
                watermark = (journal.applied_watermark()
                             if journal is not None else 0)
                records = []
                if wl.record_store is not None:
                    for rec in wl.record_store.all_records():
                        rid = rec.record_id
                        if rid is not None and lo <= route_key(rid) < hi:
                            records.append([rid, serialize_record(rec)])
                # the durable store view (NOT the drain barrier — see
                # class docstring): everything this misses is past the
                # watermark and rides the journal slice
                inner = getattr(wl.link_database, "inner",
                                wl.link_database)
                links = [list(encode_link(l)) for l in inner.get_all_links()
                         if lo <= route_key(l.id1) < hi]
                # resolution mirrors: moved links whose OTHER endpoint
                # lives outside the range need that endpoint resolvable
                # at the target for feed materialization — shipped as
                # index tombstones (resolvable, never retrievable, so
                # they can't seed target-local matches the map would
                # filter)
                mirrors = self._collect_mirrors(wl, links, span)
            except BaseException:
                if pin is not None:
                    pin.__exit__(None, None, None)
                raise
        return {
            "workload": wl_key,
            "span": span,
            "watermark": watermark,
            "records": records,
            "links": links,
            "mirrors": mirrors,
            "records_checksum": _record_rows_checksum(records),
            "links_checksum": rows_checksum(links),
            "mirrors_checksum": _record_rows_checksum(mirrors),
            "journal": journal,
            "pin": pin,
        }

    @staticmethod
    def _collect_mirrors(wl, link_rows, span: Tuple[int, int]) -> List[list]:
        """``[rid, serialized]`` for every out-of-range endpoint of the
        given encoded link rows that the source store can resolve."""
        lo, hi = span
        need = set()
        for row in link_rows:
            for endpoint in (row[0], row[1]):
                if not (lo <= route_key(endpoint) < hi):
                    need.add(endpoint)
        if not need or wl.record_store is None:
            return []
        out = []
        get_many = getattr(wl.record_store, "get_many", None)
        if get_many is not None:
            found = get_many(sorted(need))
        else:
            found = {rid: wl.record_store.get(rid) for rid in sorted(need)}
        for rid in sorted(need):
            rec = found.get(rid)
            if rec is not None:
                out.append([rid, serialize_record(rec)])
        return out

    def _load_snapshot(self, tgt_group, wl_key: Tuple[str, str],
                       snapshot: dict) -> None:
        """Apply a shipped range snapshot at the target (the PR 8
        bootstrap shape: verify checksums, then idempotent loads)."""
        if (_record_rows_checksum(snapshot["records"])
                != snapshot["records_checksum"]
                or rows_checksum(snapshot["links"])
                != snapshot["links_checksum"]
                or _record_rows_checksum(snapshot["mirrors"])
                != snapshot["mirrors_checksum"]):
            raise SnapshotIntegrityError(
                f"range snapshot for {wl_key} failed its checksum; "
                "refusing to load")
        wl = tgt_group.workloads[wl_key]
        with wl.lock:
            records = [Record(json.loads(data))
                       for _rid, data in snapshot["records"]]
            if records:
                if wl.record_store is not None:
                    wl.record_store.put_many(records)
                for rec in records:
                    wl.index.index(rec)
                wl.index.commit()
            self._load_mirrors_locked(wl, snapshot["mirrors"])
            links = [decode_link(row) for row in snapshot["links"]]
            if links:
                # timestamps ride verbatim; identical re-asserts are
                # no-ops (the idempotence contract recovery relies on)
                wl.link_database.assert_links(links)
                wl.link_database.commit()

    @staticmethod
    def _load_mirrors_locked(wl, mirrors: List[list]) -> int:
        """Fold resolution mirrors into the target: records the moved
        links reference but some other range owns, landed as index
        TOMBSTONES (resolvable by ``find_record_by_id`` — values intact
        — but excluded from retrieval, so no target-local match can form
        against a row the map routes elsewhere).  Rows already
        resolvable at the target (live residents, earlier mirrors) are
        left alone."""
        # dukecheck: holds wl.lock
        dead: List[Record] = []
        for rid, data in mirrors:
            if wl.index.find_record_by_id(rid) is not None:
                continue
            values = json.loads(data)
            values[DELETED_PROPERTY_NAME] = ["true"]
            dead.append(Record(values))
        if dead:
            if wl.record_store is not None:
                wl.record_store.put_many(dead)
            for rec in dead:
                wl.index.index(rec)
            wl.index.commit()
        return len(dead)

    def _replay_slice(self, journal, watermark: int,
                      span: Tuple[int, int], src_group, tgt_group,
                      wl_key: Tuple[str, str]) -> int:
        """Replay the source journal's post-watermark batches, filtered
        to the moving range, into the target — in bounded chunks, with
        the ``mid_replay`` kill site between chunk commits."""
        if journal is None:
            return 0
        lo, hi = span
        src_wl = src_group.workloads[wl_key]
        wl = tgt_group.workloads[wl_key]
        replayed = 0
        chunk: List = []

        def apply(rows) -> None:
            # slice rows can reference out-of-range endpoints the
            # snapshot never saw — mirror them like the snapshot path
            mirrors = self._collect_mirrors(src_wl, rows, span)
            with wl.lock:
                self._load_mirrors_locked(wl, mirrors)
                wl.link_database.assert_links(
                    [decode_link(r) for r in rows])
                wl.link_database.commit()

        for _seq, rows in journal.batches_after(watermark):
            for row in rows:
                if lo <= route_key(row[0]) < hi:
                    chunk.append(row)
            if len(chunk) >= _REPLAY_CHUNK_ROWS:
                apply(chunk)
                replayed += 1
                chunk = []
                # kill site: part of the slice durably applied at the
                # target, the rest not — restart re-copies idempotently
                faults.check_crash("mid_replay")
        if chunk:
            apply(chunk)
            replayed += 1
        # kill site (also for an empty slice, the frozen-range common
        # case): snapshot durably loaded at the target, replay done,
        # cutover not yet reached
        faults.check_crash("mid_replay")
        return replayed

    # -- drain: retire the source's stale copies ------------------------------

    def _drain_source(self, range_id: str, source: int) -> None:
        """Tombstone the moved records out of the source's retrieval
        index so no FUTURE source-local match can mint a link against a
        record the range's new owner now serves (such a link would be
        filtered from every feed — silent loss).  Values are preserved
        in the tombstone, so link rows that STAY at the source keep
        resolving their endpoints.  Idempotent (resume re-runs it).  The
        source's stale link rows stay put: the router's ownership filter
        already excludes them from every federated read."""
        r = self.fed.map.find(range_id)
        lo, hi = r.lo, r.hi
        src_group = self.fed.groups[source]
        for wl_key, wl in src_group.workloads.items():
            with wl.lock:
                if wl.record_store is None:
                    continue
                dead: List[Record] = []
                for rec in wl.record_store.all_records():
                    rid = rec.record_id
                    if (rid is None or not (lo <= route_key(rid) < hi)
                            or rec.is_deleted()):
                        continue
                    values = rec.to_dict()
                    values[DELETED_PROPERTY_NAME] = ["true"]
                    dead.append(Record(values))
                if not dead:
                    continue
                wl.record_store.put_many(dead)
                for rec in dead:
                    wl.index.index(rec)
                wl.index.commit()
                logger.info(
                    "drained %d migrated record(s) out of group %d's "
                    "%s/%s index", len(dead), source, *wl_key)
