"""The partition map: digest ranges over the record-id keyspace.

Corpus rows shard across serving groups by **digest range**: the routing
key is the first 8 bytes of SHA-256 over the *store record id*
(``[groupNo__]datasetId__entityId`` — the id ``service.datasource``
synthesizes and every link row carries as its endpoints).  The id — not
the content digest ``store.records.record_digest`` folds — because the
routing key must be stable under record updates: re-homing a record on
every content change would turn routine upserts into migrations.

A link row is owned by the range owning ``route_key(link.id1)`` (ids
are stored sorted, so id1 is deterministic for a pair).  Ownership
governs which group's feed EMITS the row in the federated merge — the
one-place dedup rule that makes post-migration stale copies at the old
owner harmless (router.py filters by it).

The map itself is a fixed set of contiguous ranges (created equal-width
at federation init; migration moves whole ranges between groups, it
never splits them), each carrying its owner group and a frozen flag.
Two monotonic stamps protect it:

  * ``version`` — bumped on every persisted change; the feed cursor
    embeds it so a client token can be recognized across map changes.
  * ``epoch`` — the write fence (PR 8's leadership epoch, generalized to
    ranges): freeze and cutover bump it, and every group checks the
    router's epoch against its own fence before accepting writes — a
    router holding a stale map can never write into a range's OLD owner
    (``StaleRouterEpoch`` tells it to refresh and re-route).

Persistence is a single JSON document written tmp + ``os.replace`` (the
corpus-snapshot discipline): a crash mid-persist leaves the previous
complete map, never a torn one — which is what makes the migration
cutover atomic.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
from typing import Dict, List, Optional

KEY_BITS = 64
KEY_SPACE = 1 << KEY_BITS


def route_key(record_id: str) -> int:
    """64-bit routing key for a store record id (first 8 bytes of its
    SHA-256, big-endian) — uniform over the keyspace, stable forever."""
    digest = hashlib.sha256(
        record_id.encode("utf-8", "surrogatepass")).digest()
    return int.from_bytes(digest[:8], "big")


class StaleRouterEpoch(RuntimeError):
    """A router presented an epoch below a group's fence: its map
    predates a freeze/cutover, so its routing for some range is no
    longer trustworthy.  The router refreshes its map and re-routes —
    it must never be allowed to write into a range's old owner."""

    def __init__(self, fence_epoch: int, presented: int):
        super().__init__(
            f"router epoch {presented} is stale (group fence at "
            f"{fence_epoch}); refresh the partition map and re-route")
        self.fence_epoch = fence_epoch
        self.presented = presented


class Range:
    """One contiguous slice [lo, hi) of the routing keyspace."""

    __slots__ = ("lo", "hi", "group", "frozen")

    def __init__(self, lo: int, hi: int, group: int, frozen: bool = False):
        self.lo = lo
        self.hi = hi
        self.group = group
        self.frozen = frozen

    @property
    def range_id(self) -> str:
        """Stable identity: the start key, zero-padded hex (ranges never
        split, so the start key names the range for its lifetime —
        cursors and migration state refer to it across owner changes)."""
        return f"{self.lo:016x}"

    def contains(self, key: int) -> bool:
        return self.lo <= key < self.hi

    def to_json(self) -> dict:
        return {"lo": f"{self.lo:016x}", "hi": f"{self.hi:016x}",
                "group": self.group, "frozen": self.frozen}

    @classmethod
    def from_json(cls, obj: dict) -> "Range":
        return cls(int(obj["lo"], 16), int(obj["hi"], 16),
                   int(obj["group"]), bool(obj.get("frozen", False)))


class PartitionMap:
    """Versioned, epoch-stamped digest-range → group assignment.

    Mutations (freeze / assign) persist atomically BEFORE they take
    effect in memory — a crash can lose an un-persisted intent (redone
    by migration resume) but can never leave memory ahead of disk, so a
    restart always reloads exactly what the last completed mutation
    published.  Reads snapshot under the lock and hand out copies; the
    lock is a leaf (nothing is ever acquired under it except the file
    write)."""

    def __init__(self, ranges: List[Range], version: int, epoch: int,
                 n_groups: int, path: Optional[str] = None):
        self._lock = threading.Lock()
        self._ranges = ranges  # guarded by: self._lock [writes]
        self.version = version  # guarded by: self._lock [writes]
        self.epoch = epoch  # guarded by: self._lock [writes]
        self.n_groups = n_groups
        self.path = path

    # -- construction / persistence ------------------------------------------

    @classmethod
    def create(cls, n_groups: int, n_ranges: int,
               path: Optional[str] = None) -> "PartitionMap":
        """Equal-width ranges, round-robin over groups (adjacent ranges
        land on different groups, so a hot contiguous key region spreads
        instead of camping on one group)."""
        n_ranges = max(n_groups, n_ranges)
        bounds = [KEY_SPACE * i // n_ranges for i in range(n_ranges)]
        bounds.append(KEY_SPACE)
        ranges = [
            Range(bounds[i], bounds[i + 1], i % n_groups)
            for i in range(n_ranges)
        ]
        pmap = cls(ranges, version=1, epoch=1, n_groups=n_groups, path=path)
        if path is not None:
            pmap._persist_locked()
        return pmap

    @classmethod
    def load(cls, path: str) -> "PartitionMap":
        with open(path, "r", encoding="utf-8") as f:
            doc = json.load(f)
        ranges = [Range.from_json(r) for r in doc["ranges"]]
        pmap = cls(ranges, version=int(doc["version"]),
                   epoch=int(doc["epoch"]), n_groups=int(doc["n_groups"]),
                   path=path)
        pmap._validate(ranges)
        return pmap

    @classmethod
    def load_or_create(cls, path: str, *, n_groups: int,
                       n_ranges: int) -> "PartitionMap":
        if os.path.exists(path):
            return cls.load(path)
        return cls.create(n_groups, n_ranges, path=path)

    def _persist_locked(self) -> None:
        # dukecheck: holds self._lock
        if self.path is None:
            return
        from ..utils.atomicio import atomic_write_json

        atomic_write_json(self.path, {
            "version": self.version,
            "epoch": self.epoch,
            "n_groups": self.n_groups,
            "ranges": [r.to_json() for r in self._ranges],
        })

    @staticmethod
    def _validate(ranges: List[Range]) -> None:
        """Full coverage, no overlap — a map that drops or doubles a key
        would silently lose or duplicate rows, the exact failure class
        this subsystem exists to exclude."""
        ordered = sorted(ranges, key=lambda r: r.lo)
        if not ordered or ordered[0].lo != 0 or ordered[-1].hi != KEY_SPACE:
            raise ValueError("partition map does not cover the keyspace")
        for prev, cur in zip(ordered, ordered[1:]):
            if prev.hi != cur.lo:
                raise ValueError(
                    f"partition map gap/overlap at {prev.hi:016x} vs "
                    f"{cur.lo:016x}")

    # -- reads ----------------------------------------------------------------

    def ranges(self) -> List[Range]:
        """Snapshot copy (callers iterate lock-free over it)."""
        with self._lock:
            return [Range(r.lo, r.hi, r.group, r.frozen)
                    for r in self._ranges]

    def owner(self, key: int) -> Range:
        with self._lock:
            for r in self._ranges:
                if r.contains(key):
                    return Range(r.lo, r.hi, r.group, r.frozen)
        raise AssertionError(f"key {key:#x} outside the keyspace")

    def find(self, range_id: str) -> Range:
        with self._lock:
            for r in self._ranges:
                if r.range_id == range_id:
                    return Range(r.lo, r.hi, r.group, r.frozen)
        raise KeyError(f"unknown range {range_id!r}")

    def group_ranges(self, group: int) -> List[Range]:
        with self._lock:
            return [Range(r.lo, r.hi, r.group, r.frozen)
                    for r in self._ranges if r.group == group]

    def range_ids(self) -> List[str]:
        with self._lock:
            return [r.range_id for r in self._ranges]

    def to_json(self) -> dict:
        with self._lock:
            return {
                "version": self.version,
                "epoch": self.epoch,
                "n_groups": self.n_groups,
                "ranges": [dict(r.to_json(), id=r.range_id)
                           for r in self._ranges],
            }

    # -- mutations (migration only) -------------------------------------------

    def freeze(self, range_id: str) -> int:
        """Mark the range frozen (writes 429 at the router) and bump
        version+epoch; persisted before returning.  Idempotent — a
        resumed migration re-freezing an already-frozen range changes
        nothing.  Returns the (possibly new) epoch."""
        with self._lock:
            r = self._find_locked(range_id)
            if not r.frozen:
                self._mutate_persist_locked(r, group=r.group, frozen=True)
            return self.epoch

    def assign(self, range_id: str, group: int) -> int:
        """Cut the range over to ``group`` and thaw it — THE atomic
        cutover point (single ``os.replace``): before this persists the
        source owns the range, after it the target does, and no state
        in between can be observed by a restart.  Returns the new
        epoch."""
        if not (0 <= group < self.n_groups):
            raise ValueError(f"unknown group {group}")
        with self._lock:
            r = self._find_locked(range_id)
            if r.group != group or r.frozen:
                self._mutate_persist_locked(r, group=group, frozen=False)
            return self.epoch

    def _mutate_persist_locked(self, r: Range, *, group: int,
                               frozen: bool) -> None:
        # dukecheck: holds self._lock
        """Apply one range mutation + version/epoch bump and persist —
        rolling the MEMORY back if the persist fails, so the live
        process never routes on state a restart would not reload (the
        class contract: memory is never ahead of disk).  A failed
        freeze leaves the range live instead of 429ing forever on an
        intent only this process ever knew about."""
        saved = (r.group, r.frozen, self.version, self.epoch)
        r.group = group
        r.frozen = frozen
        self.version += 1
        self.epoch += 1
        try:
            self._persist_locked()
        except BaseException:
            r.group, r.frozen, self.version, self.epoch = saved
            raise

    def _find_locked(self, range_id: str) -> Range:
        # dukecheck: holds self._lock
        for r in self._ranges:
            if r.range_id == range_id:
                return r
        raise KeyError(f"unknown range {range_id!r}")


def owned_spans(ranges: List[Range], group: int) -> List[tuple]:
    """The (lo, hi) spans of ``ranges`` owned by ``group`` — the
    filter the router hands a group's feed walk."""
    return [(r.lo, r.hi) for r in ranges if r.group == group]


def span_covers(spans: List[tuple], key: int) -> bool:
    return any(lo <= key < hi for lo, hi in spans)


def link_owner_key(id1: str) -> int:
    """Routing key that OWNS a link row: the key of its lexicographically
    lower endpoint (``Link`` stores ids sorted, so this is stable however
    the pair was asserted)."""
    return route_key(id1)


# map version embedded in cursors; bump if Dict / encoding changes shape
CURSOR_FORMAT = 1


def encode_cursor(version: int, positions: Dict[str, int]) -> str:
    """Opaque federated ``?since=`` token: base64url JSON of the map
    version + per-RANGE timestamp cursors.  Per range — not per group —
    so the cursor survives a range changing owners: after a cutover the
    new owner simply continues the range's stream past the same
    position (migration ships rows with timestamps verbatim)."""
    import base64

    doc = {"f": CURSOR_FORMAT, "v": version,
           "r": {k: int(v) for k, v in positions.items() if v}}
    raw = json.dumps(doc, separators=(",", ":"), sort_keys=True)
    return base64.urlsafe_b64encode(raw.encode("ascii")).decode("ascii")


class BadCursor(ValueError):
    pass


def decode_cursor(token: str) -> Dict[str, int]:
    """Per-range positions out of a federated token.  A bare integer is
    accepted as a legacy single-group cursor: it becomes every range's
    position (the pre-federation ``?since=<millis>`` client keeps
    working).  Unknown ranges in the token are ignored and missing
    ranges start at 0 — both directions of map drift are safe because
    feed semantics are strictly-greater-than per range."""
    import base64
    import binascii

    token = (token or "").strip()
    if not token:
        return {}
    try:
        return {"*": int(token)}  # legacy integer cursor: applies to all
    except ValueError:
        pass
    try:
        raw = base64.urlsafe_b64decode(token.encode("ascii"))
        doc = json.loads(raw.decode("ascii"))
        if doc.get("f") != CURSOR_FORMAT:
            raise BadCursor(f"unknown cursor format {doc.get('f')!r}")
        return {str(k): int(v) for k, v in dict(doc.get("r", {})).items()}
    except BadCursor:
        raise
    except (ValueError, binascii.Error, AttributeError, TypeError) as e:
        raise BadCursor(f"undecodable since token: {e}") from e
